"""JAX/Pallas GF(2^8) region kernels — the TPU erasure-code hot path.

This is the TPU-native replacement for the SIMD region kernels the
reference gets from jerasure/gf-complete/ISA-L (the hot loop behind
ECUtil.cc:488-514's encode_chunks and the benchmark's encode loop,
ceph_erasure_code_benchmark.cc:186-191).

Formulation
-----------
A GF(2^8) multiply by a *constant* c is GF(2)-linear on the bits of the
operand:  c*b = XOR_s bit_s(b) * (c * x^s).  Working on uint32 lanes that
each hold 4 independent bytes of a chunk:

    y32 ^= ((x32 >> s) & 0x01010101) * byte(c * x^s)      for s in 0..7

— the shifted mask extracts bit s of each byte into its low bit-position,
and the integer multiply broadcasts the constant byte into every byte slot
with no carries (mask bytes are 0/1, products fit a byte).  The whole
(m, k) matrix multiply unrolls at trace time into a static chain of
shift/and/mul/xor VPU ops: no gathers, no tables, no data-dependent control
flow — exactly what XLA/Mosaic want.  Coefficient 0 contributes nothing and
coefficient 1 is a single XOR, so XOR-heavy matrices (Vandermonde row 0,
cauchy_good's all-ones row) cost almost nothing — the same optimisation
jerasure's XOR-schedule (cauchy_good) path performs on CPUs.

The same trace builds three ways: a Pallas TPU kernel (data staged through
VMEM in blocks), the identical jnp graph for CPU/debug, and Pallas
interpret mode for CI coverage of the kernel itself.

Kernel realizations (the auto-tuner's candidate set, KERNELS):

- ``xla``    — the VPU bit-term chain above as a plain jnp graph;
- ``pallas`` — the same chain as a Pallas kernel (TPU, or interpret);
- ``mxu``    — GF(2) bit-matrix matmul on the systolic array
  (gf_matmul_mxu_graph; needs 8c <= 256 for exact bf16 accumulation);
- ``bitxor`` — XOR-scheduled GF(2) bitplanes (gf_bitxor_graph): unpack
  each input byte row into 8 LSB-positioned planes ONCE per launch,
  run the common-subexpression-eliminated XOR schedule built from the
  bit-matrix (ops/xor_schedule.py, the arXiv:2108.02692 technique),
  pack the output planes back — no integer multiplies, and shared
  partial sums are computed once across all output bit-rows.

``kernel_supports`` is the per-candidate viability predicate the
runtime auto-selection (ec/matrix_code.py) consults so unsupported
candidates are SKIPPED, never raised.
"""

from __future__ import annotations

import functools
import threading

import jax
import jax.numpy as jnp
import numpy as np

from . import gf256
from .xor_schedule import XorSchedule, build_schedule

_MASK = 0x01010101  # low bit of each byte lane in a uint32

#: kernel realizations the runtime auto-selection races (ec/matrix_code)
KERNELS = ("xla", "pallas", "mxu", "bitxor")


def kernel_supports(kernel: str, M: np.ndarray, shape=None, *,
                    interpret: bool = False) -> bool:
    """Whether candidate ``kernel`` can run matrix ``M`` (optionally at
    input ``shape``) in this process — the auto-selection viability
    guard: a False here means SKIP the candidate, never try-and-raise.

    - ``mxu`` needs 8c <= 256 (exact bf16 accumulation bound of
      gf_matmul_mxu_graph);
    - ``pallas`` needs the TPU backend (or an explicit interpret=True,
      the CI coverage mode — interpreter speed, honest label);
    - ``xla`` and ``bitxor`` lower as plain graphs everywhere.
    """
    if kernel not in KERNELS:
        return False
    M = np.asarray(M)
    if M.ndim != 2 or 0 in M.shape:
        return False
    if shape is not None and tuple(shape)[0] != M.shape[1]:
        return False
    if kernel == "mxu":
        return 8 * M.shape[1] <= 256
    if kernel == "pallas":
        return interpret or jax.default_backend() == "tpu"
    return True


def _terms(M: np.ndarray) -> tuple[tuple[tuple[int, int, int], ...], ...]:
    """Static per-output-row term lists: row i -> ((j, s, v), ...) with
    v = M[i,j] * x^s != 0; a (j, -1, 0) entry marks a plain XOR (coef 1)."""
    M = np.asarray(M, dtype=np.uint8)
    rows = []
    for i in range(M.shape[0]):
        row: list[tuple[int, int, int]] = []
        for j in range(M.shape[1]):
            c = int(M[i, j])
            if c == 0:
                continue
            if c == 1:
                row.append((j, -1, 0))
                continue
            for s in range(8):
                v = int(gf256.gf_mul(c, 1 << s))
                if v:
                    row.append((j, s, v))
        rows.append(tuple(row))
    return tuple(rows)


def _accumulate_row(x, terms):
    """XOR-accumulate one output row from input rows x (c, n) uint32."""
    acc = None
    for j, s, v in terms:
        xj = x[j : j + 1, :]
        t = xj if s < 0 else (
            (xj >> jnp.uint32(s)) & jnp.uint32(_MASK)) * jnp.uint32(v)
        acc = t if acc is None else acc ^ t
    if acc is None:
        return jnp.zeros_like(x[0:1, :])
    return acc


def _rows_op(x, terms_all):
    return jnp.concatenate([_accumulate_row(x, t) for t in terms_all], axis=0)


def _pallas_region_kernel(rows_op):
    """Pallas kernel body around any (c, n) -> (r, n) uint32 rows op —
    shared by the bit-term chain and the scheduled-XOR realization."""
    def kernel(x_ref, o_ref):
        o_ref[...] = rows_op(x_ref[...])

    return kernel


# ---------------------------------------------------------------------------
# bitxor: XOR-scheduled GF(2) bitplanes
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=128)
def _cached_schedule(key: bytes, shape: tuple[int, int]) -> XorSchedule:
    B = np.frombuffer(key, dtype=np.uint8).reshape(shape)
    return build_schedule(B)


def bitxor_schedule(M: np.ndarray) -> XorSchedule:
    """The CSE'd XOR schedule of a GF(2^8) matrix's bit-matrix
    expansion (gf256.bitmatrix), cached per matrix — schedule
    construction is CPU work done once, the launches replay it."""
    B = gf256.bitmatrix(np.asarray(M, dtype=np.uint8))
    return _cached_schedule(B.tobytes(), B.shape)


def _eval_schedule_nodes(sched: XorSchedule, nodes: list) -> list:
    """Run the intermediate op chain in place (inputs pre-filled)."""
    for dst, a, b in sched.ops:
        nodes[dst] = nodes[a] ^ nodes[b]
    return nodes


def _combine_terms(nodes: list, terms: tuple[int, ...]):
    acc = None
    for t in terms:
        acc = nodes[t] if acc is None else acc ^ nodes[t]
    return acc


def _bitxor_rows(x32, sched: XorSchedule):
    """(c, n4) uint32 lanes -> (r, n4) via the scheduled GF(2) planes.

    Input plane 8j+s is bit s of every byte of row j, kept in the low
    bit of its byte lane (one shift+mask per USED plane, amortized over
    all output rows — the existing bit-term chain re-extracts it per
    term); output byte row i packs its 8 scheduled planes back with
    shifts, no multiplies anywhere."""
    c = x32.shape[0]
    if sched.n_in != 8 * c:
        raise ValueError(f"schedule wants {sched.n_in // 8} rows, got {c}")
    nodes: list = [None] * (sched.n_in + len(sched.ops))
    for p in sched.used_inputs:
        xj = x32[p >> 3: (p >> 3) + 1, :]
        s = p & 7
        if s:
            xj = xj >> jnp.uint32(s)
        nodes[p] = xj & jnp.uint32(_MASK)
    _eval_schedule_nodes(sched, nodes)
    rows = []
    for i in range(len(sched.outputs) // 8):
        acc = None
        for t in range(8):
            q = _combine_terms(nodes, sched.outputs[8 * i + t])
            if q is None:
                continue
            if t:
                q = q << jnp.uint32(t)
            acc = q if acc is None else acc ^ q
        rows.append(acc if acc is not None
                    else jnp.zeros_like(x32[0:1, :]))
    return jnp.concatenate(rows, axis=0)


def gf_bitxor_graph(M: np.ndarray):
    """fn(data (c, L) uint8) -> (r, L) uint8 computing M @ data over
    GF(2^8) as the XOR-scheduled bitplane program (L % 4 == 0); the
    bitxor counterpart of gf_matmul_graph, byte-identical to the
    oracle, embeddable in jit/shard_map bodies."""
    sched = bitxor_schedule(M)
    r, c = np.asarray(M).shape

    def fn(data_u8):
        if data_u8.shape[0] != c:
            raise ValueError(f"expected {c} rows, got {data_u8.shape[0]}")
        n4 = data_u8.shape[-1] // 4
        x32 = jax.lax.bitcast_convert_type(
            data_u8.reshape(c, n4, 4), jnp.uint32)
        y32 = _bitxor_rows(x32, sched)
        return jax.lax.bitcast_convert_type(y32, jnp.uint8).reshape(r, n4 * 4)

    return fn


def gf_region_graph(M: np.ndarray, kernel: str = "xla"):
    """Byte-domain graph fn(data (c, L) u8) -> (r, L) u8 for a named
    kernel realization — the builder shard_map bodies and fused passes
    embed, so the sharded/fused paths ride the picked kernel unchanged.
    ``pallas``/``auto`` lower to the same XLA graph here (Pallas is a
    launch-level realization, not an embeddable sub-graph)."""
    if kernel == "bitxor":
        return gf_bitxor_graph(M)
    if kernel == "mxu":
        return gf_matmul_mxu_graph(M)
    return gf_matmul_graph(M)


def _sched_plane_rows(x32, sched: XorSchedule):
    """(n_in, n4) uint32 plane rows -> (n_out, n4): the schedule applied
    to rows that ARE the planes already (the bit-matrix code family's
    packet rows) — no bit extraction, no packing."""
    nodes: list = [None] * (sched.n_in + len(sched.ops))
    for p in sched.used_inputs:
        nodes[p] = x32[p: p + 1, :]
    _eval_schedule_nodes(sched, nodes)
    rows = []
    for terms in sched.outputs:
        acc = _combine_terms(nodes, terms)
        rows.append(acc if acc is not None
                    else jnp.zeros_like(x32[0:1, :]))
    return jnp.concatenate(rows, axis=0)


class ScheduledXor:
    """out(R, L) = B(R, C) @ rows(C, L) over GF(2), executed as the
    CSE'd XOR schedule on uint32 lanes — the shared bitxor executor for
    the GF(2) bit-matrix code family (ec/bitmatrix_code.py routes its
    jerasure-parity packet rows here on the jax backend) and any caller
    already holding plane rows.  Pallas on TPU (or interpret for CI),
    the identical jnp graph elsewhere; same 512-byte lane quantum and
    per-shape jit LRU as RegionMatmul."""

    # VMEM block: same lane quantum as RegionMatmul
    BLOCK = 8192

    def __init__(self, B: np.ndarray, *, interpret: bool = False):
        self.B = np.ascontiguousarray(B, dtype=np.uint8) & 1
        self.R, self.C = self.B.shape
        self.sched = _cached_schedule(self.B.tobytes(), self.B.shape)
        on_tpu = jax.default_backend() == "tpu"
        self._interpret = interpret and not on_tpu
        self._use_pallas = on_tpu or self._interpret
        self._shape_cache: dict[int, object] = {}
        self._cache_lock = threading.Lock()

    def _rows_op(self, n4: int):
        sched = self.sched
        if not self._use_pallas:
            return lambda x32: _sched_plane_rows(x32, sched)

        from jax.experimental import pallas as pl

        block = min(self.BLOCK, n4)
        grid = (n4 // block,)
        kernel = _pallas_region_kernel(
            lambda x32: _sched_plane_rows(x32, sched))
        R, C, interpret = self.R, self.C, self._interpret

        def run(x32):
            return pl.pallas_call(
                kernel,
                out_shape=jax.ShapeDtypeStruct((R, n4), jnp.uint32),
                grid=grid,
                in_specs=[pl.BlockSpec((C, block), lambda g: (0, g))],
                out_specs=pl.BlockSpec((R, block), lambda g: (0, g)),
                interpret=interpret,
            )(x32)

        return run

    def _compiled(self, n4: int):
        with self._cache_lock:
            fn = self._shape_cache.pop(n4, None)
            if fn is None:
                fn = jax.jit(self._rows_op(n4))
                if len(self._shape_cache) >= 16:
                    self._shape_cache.pop(next(iter(self._shape_cache)))
            self._shape_cache[n4] = fn
        return fn

    def _quantum(self, L: int) -> int:
        return 512 if L <= 4 * self.BLOCK else 4 * self.BLOCK

    def __call__(self, rows) -> jax.Array:
        """rows (C, L) uint8 -> (R, L) uint8 device array (no host
        sync — callers np.asarray when they want the bytes)."""
        if (isinstance(rows, np.ndarray) and rows.dtype == np.uint8
                and rows.ndim == 2 and rows.shape[0] == self.C
                and rows.shape[1] > 0):
            L = rows.shape[1]
            pad = (-L) % self._quantum(L)
            if pad:
                rows = np.pad(rows, ((0, 0), (0, pad)))
            x32 = np.ascontiguousarray(rows).view(np.uint32)
            y32 = self._compiled(x32.shape[-1])(x32)
            out = jax.lax.bitcast_convert_type(y32, jnp.uint8).reshape(
                self.R, L + pad)
            return out[:, :L] if pad else out
        rows = jnp.asarray(rows, dtype=jnp.uint8)
        if rows.ndim != 2 or rows.shape[0] != self.C:
            raise ValueError(f"expected ({self.C}, L) rows, got {rows.shape}")
        L = rows.shape[1]
        if L == 0:
            return jnp.zeros((self.R, 0), dtype=jnp.uint8)
        pad = (-L) % self._quantum(L)
        if pad:
            rows = jnp.pad(rows, ((0, 0), (0, pad)))
        n4 = (L + pad) // 4
        x32 = jax.lax.bitcast_convert_type(
            rows.reshape(self.C, n4, 4), jnp.uint32)
        y32 = self._compiled(n4)(x32)
        out = jax.lax.bitcast_convert_type(y32, jnp.uint8).reshape(
            self.R, L + pad)
        return out[:, :L] if pad else out


def gf_matmul_mxu_graph(M: np.ndarray):
    """MXU formulation: the GF(2^8) region matmul as a GF(2) bit-matrix
    matmul on the systolic array (the Cauchy-bitmatrix trick).

    parity_bits(8r, N) = B(8r, 8c) @ data_bits(8c, N)  mod 2

    with B the bit-matrix expansion (gf256.bitmatrix) and data_bits the
    LSB-first bit-planes.  Contraction depth 8c <= 256 (c <= 32) keeps
    bf16 accumulation exact (partial sums stay below 256, the bf16
    exact-integer bound).  Complements the VPU bit-term formulation
    (gf_matmul_graph); bench picks the faster one on real hardware.
    """
    M = np.asarray(M, dtype=np.uint8)
    r, c = M.shape
    if 8 * c > 256:
        raise ValueError("MXU path needs c <= 32 (exact bf16 accumulation)")
    B = jnp.asarray(gf256.bitmatrix(M), dtype=jnp.bfloat16)  # (8r, 8c)
    shifts = jnp.arange(8, dtype=jnp.uint8)

    def fn(data_u8):
        if data_u8.shape[0] != c:
            raise ValueError(f"expected {c} rows, got {data_u8.shape[0]}")
        n = data_u8.shape[-1]
        # unpack: (c, n) -> (c, 8, n) -> (8c, n) bit-planes, LSB-first
        planes = ((data_u8[:, None, :] >> shifts[None, :, None]) & 1)
        planes = planes.reshape(8 * c, n).astype(jnp.bfloat16)
        acc = jnp.dot(B, planes,
                      preferred_element_type=jnp.float32)  # (8r, n)
        bits = acc.astype(jnp.int32) & 1
        # pack: (8r, n) -> (r, 8, n) -> bytes
        bits = bits.reshape(r, 8, n)
        out = (bits << shifts[None, :, None].astype(jnp.int32)).sum(
            axis=1, dtype=jnp.int32)
        return out.astype(jnp.uint8)

    return fn


def gf_matmul_graph(M: np.ndarray):
    """Return a pure, jit-friendly fn(data (c, L) uint8) -> (r, L) uint8
    computing M @ data over GF(2^8) as a plain jnp graph (no pallas_call),
    for embedding inside larger jitted/shard_mapped programs (L % 4 == 0)."""
    terms_all = _terms(M)
    r, c = np.asarray(M).shape

    def fn(data_u8):
        if data_u8.shape[0] != c:
            raise ValueError(f"expected {c} rows, got {data_u8.shape[0]}")
        n4 = data_u8.shape[-1] // 4
        x32 = jax.lax.bitcast_convert_type(
            data_u8.reshape(c, n4, 4), jnp.uint32)
        y32 = _rows_op(x32, terms_all)
        return jax.lax.bitcast_convert_type(y32, jnp.uint8).reshape(r, n4 * 4)

    return fn


class RegionMatmul:
    """out(r, L) = M(r, c) @ data(c, L) over GF(2^8), JAX-compiled.

    ``data`` is uint8 with L a multiple of 4; stripes batch by widening L
    (columns are independent), which is how the stripe batcher feeds many
    stripes per launch (SURVEY.md §5 long-context analogue: a stripe batch
    is a (c, batch*chunk) tensor).
    """

    # VMEM block: BLOCK uint32 lanes per row (32 KiB/row at 8192)
    BLOCK = 8192

    def __init__(self, M: np.ndarray, *, interpret: bool = False,
                 kernel: str = "auto"):
        """``interpret=True`` forces the Pallas kernel in interpret mode
        (CI coverage of the kernel body off-TPU); otherwise the Pallas
        path runs compiled on TPU and the identical jnp graph elsewhere.

        ``kernel`` picks the realization (KERNELS): ``auto`` keeps the
        legacy per-platform choice (pallas on TPU, the xla graph
        elsewhere); an explicit name pins it — ``pallas`` requires TPU
        or interpret, ``mxu`` requires 8c <= 256 (both raise ValueError
        here; runtime auto-selection guards with kernel_supports first),
        ``bitxor`` runs the scheduled-bitplane program (Pallas-lowered
        on TPU/interpret, fused XLA graph elsewhere)."""
        self.M = np.ascontiguousarray(M, dtype=np.uint8)
        self.r, self.c = self.M.shape
        if kernel not in ("auto",) + KERNELS:
            raise ValueError(f"unknown kernel {kernel!r}")
        self.kernel = kernel
        on_tpu = jax.default_backend() == "tpu"
        self._interpret = interpret and not on_tpu
        pallas_ok = on_tpu or self._interpret
        if kernel == "pallas" and not pallas_ok:
            raise ValueError(
                "pallas kernel needs the TPU backend or interpret=True")
        if kernel == "mxu" and 8 * self.c > 256:
            raise ValueError("MXU path needs c <= 32 "
                             "(exact bf16 accumulation)")
        # xla pins the plain graph even on TPU; mxu is a dot graph, not
        # a Pallas body; bitxor Pallas-lowers wherever pallas runs
        self._use_pallas = pallas_ok and kernel in ("auto", "pallas",
                                                    "bitxor")
        self._terms = (_terms(self.M)
                       if kernel in ("auto", "xla", "pallas") else None)
        self._sched = bitxor_schedule(self.M) if kernel == "bitxor" \
            else None
        self._shape_cache: dict[tuple, object] = {}
        # one matmul op serves many threads (OSD shard workers, batcher
        # flushers); the LRU touch and eviction must not interleave
        self._cache_lock = threading.Lock()

    def _compiled(self, key: tuple):
        # true LRU: a hot shape must not be evicted just because it was
        # compiled first (a hit re-inserts behind newer one-shots).
        # Building under the lock is fine — jax.jit wrapping is lazy;
        # the expensive trace happens at first call, outside the lock.
        with self._cache_lock:
            fn = self._shape_cache.pop(key, None)
            if fn is None:
                kind, n4 = key
                fn = (self._build_u32(n4) if kind == "u32"
                      else self._build_u8(n4, donate=kind == "u8d"))
                if len(self._shape_cache) >= 16:
                    self._shape_cache.pop(next(iter(self._shape_cache)))
            self._shape_cache[key] = fn
        return fn

    def _lanes_op(self, n4: int):
        """The core (c, n4) -> (r, n4) uint32 lane computation: a Pallas
        grid over VMEM blocks on TPU (or interpret mode), the identical
        jnp graph elsewhere.  Keeping the callable u32-in/u32-out means no
        device-side byte<->lane bitcasts: feeding XLA the pre-packed lanes
        avoids the layout the compiler otherwise invents for the bitcast
        (minor-most rows axis, T(8,128)-padded 16x — enough to OOM HBM on
        multi-GiB batches)."""
        core = self._rows_core()
        if not self._use_pallas:
            return core

        from jax.experimental import pallas as pl

        block = min(self.BLOCK, n4)
        grid = (n4 // block,)
        kernel = _pallas_region_kernel(core)
        r, c, interpret = self.r, self.c, self._interpret

        def run(x32):
            return pl.pallas_call(
                kernel,
                out_shape=jax.ShapeDtypeStruct((r, n4), jnp.uint32),
                grid=grid,
                in_specs=[pl.BlockSpec((c, block), lambda g: (0, g))],
                out_specs=pl.BlockSpec((r, block), lambda g: (0, g)),
                interpret=interpret,
            )(x32)

        return run

    def _rows_core(self):
        """The raw (c, n) -> (r, n) uint32 lanes computation of the
        selected realization — what the Pallas kernel body, the jnp
        graph, and interpret mode all share."""
        if self.kernel == "mxu":
            mxu = gf_matmul_mxu_graph(self.M)
            r, c = self.r, self.c

            def core(x32):
                u8 = jax.lax.bitcast_convert_type(x32, jnp.uint8)
                y8 = mxu(u8.reshape(c, 4 * x32.shape[-1]))
                return jax.lax.bitcast_convert_type(
                    y8.reshape(r, x32.shape[-1], 4), jnp.uint32)

            return core
        if self.kernel == "bitxor":
            sched = self._sched
            return lambda x32: _bitxor_rows(x32, sched)
        terms_all = self._terms
        return lambda x32: _rows_op(x32, terms_all)

    def _build_u32(self, n4: int):
        return jax.jit(self._lanes_op(n4))

    def _build_u8(self, n4: int, donate: bool = False):
        # donate=True builds the DONATED variant (jax donate_argnums,
        # SNIPPETS [1] idiom): XLA may alias the input buffer for the
        # output instead of allocating, so a flush's folded scratch
        # tensor costs no extra HBM and no copy.  Callers must own the
        # input exclusively — donation deletes it (__call__ donate flag)
        dargs = (0,) if donate else ()
        if not self._use_pallas:
            # identical math as a plain jnp graph — shared with the
            # gf_region_graph builders so the lane-packing logic lives
            # once per realization
            return jax.jit(gf_region_graph(self.M, self.kernel),
                           donate_argnums=dargs)
        run, r, c = self._lanes_op(n4), self.r, self.c

        def fn(data_u8):
            x32 = jax.lax.bitcast_convert_type(
                data_u8.reshape(c, n4, 4), jnp.uint32)
            y32 = run(x32)
            return jax.lax.bitcast_convert_type(y32, jnp.uint8).reshape(
                r, n4 * 4)

        return jax.jit(fn, donate_argnums=dargs)

    def _quantum(self, L: int) -> int:
        # uint32 tiling wants multiples of 128 lanes (512 bytes); beyond one
        # block, round up to a whole block so the grid divides evenly.
        return 512 if L <= 4 * self.BLOCK else 4 * self.BLOCK

    def encode_lanes(self, x32) -> jax.Array:
        """Raw lane-domain entry: x32 (c, n4) uint32 -> (r, n4) uint32.
        n4 must already be a multiple of 128 (whole tiles); the byte view
        of a chunk IS its lane view (little-endian u32 of 4 consecutive
        bytes), so callers holding host buffers use numpy ``.view`` —
        zero-copy — rather than paying a device-side bitcast."""
        n4 = x32.shape[-1]
        if n4 % 128 or (n4 > self.BLOCK and n4 % self.BLOCK):
            # the Pallas grid is (n4 // block,) whole blocks — a ragged
            # tail would silently stay unwritten in the output
            raise ValueError(
                f"encode_lanes wants n4 % 128 == 0 and, beyond one block, "
                f"n4 % {self.BLOCK} == 0; got {n4}")
        return self._compiled(("u32", n4))(x32)

    def __call__(self, data, *, donate: bool = False) -> jax.Array:
        """``donate=True`` runs the donated-input variant: the caller
        asserts exclusive ownership of ``data`` (a flush's folded
        scratch buffer, never an arena/cache-held array) and XLA may
        alias it for the output — the buffer is DELETED afterwards."""
        if (isinstance(data, np.ndarray) and data.dtype == np.uint8
                and data.ndim == 2 and data.shape[0] == self.c
                and data.shape[1] > 0):
            # host fast path: pad host-side, view bytes as u32 lanes
            # (zero-copy), run the lane kernel, un-view on device
            L = data.shape[1]
            pad = (-L) % self._quantum(L)
            if pad:
                data = np.pad(data, ((0, 0), (0, pad)))
            x32 = np.ascontiguousarray(data).view(np.uint32)
            y32 = self.encode_lanes(x32)
            out = jax.lax.bitcast_convert_type(y32, jnp.uint8).reshape(
                self.r, L + pad)
            return out[:, :L] if pad else out
        data = jnp.asarray(data, dtype=jnp.uint8)
        if data.ndim != 2 or data.shape[0] != self.c:
            raise ValueError(f"expected ({self.c}, L) data, got {data.shape}")
        L = data.shape[1]
        if L == 0:
            return jnp.zeros((self.r, 0), dtype=jnp.uint8)
        pad = (-L) % self._quantum(L)
        if pad:
            data = jnp.pad(data, ((0, 0), (0, pad)))
        kind = "u8d" if donate else "u8"
        out = self._compiled((kind, (L + pad) // 4))(data)
        return out[:, :L] if pad else out
