"""ctypes bindings for the native GF(2^8) library (native/libcephtpu.so).

Builds the shared object on first use via `make` if it is missing or stale —
the moral equivalent of the reference's dlopen plugin path
(ErasureCodePlugin.cc:138 loading libec_<name>.so), with the version check
replaced by an mtime staleness check.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "build", "libcephtpu.so")
_LOCK = threading.Lock()


class NativeUnavailable(RuntimeError):
    pass


def _stale() -> bool:
    if not os.path.exists(_SO_PATH):
        return True
    so_m = os.path.getmtime(_SO_PATH)
    for f in os.listdir(_NATIVE_DIR):
        if f.endswith((".cc", ".h")) and os.path.getmtime(
            os.path.join(_NATIVE_DIR, f)
        ) > so_m:
            return True
    return False


_LIB_RESULT: ctypes.CDLL | Exception | None = None


def lib() -> ctypes.CDLL:
    """Load (building if needed) the native library; caches failure too so a
    broken toolchain doesn't re-run `make` on every probe."""
    global _LIB_RESULT
    if _LIB_RESULT is not None:
        if isinstance(_LIB_RESULT, Exception):
            raise _LIB_RESULT
        return _LIB_RESULT
    try:
        _LIB_RESULT = _load()
    except Exception as e:  # noqa: BLE001 - cache any load/build failure
        _LIB_RESULT = NativeUnavailable(str(e))
        raise _LIB_RESULT
    return _LIB_RESULT


def _load() -> ctypes.CDLL:
    with _LOCK:
        if _stale():
            try:
                subprocess.run(
                    ["make", "-s"], cwd=_NATIVE_DIR, check=True,
                    capture_output=True, text=True,
                )
            except (subprocess.CalledProcessError, FileNotFoundError) as e:
                detail = getattr(e, "stderr", "") or str(e)
                raise NativeUnavailable(f"native build failed: {detail}")
        L = ctypes.CDLL(_SO_PATH)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        L.ct_init.restype = ctypes.c_int
        L.ct_gf_mul.restype = ctypes.c_uint8
        L.ct_gf_mul.argtypes = [ctypes.c_uint8, ctypes.c_uint8]
        L.ct_gf_inv.restype = ctypes.c_uint8
        L.ct_gf_inv.argtypes = [ctypes.c_uint8]
        for name in ("ct_vandermonde_matrix", "ct_cauchy_matrix",
                     "ct_cauchy_good_matrix"):
            fn = getattr(L, name)
            fn.restype = ctypes.c_int
            fn.argtypes = [ctypes.c_int, ctypes.c_int, u8p]
        L.ct_mat_inv.restype = ctypes.c_int
        L.ct_mat_inv.argtypes = [ctypes.c_int, u8p, u8p]
        L.ct_decode_matrix.restype = ctypes.c_int
        L.ct_decode_matrix.argtypes = [
            u8p, ctypes.c_int, ctypes.c_int, ctypes.POINTER(ctypes.c_int), u8p]
        L.ct_region_mac.restype = None
        L.ct_region_mac.argtypes = [u8p, u8p, ctypes.c_size_t, ctypes.c_uint8]
        L.ct_encode.restype = None
        L.ct_encode.argtypes = [u8p, ctypes.c_int, ctypes.c_int, u8p, u8p,
                                ctypes.c_size_t]
        L.ct_encode_ptrs.restype = None
        L.ct_encode_ptrs.argtypes = [
            u8p, ctypes.c_int, ctypes.c_int, ctypes.POINTER(u8p),
            ctypes.POINTER(u8p), ctypes.c_size_t]
        L.ct_lincomb_rows.restype = None
        L.ct_lincomb_rows.argtypes = [
            ctypes.POINTER(u8p), ctypes.POINTER(u8p),
            ctypes.POINTER(u8p), ctypes.c_uint8, ctypes.c_uint8,
            ctypes.c_int, ctypes.c_size_t]
        L.ct_crc32c.restype = ctypes.c_uint32
        L.ct_crc32c.argtypes = [ctypes.c_uint32, u8p, ctypes.c_size_t]
        L.ct_xxhash32.restype = ctypes.c_uint32
        L.ct_xxhash32.argtypes = [ctypes.c_uint32, u8p, ctypes.c_size_t]
        L.ct_xxhash64.restype = ctypes.c_uint64
        L.ct_xxhash64.argtypes = [ctypes.c_uint64, u8p, ctypes.c_size_t]
        L.chacha20_xor.restype = None
        L.chacha20_xor.argtypes = [u8p, u8p, ctypes.c_uint32, u8p,
                                   ctypes.c_uint64]
        L.ct_init()
        return L


def _u8p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def available() -> bool:
    try:
        lib()
        return True
    except NativeUnavailable:
        return False


def vandermonde_matrix(k: int, m: int) -> np.ndarray:
    out = np.empty((m, k), dtype=np.uint8)
    if lib().ct_vandermonde_matrix(k, m, _u8p(out)) != 0:
        raise ValueError(f"bad (k={k}, m={m})")
    return out


def cauchy_matrix(k: int, m: int) -> np.ndarray:
    out = np.empty((m, k), dtype=np.uint8)
    if lib().ct_cauchy_matrix(k, m, _u8p(out)) != 0:
        raise ValueError(f"bad (k={k}, m={m})")
    return out


def cauchy_good_matrix(k: int, m: int) -> np.ndarray:
    out = np.empty((m, k), dtype=np.uint8)
    if lib().ct_cauchy_good_matrix(k, m, _u8p(out)) != 0:
        raise ValueError(f"bad (k={k}, m={m})")
    return out


def mat_inv(A: np.ndarray) -> np.ndarray:
    A = np.ascontiguousarray(A, dtype=np.uint8)
    n = A.shape[0]
    out = np.empty((n, n), dtype=np.uint8)
    if lib().ct_mat_inv(n, _u8p(A), _u8p(out)) != 0:
        raise np.linalg.LinAlgError("singular")
    return out


def decode_matrix(C: np.ndarray, k: int, available_ids: list[int]) -> np.ndarray:
    C = np.ascontiguousarray(C, dtype=np.uint8)
    m = C.shape[0]
    if not (0 < k <= 256 and k + m <= 256):
        raise ValueError(f"bad (k={k}, m={m})")
    if len(available_ids) < k:
        raise ValueError(f"need >= {k} available chunk ids")
    if any(not 0 <= i < k + m for i in available_ids[:k]):
        raise ValueError(f"chunk id out of range in {available_ids[:k]}")
    avail = (ctypes.c_int * k)(*available_ids[:k])
    out = np.empty((k, k), dtype=np.uint8)
    if lib().ct_decode_matrix(_u8p(C), k, m, avail, _u8p(out)) != 0:
        raise np.linalg.LinAlgError("singular decode set")
    return out


def encode_region(G: np.ndarray, data: np.ndarray) -> np.ndarray:
    """parity (m,L) = G (m,k) @ data (k,L), native kernels (AVX2 if present)."""
    G = np.ascontiguousarray(G, dtype=np.uint8)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    m, k = G.shape
    assert data.shape[0] == k
    L = data.shape[1]
    parity = np.empty((m, L), dtype=np.uint8)
    lib().ct_encode(_u8p(G), m, k, _u8p(data), _u8p(parity), L)
    return parity


def region_mac(dst: np.ndarray, src: np.ndarray, coef: int) -> None:
    """dst ^= coef * src over GF(2^8), in place. Both must be uint8."""
    if dst.dtype != np.uint8 or src.dtype != np.uint8:
        raise TypeError("region_mac requires uint8 arrays")
    if not (dst.flags.c_contiguous and src.flags.c_contiguous):
        raise ValueError("region_mac requires contiguous arrays")
    if src.size < dst.size:
        raise ValueError(f"src ({src.size}) shorter than dst ({dst.size})")
    lib().ct_region_mac(_u8p(dst), _u8p(src), dst.size, coef)


def encode_region_ptrs(G: np.ndarray, rows: list[np.ndarray],
                       L: int) -> np.ndarray:
    """Like encode_region but gathering input rows by pointer — the shape of
    the decode path where survivor chunks live in separate buffers (the
    reference marshals shard_id_map -> char*[] the same way,
    ErasureCodeJerasure.cc:121-163)."""
    G = np.ascontiguousarray(G, dtype=np.uint8)
    m, k = G.shape
    if len(rows) < k:
        raise ValueError(f"need {k} input rows")
    for r in rows[:k]:
        if r.dtype != np.uint8 or not r.flags.c_contiguous or r.size < L:
            raise ValueError("rows must be contiguous uint8 of >= L bytes")
    out = np.empty((m, L), dtype=np.uint8)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    in_ptrs = (u8p * k)(*[_u8p(r) for r in rows[:k]])
    out_ptrs = (u8p * m)(*[_u8p(out[i]) for i in range(m)])
    lib().ct_encode_ptrs(_u8p(G), m, k, in_ptrs, out_ptrs, L)
    return out


def lincomb_rows_ptrs(dst_ptrs: np.ndarray, a_ptrs: np.ndarray,
                      b_ptrs: np.ndarray | None,
                      ca: int, cb: int, L: int) -> None:
    """Like lincomb_rows, but the rows are given as uint64 ADDRESS
    arrays (base + offset computed with numpy) — one ctypes cast per
    call instead of one per row, which is what makes thousands of tiny
    coupling rows per encode affordable."""
    n = len(dst_ptrs)
    if n == 0:
        return
    u8pp = ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))
    d = np.ascontiguousarray(dst_ptrs, dtype=np.uint64)
    a = np.ascontiguousarray(a_ptrs, dtype=np.uint64)
    bp = None
    if b_ptrs is not None:
        b = np.ascontiguousarray(b_ptrs, dtype=np.uint64)
        bp = b.ctypes.data_as(u8pp)
    lib().ct_lincomb_rows(d.ctypes.data_as(u8pp),
                          a.ctypes.data_as(u8pp), bp, ca, cb, n, L)


def crc32c(data: bytes | np.ndarray, crc: int = 0) -> int:
    """Standard CRC-32C (init/xorout 0xFFFFFFFF folded in; chainable by
    passing a previous result as ``crc``) — the checksum family Ceph's
    Checksummer dispatches (src/common/Checksummer.h:13)."""
    a = np.frombuffer(data, dtype=np.uint8) if isinstance(
        data, (bytes, bytearray, memoryview)) else np.ascontiguousarray(
            data, dtype=np.uint8)
    return int(lib().ct_crc32c(ctypes.c_uint32(crc).value, _u8p(a), a.size))


def crc32c_blocks(data, block: int, crc: int = 0) -> list[int]:
    """Per-block CRC-32C over one contiguous buffer (the BlueStore
    per-page csum sweep): ONE pointer marshal for the whole buffer
    instead of one ctypes round-trip per 4K page — the store ingest
    path calls this hundreds of times per MiB, where the per-call
    overhead dwarfs the checksum itself.  The tail block may be
    short."""
    a = np.frombuffer(data, dtype=np.uint8) if isinstance(
        data, (bytes, bytearray, memoryview)) else np.ascontiguousarray(
            data, dtype=np.uint8)
    fn = lib().ct_crc32c
    base = a.ctypes.data
    seed = ctypes.c_uint32(crc).value
    out = []
    u8 = ctypes.POINTER(ctypes.c_uint8)
    for off in range(0, a.size, block):
        n = min(block, a.size - off)
        out.append(int(fn(seed, ctypes.cast(base + off, u8), n)))
    return out


def xxhash32(data: bytes | np.ndarray, seed: int = 0) -> int:
    """XXH32 (public xxHash spec) — the non-crc member of the reference
    Checksummer dispatch (src/common/Checksummer.h:13)."""
    a = np.frombuffer(data, dtype=np.uint8) if isinstance(
        data, (bytes, bytearray, memoryview)) else np.ascontiguousarray(
            data, dtype=np.uint8)
    return int(lib().ct_xxhash32(ctypes.c_uint32(seed).value, _u8p(a),
                                 a.size))


def xxhash64(data: bytes | np.ndarray, seed: int = 0) -> int:
    """XXH64 (public xxHash spec)."""
    a = np.frombuffer(data, dtype=np.uint8) if isinstance(
        data, (bytes, bytearray, memoryview)) else np.ascontiguousarray(
            data, dtype=np.uint8)
    return int(lib().ct_xxhash64(ctypes.c_uint64(seed).value, _u8p(a),
                                 a.size))


CSUM_FUNCS = {"crc32c": crc32c, "xxhash32": xxhash32, "xxhash64": xxhash64}


def checksummer(kind: str):
    """Checksummer dispatch (src/common/Checksummer.h:13 template
    switch): pick a checksum family by name."""
    try:
        return CSUM_FUNCS[kind]
    except KeyError:
        raise ValueError(f"unknown checksum {kind!r}") from None


def chacha20_xor(key: bytes, nonce: bytes, data: bytes,
                 counter: int = 0) -> bytes:
    """ChaCha20 keystream XOR (RFC 8439): encrypt == decrypt.  The
    messenger's secure-mode cipher (the crypto_onwire role; the
    reference uses AES-GCM via openssl, this library is dependency-free
    so the wire cipher is ChaCha20 + the messenger's HMAC tag)."""
    if len(key) != 32 or len(nonce) != 12:
        raise ValueError("chacha20 wants a 32-byte key, 12-byte nonce")
    buf = np.frombuffer(bytes(data), dtype=np.uint8).copy()
    k = np.frombuffer(key, dtype=np.uint8)
    n = np.frombuffer(nonce, dtype=np.uint8)
    if buf.size:
        lib().chacha20_xor(_u8p(k), _u8p(n), counter, _u8p(buf), buf.size)
    return buf.tobytes()
