"""XOR schedules for GF(2) bit-matrices — the bitxor kernel's front end.

A GF(2) bit-matrix applied to plane rows is pure XOR:

    out[r] = XOR over c of planes[c]  where B[r, c] == 1

Evaluated row by row that costs sum(popcount(row)) - rows XORs.  The
optimization literature for XOR-based erasure codes ("Accelerating
XOR-based Erasure Coding using Program Optimization Techniques",
arXiv:2108.02692; "Fast XOR-based Erasure Coding based on Polynomial
Ring Transforms", arXiv:1701.07731) gets its wins from *scheduling*
those XORs: partial sums shared between output rows are computed once
and memoized — classic common-subexpression elimination over the XOR
chain, jerasure's "smart scheduling" generalized across rows.

This module builds such schedules CPU-side at matrix-construction time
(they are then baked into traced XLA/Pallas programs by
ops/ec_kernels.py) with the greedy pairwise-matching CSE: repeatedly
find the operand PAIR co-occurring in the most rows, hoist it into a
fresh intermediate node, substitute, stop when no pair repeats.  The
pair counts update incrementally and the max extraction rides a lazy
heap, so construction stays near-linear in the schedule it emits.

Everything here is numpy/stdlib only — the schedule is shared by the
device lowerings AND the numpy oracle evaluator the property tests
compare against, so a scheduler bug cannot hide behind a lowering bug.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass

import numpy as np

#: above this many bit-matrix cells the CSE pass is skipped (the initial
#: pair count is O(rows * row_weight^2) Python work — a 512x512 decode
#: bit-matrix would pay seconds for a schedule the kernel LRU may churn
#: out anyway); the raw per-row schedule is still correct, just unshared
CSE_CELL_LIMIT = 1 << 16


@dataclass(frozen=True)
class XorSchedule:
    """A straight-line XOR program over input planes 0..n_in-1.

    ``ops`` is the intermediate chain: ``(dst, a, b)`` computes node
    ``dst = a ^ b`` (dst ids start at ``n_in`` and ascend, operands
    always precede their op).  ``outputs[r]`` lists the node ids whose
    XOR is output row r — an empty tuple is an all-zero row.
    ``used_inputs`` are the input plane ids the program actually reads
    (lowering skips unpacking the rest)."""

    n_in: int
    ops: tuple[tuple[int, int, int], ...]
    outputs: tuple[tuple[int, ...], ...]
    used_inputs: tuple[int, ...]

    def xor_count(self) -> int:
        """Total XORs the schedule performs (intermediates + per-row
        combines) — the quantity CSE minimizes."""
        return len(self.ops) + sum(max(0, len(o) - 1)
                                   for o in self.outputs)

    def naive_xor_count(self) -> int:
        """XOR cost of evaluating each row independently (popcount-1
        per nonempty row) — the pre-CSE baseline."""
        naive = 0
        stack = {i: frozenset([i]) for i in range(self.n_in)}
        for dst, a, b in self.ops:
            stack[dst] = stack[a] ^ stack[b]
        for out in self.outputs:
            leaves: frozenset = frozenset()
            for t in out:
                leaves = leaves ^ stack[t]
            naive += max(0, len(leaves) - 1)
        return naive


def build_schedule(B: np.ndarray, *, cse: bool = True) -> XorSchedule:
    """Greedy pairwise-CSE XOR schedule for bit-matrix ``B`` (R, C).

    Deterministic: ties between equally-frequent pairs break toward the
    lexicographically smallest (a, b), so the same matrix always yields
    the same schedule (the CI pick-stability contract rides on this).
    """
    B = np.ascontiguousarray(B, dtype=np.uint8) & 1
    if B.ndim != 2:
        raise ValueError(f"bit-matrix must be 2-D, got {B.shape}")
    R, C = B.shape
    rows: list[set[int]] = [set(np.nonzero(B[r])[0].tolist())
                            for r in range(R)]
    ops: list[tuple[int, int, int]] = []
    if cse and R * C <= CSE_CELL_LIMIT and R > 1:
        ops = _cse_pass(rows, C)
    outputs = tuple(tuple(sorted(row)) for row in rows)
    used: set[int] = set()
    for _dst, a, b in ops:
        used.add(a)
        used.add(b)
    for out in outputs:
        used.update(out)
    used_inputs = tuple(sorted(u for u in used if u < C))
    return XorSchedule(n_in=C, ops=tuple(ops), outputs=outputs,
                       used_inputs=used_inputs)


def _cse_pass(rows: list[set[int]], n_in: int) -> list[tuple[int, int, int]]:
    """Hoist repeated operand pairs into fresh nodes, mutating ``rows``
    in place; returns the intermediate op chain."""
    cnt: dict[tuple[int, int], int] = {}
    heap: list[tuple[int, tuple[int, int]]] = []

    def bump(pair: tuple[int, int], by: int) -> None:
        n = cnt.get(pair, 0) + by
        if n <= 0:
            cnt.pop(pair, None)
            return
        cnt[pair] = n
        if by > 0:
            # lazy heap: stale entries are skipped at pop time
            heapq.heappush(heap, (-n, pair))

    for row in rows:
        srow = sorted(row)
        for i, a in enumerate(srow):
            for b in srow[i + 1:]:
                bump((a, b), 1)

    ops: list[tuple[int, int, int]] = []
    nxt = n_in
    while heap:
        negn, pair = heap[0]
        live = cnt.get(pair, 0)
        if live < 2:
            heapq.heappop(heap)
            continue
        if -negn != live:
            heapq.heappop(heap)
            heapq.heappush(heap, (-live, pair))
            continue
        a, b = pair
        ops.append((nxt, a, b))
        for row in rows:
            if a not in row or b not in row:
                continue
            row.discard(a)
            row.discard(b)
            # retire the old pairs of a and b against the row's other
            # members, then count the new node's pairs — the pair table
            # stays exact without a rescan
            for other in row:
                bump((min(a, other), max(a, other)), -1)
                bump((min(b, other), max(b, other)), -1)
                bump((other, nxt), 1)
            row.add(nxt)
        cnt.pop(pair, None)
        nxt += 1
    return ops


def apply_schedule(sched: XorSchedule, planes: np.ndarray) -> np.ndarray:
    """Numpy oracle evaluator: planes (n_in, ...) -> (len(outputs), ...)
    by running the schedule literally.  The property tests compare this
    against the naive bit-matrix apply AND the device lowerings, so it
    stays dead simple on purpose."""
    planes = np.asarray(planes)
    if planes.shape[0] != sched.n_in:
        raise ValueError(
            f"expected {sched.n_in} planes, got {planes.shape[0]}")
    nodes: list[np.ndarray | None] = \
        [None] * (sched.n_in + len(sched.ops))
    for p in sched.used_inputs:
        nodes[p] = planes[p]
    for dst, a, b in sched.ops:
        nodes[dst] = nodes[a] ^ nodes[b]
    zero = np.zeros_like(planes[0]) if sched.n_in else None
    out = []
    for terms in sched.outputs:
        acc = None
        for t in terms:
            acc = nodes[t] if acc is None else acc ^ nodes[t]
        out.append(zero if acc is None else acc)
    return np.stack(out) if out else \
        np.zeros((0,) + planes.shape[1:], dtype=planes.dtype)


def naive_apply(B: np.ndarray, planes: np.ndarray) -> np.ndarray:
    """Reference bit-matrix apply: out[r] = XOR of planes where B[r]."""
    B = np.ascontiguousarray(B, dtype=np.uint8) & 1
    out = np.zeros((B.shape[0],) + planes.shape[1:], dtype=planes.dtype)
    for r in range(B.shape[0]):
        idx = np.nonzero(B[r])[0]
        if idx.size:
            out[r] = np.bitwise_xor.reduce(planes[idx], axis=0)
    return out
