"""OSD data plane: object stores, transactions, PGs, backends, daemons
(the reference's src/os + src/osd layers, SURVEY.md §2.5-2.6)."""
