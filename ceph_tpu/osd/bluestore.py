"""BlueStore-lite: raw-block allocator + KV-metadata ObjectStore.

The capability slot of the reference's BlueStore proper (SURVEY.md §2.6;
ref src/os/bluestore/BlueStore.cc — onodes+extent metadata in RocksDB,
data on a raw device through an allocator, deferred small writes staged
through the KV WAL, checksums verified on every read), designed for this
codebase rather than translated:

- **device**: one flat file (`block.img`), grown in 16 MiB steps, carved
  into 4 KiB allocation units ("pages" — the min_alloc_size role);
- **allocator**: a free-page heap + per-page refcounts, REBUILT at mount
  by scanning the onode table (the fsck/freelist role: the onodes are
  the single source of truth, so space leaked by a crash between a data
  write and its KV commit is reclaimed on the next mount);
- **metadata**: every onode (size, attrs, page map with per-page crc32c)
  lives in a `KeyValueDB` (WalKV: crc-framed fsync'd WAL + snapshot
  compaction) under prefix "O"; omap under "M"; collections under "C";
- **large writes** go to freshly allocated pages (never over live data),
  are fsync'd, and only then does the KV transaction commit the new page
  map — the classic write-ahead ordering;
- **small writes** (<= deferred_limit) take the deferred path: the new
  page content commits INSIDE the KV transaction (prefix "D") and the
  device page is written after the commit returns; a crash replays "D"
  records at mount (BlueStore's deferred-write mechanism, which is what
  makes sub-alloc-unit overwrites cheap and safe);
- **clone** is O(pages): the page map is copied and per-page refcounts
  bump — writes always allocate, so sharing is copy-on-write for free;
- **reads** verify each page's crc32c against the onode every time
  (BlueStore _verify_csum role) and raise StoreError on rot;
- **inline compression** (the Compression.cc / per-blob csum+compress
  role): large aligned writes compress into BLOBS — zlib over a run of
  logical pages stored in fewer physical pages — recorded in the onode
  and transparently decompressed on read; a blob is only kept when it
  saves real pages (required_ratio), and any overwrite of a compressed
  range first materialises it back to plain pages (BlueStore's
  compressed-blob GC-on-overwrite).

Transactions are atomic: ops stage against shadow onodes and commit in
one KV batch; validation failures roll back staged allocations and leave
no trace.
"""

from __future__ import annotations

import heapq
import os
import threading
from typing import Callable

from ..ops.native import crc32c, crc32c_blocks
from ..utils.buffer import BufferList
from ..utils.codec import Decoder, Encoder
from .filestore import _dec_value, _enc_value, _esc
from .kvstore import KVTransaction, WalKV
from .objectstore import (CollectionId, NoSuchCollection, NoSuchObject,
                          ObjectId, ObjectStore, StoreError, Transaction,
                          TxOp)

PAGE = 4096                 # allocation unit (min_alloc_size role)
EXTEND_PAGES = 4096         # device growth step: 16 MiB
DEFER_LIMIT = 16 * 1024     # writes at or below take the deferred path
DEFER_FLUSH_N = 64          # flush+trim "D" records past this many

HOLE = -1                   # page map entry for an unwritten page
BLOB_BASE = -2              # entries <= BLOB_BASE reference a blob:
#                             phys = BLOB_BASE - blob_id, crc = index of
#                             this logical page inside the blob's span
COMPRESS_MIN_PAGES = 8      # blob threshold: 32 KiB of aligned pages
COMPRESS_RATIO = 0.875      # keep a blob only if it saves >= 1/8th

_P_SUPER, _P_COLL, _P_ONODE, _P_OMAP, _P_DEFER = "S", "C", "O", "M", "D"


class Onode:
    """In-RAM onode: the decoded image of one "O" record (+ its omap)."""

    __slots__ = ("size", "attrs", "omap", "pages", "blobs")

    def __init__(self):
        self.size = 0
        self.attrs: dict[str, object] = {}
        self.omap: dict[str, object] = {}
        self.pages: list[tuple[int, int]] = []  # (phys page, crc32c)
        # compressed blobs: id -> {"pages": [(phys, crc)...] of the
        # compressed stream, "clen": compressed bytes, "raw": raw bytes}
        self.blobs: dict[int, dict] = {}

    def copy(self) -> "Onode":
        o = Onode()
        o.size = self.size
        o.attrs = dict(self.attrs)
        o.omap = dict(self.omap)
        o.pages = list(self.pages)
        o.blobs = {i: {"pages": list(b["pages"]), "clen": b["clen"],
                       "raw": b["raw"]} for i, b in self.blobs.items()}
        return o

    def phys_pages(self):
        """Every physical page this onode references (plain + blob)."""
        for phys, _crc in self.pages:
            if phys >= 0:
                yield phys
        for b in self.blobs.values():
            for phys, _crc in b["pages"]:
                yield phys


def _onode_key(cid: CollectionId, oid: ObjectId) -> str:
    return (f"{cid.pool}.{cid.pg_seed:x}|{_esc(oid.name)}"
            f"|{oid.shard}|{oid.generation}")


def _coll_prefix(cid: CollectionId) -> str:
    return f"{cid.pool}.{cid.pg_seed:x}|"


def _encode_onode(oid: ObjectId, o: Onode) -> bytes:
    e = Encoder()

    def body(se: Encoder):
        se.string(oid.name); se.i64(oid.shard); se.i64(oid.generation)
        se.u64(o.size)
        se.u32(len(o.attrs))
        for k, v in sorted(o.attrs.items()):
            se.string(str(k)); _enc_value(se, v)
        se.u32(len(o.pages))
        for phys, crc in o.pages:
            se.i64(phys); se.u32(crc)
        se.u32(len(o.blobs))                      # v2 tail
        for bid, b in sorted(o.blobs.items()):
            se.u64(bid)
            se.u64(b["clen"])
            se.u64(b["raw"])
            se.u32(len(b["pages"]))
            for phys, crc in b["pages"]:
                se.i64(phys); se.u32(crc)
    e.versioned(2, 1, body)
    return e.tobytes()


def _decode_onode(raw: bytes) -> tuple[ObjectId, Onode]:
    d = Decoder(raw)

    def body(sd: Decoder, version: int):
        oid = ObjectId(sd.string(), sd.i64(), sd.i64())
        o = Onode()
        o.size = sd.u64()
        for _ in range(sd.u32()):
            k = sd.string(); o.attrs[k] = _dec_value(sd)
        o.pages = [(sd.i64(), sd.u32()) for _ in range(sd.u32())]
        if version >= 2:
            for _ in range(sd.u32()):
                bid = sd.u64()
                clen = sd.u64()
                raw = sd.u64()
                pages = [(sd.i64(), sd.u32())
                         for _ in range(sd.u32())]
                o.blobs[bid] = {"pages": pages, "clen": clen,
                                "raw": raw}
        return oid, o
    return d.versioned(2, body)


class _Staging:
    """Shadow state for one transaction (nothing escapes until commit)."""

    def __init__(self):
        self.onodes: dict[tuple, Onode | None] = {}   # None = removed
        self.colls_created: set[CollectionId] = set()
        self.colls_removed: set[CollectionId] = set()
        self.kv = KVTransaction()
        self.large: list[tuple[int, bytes]] = []      # (phys, 4K payload)
        self.defer: list[tuple[int, bytes]] = []
        self.page_data: dict[int, bytes] = {}         # staged content
        self.allocs: list[int] = []
        self.frees: list[int] = []
        self.touched: set[tuple] = set()              # need onode re-put


class BlueStore(ObjectStore):
    """Durable ObjectStore: block-device pages + KV onodes (see module
    docstring for the layout and crash-ordering rules)."""

    def __init__(self, path: str, defer_limit: int = DEFER_LIMIT,
                 kv_backend: str | None = None,
                 compression: str = "zlib",
                 kv_name: str | None = None,
                 kv_memtable_bytes: int | None = None,
                 kv_cache_bytes: int | None = None,
                 kv_background: bool | None = None):
        self.path = path
        self.defer_limit = defer_limit
        # metadata KV tier: "wal" (snapshot-compacting log) or "sst"
        # (leveled LSM with background flush/compaction, osd/sstkv.py).
        # None = unset; configure_kv may fill it from config before
        # mount, else "wal".  kv_name stands the kv.<name> perf
        # registry (flush/compact/stall/cache telemetry)
        self.kv_backend = kv_backend
        self.kv_name = kv_name
        self.kv_memtable_bytes = kv_memtable_bytes
        self.kv_cache_bytes = kv_cache_bytes
        self.kv_background = kv_background
        # inline blob compression mode ("zlib" | "none") —
        # bluestore_compression_{mode,algorithm} role
        self.compression = None if compression in ("none", "", None) \
            else compression
        self._lock = threading.RLock()
        self._mounted = False
        self._dev = None
        self._kv = None
        self._colls: dict[CollectionId, dict[ObjectId, Onode]] = {}
        self._free: list[int] = []        # heap of free page numbers
        self._refs: dict[int, int] = {}   # phys -> refcount (live pages)
        self._npages = 0
        self._deferred: dict[int, bytes] = {}  # committed, not yet fsync'd
        # staged deferred payloads whose KV commit is still queued in the
        # async pipeline: readable from RAM immediately (read-your-writes
        # before durability) but NOT written to the device until after
        # their batch's KV fsync — an in-place deferred overwrite of a
        # live page must never clobber committed bytes the crash replay
        # would still need
        self._deferred_pending: dict[int, bytes] = {}

    def configure_kv(self, cfg, name: str | None = None) -> None:
        """Fill UNSET kv-tier knobs from config before mount (the
        daemon calls this with its own name so maintenance telemetry
        lands on ``kv.<daemon>``).  Explicit constructor arguments
        always win — a store built with ``kv_backend="sst"`` stays
        sst whatever the config says."""
        if self._mounted:
            return
        if name is not None and self.kv_name is None:
            self.kv_name = name

        def opt(key):
            # per-option guard: a cfg missing ONE knob (older/test
            # schema) must not silently drop the knobs after it
            try:
                return cfg[key]
            except Exception:  # noqa: BLE001
                return None
        if self.kv_backend is None:
            self.kv_backend = opt("kv_backend")
        if self.kv_memtable_bytes is None:
            self.kv_memtable_bytes = opt("kv_memtable_bytes")
        if self.kv_cache_bytes is None:
            self.kv_cache_bytes = opt("kv_cache_bytes")
        if self.kv_background is None:
            bg = opt("kv_bg_maintenance")
            if bg is not None:
                self.kv_background = str(bg).lower() == "on"

    def kv_stats(self) -> dict | None:
        with self._lock:
            return self._kv.stats() if self._kv is not None else None

    # ------------------------------------------------------------ mount
    def mount(self) -> None:
        with self._lock:
            if self._mounted:
                return
            os.makedirs(self.path, exist_ok=True)
            from .kvstore import create_kv
            backend = self.kv_backend or "wal"
            if backend == "wal":
                # bg snapshot compaction: the wal backend's inline
                # stall in miniature moves off the submit (kv-sync)
                # path too, unless explicitly pinned off
                self._kv = WalKV(self.path, name=self.kv_name,
                                 bg_compact=self.kv_background
                                 is not False)
            else:
                kw: dict = {"name": self.kv_name}
                if self.kv_memtable_bytes is not None:
                    kw["memtable_bytes"] = self.kv_memtable_bytes
                if self.kv_cache_bytes is not None:
                    kw["cache_bytes"] = self.kv_cache_bytes
                if self.kv_background is not None:
                    kw["background"] = self.kv_background
                self._kv = create_kv(backend,
                                     os.path.join(self.path, "kv"),
                                     **kw)
            super_raw = self._kv.get(_P_SUPER, "super")
            if super_raw is None:
                self._kv.put(_P_SUPER, "super", str(PAGE).encode())
            elif int(super_raw) != PAGE:
                raise StoreError(f"page size mismatch: {super_raw!r}")
            dev_path = os.path.join(self.path, "block.img")
            if not os.path.exists(dev_path):
                open(dev_path, "wb").close()
            # "r+b", NOT append mode: O_APPEND would force every page
            # write to the end of the device regardless of seek
            self._dev = open(dev_path, "r+b")
            self._dev.seek(0, os.SEEK_END)
            self._npages = self._dev.tell() // PAGE
            self._load_metadata()
            self._replay_deferred()
            self._mounted = True

    def umount(self) -> None:
        self.flush()  # drain the commit pipeline before closing
        with self._lock:
            if not self._mounted:
                return
            self._flush_deferred()
            self._dev.close()
            self._dev = None
            self._kv.close()
            self._kv = None
            self._mounted = False

    def _load_metadata(self) -> None:
        self._colls = {}
        self._refs = {}
        key_to_obj: dict[str, tuple[CollectionId, ObjectId]] = {}
        for ckey, _ in self._kv.iterate(_P_COLL):
            pool, seed = ckey.split(".")
            self._colls[CollectionId(int(pool), int(seed, 16))] = {}
        for okey, raw in self._kv.iterate(_P_ONODE):
            pool, seed = okey.split("|", 1)[0].split(".")
            cid = CollectionId(int(pool), int(seed, 16))
            oid, onode = _decode_onode(raw)
            self._colls.setdefault(cid, {})[oid] = onode
            key_to_obj[okey] = (cid, oid)
            for phys in onode.phys_pages():
                self._refs[phys] = self._refs.get(phys, 0) + 1
        for mkey, val in self._kv.iterate(_P_OMAP):
            okey, _, user = mkey.partition("\x00")
            ref = key_to_obj.get(okey)
            if ref is None:
                continue  # orphaned omap row; ignored (fsck would trim)
            cid, oid = ref
            self._colls[cid][oid].omap[user] = _dec_value(Decoder(val))
        # freelist = every device page nobody references (fsck role:
        # reclaims pages leaked by a crash before their KV commit)
        self._free = [p for p in range(self._npages) if p not in self._refs]
        heapq.heapify(self._free)

    def _replay_deferred(self) -> None:
        """Apply committed-but-unwritten deferred pages (crash window:
        KV committed the content, the device write never happened)."""
        pending = list(self._kv.iterate(_P_DEFER))
        if not pending:
            return
        for key, data in pending:
            self._dev_write(int(key), data)
        self._dev.flush()
        os.fsync(self._dev.fileno())
        tx = KVTransaction()
        for key, _ in pending:
            tx.rm(_P_DEFER, key)
        self._kv.submit(tx)

    # ----------------------------------------------------- device pages
    def _dev_write(self, phys: int, data: bytes) -> None:
        assert len(data) == PAGE
        self._dev.seek(phys * PAGE)
        self._dev.write(data)

    def _dev_read(self, phys: int) -> bytes:
        self._dev.seek(phys * PAGE)
        return self._dev.read(PAGE)

    def _alloc(self, st: _Staging) -> int:
        if not self._free:
            new_pages = range(self._npages, self._npages + EXTEND_PAGES)
            self._npages += EXTEND_PAGES
            self._dev.truncate(self._npages * PAGE)
            for p in new_pages:
                heapq.heappush(self._free, p)
        phys = heapq.heappop(self._free)
        self._refs[phys] = 1
        st.allocs.append(phys)
        return phys

    def _rollback(self, st: _Staging) -> None:
        # st.allocs holds fresh allocations (ref set to 1) AND clone ref
        # bumps (+1 on a live page): undo both by decrementing, freeing
        # only pages that hit zero
        for phys in st.allocs:
            n = self._refs.get(phys, 0) - 1
            if n <= 0:
                self._refs.pop(phys, None)
                heapq.heappush(self._free, phys)
            else:
                self._refs[phys] = n

    def _read_page(self, st: _Staging | None, phys: int, crc: int,
                   verify: bool = True) -> bytes:
        if st is not None and phys in st.page_data:
            return st.page_data[phys]
        data = self._deferred_pending.get(phys)
        if data is None:
            data = self._deferred.get(phys)
        if data is None:
            data = self._dev_read(phys)
        if verify and crc32c(data) != crc:
            raise StoreError(f"checksum mismatch on page {phys}")
        return data

    # ------------------------------------------------------ transactions
    def _prepare(self, tx: Transaction) -> _Staging:
        """Stage against shadow onodes, then pre-commit apply: large
        payloads land on FRESH pages (buffered — the batch fsync makes
        them durable before any KV metadata points at them), the onode/
        omap/defer KV mutations are encoded into the staging's batch,
        and the in-RAM state flips so reads observe the transaction
        immediately (read-your-writes before durability).  What does
        NOT happen here: fsyncs, the KV commit, freed-page release and
        deferred device writes — all batch-ordered in _commit_batch so
        a crash can only lose un-acked suffixes."""
        with self._lock:
            if not self._mounted:
                raise StoreError("not mounted")
            st = _Staging()
            try:
                for op in tx.ops:
                    self._stage(st, op)
            except Exception:
                self._rollback(st)
                raise
            # 1) large writes: buffered now, fsync'd once per batch.
            #    Fresh pages only — never over live data — so the
            #    buffered window cannot clobber committed bytes.
            #    Contiguous pages (the common fresh-allocation shape)
            #    coalesce into one seek+write per run instead of one
            #    syscall pair per 4K page.
            if st.large:
                run_phys = -2
                run: list = []
                for phys, content in sorted(st.large):
                    if phys != run_phys + len(run):
                        if run:
                            self._dev.seek(run_phys * PAGE)
                            self._dev.write(b"".join(run))
                        run_phys, run = phys, []
                    run.append(content)
                if run:
                    self._dev.seek(run_phys * PAGE)
                    self._dev.write(b"".join(run))
            # 2) KV mutations for this tx (order matters: the staged
            #    coll/omap ops are already in st.kv; onodes then
            #    deferred payloads append behind them)
            for (cid, oid), onode in st.onodes.items():
                if (cid, oid) not in st.touched:
                    continue
                key = _onode_key(cid, oid)
                if onode is None:
                    st.kv.rm(_P_ONODE, key)
                else:
                    st.kv.put(_P_ONODE, key, _encode_onode(oid, onode))
            # deferred payloads detach here (they may be memoryview
            # carves over an rx frame; they outlive this call) and
            # become readable from RAM at once
            st.defer = [(phys, bytes(content))
                        for phys, content in st.defer]
            for phys, content in st.defer:
                st.kv.put(_P_DEFER, str(phys), content)
                self._deferred_pending[phys] = content
            # 3) in-RAM state flips to the shadow copies — reads (and
            #    later prepares) see this transaction from now on
            for cid in st.colls_created:
                self._colls.setdefault(cid, {})
            for (cid, oid), onode in st.onodes.items():
                if onode is None:
                    self._colls.get(cid, {}).pop(oid, None)
                else:
                    self._colls.setdefault(cid, {})[oid] = onode
            for cid in st.colls_removed:
                self._colls.pop(cid, None)
            return st

    def _commit_batch(self, items: list) -> int:
        """Group commit: ONE device fsync covering every item's large
        pages, ONE vectored KV append + fsync for the whole batch, then
        the ordered epilogue (freed-page release, deferred device
        writes) that must wait for durability.

        Crash ordering: the device fsync precedes any KV write, so a
        committed (prefix of the) KV batch only ever references durable
        pages; freed pages rejoin the allocator only AFTER the KV fsync
        (a reallocated page's new bytes could otherwise clobber data a
        replayed-but-unfreed onode still points at); deferred device
        writes run after commit exactly as the inline path did, with
        the "D" replay covering the crash window."""
        fsyncs = 0
        if any(st.large for st in items):
            self._dev.flush()
            os.fsync(self._dev.fileno())
            fsyncs += 1
        merged = KVTransaction()
        for st in items:
            merged.ops.extend(st.kv.ops)
        with self._lock:
            # pages whose refcount hits ZERO across this batch shed any
            # pending "D" record IN THIS COMMIT: once free they can be
            # reallocated, and a stale deferred replay after a crash
            # would clobber the new owner.  Dead pages are unreachable
            # from live onodes (every RAM flip already happened at
            # prepare), so no concurrent preparer can re-reference one
            # before the frees apply below.
            dead: set[int] = set()
            refsim: dict[int, int] = {}
            for st in items:
                for phys in st.frees:
                    n = refsim.get(phys, self._refs.get(phys, 0)) - 1
                    refsim[phys] = n
                    if n <= 0:
                        dead.add(phys)
            for phys in dead:
                merged.rm(_P_DEFER, str(phys))
        if merged.ops:
            self._kv.submit(merged, sync=False)
            self._kv.sync()
            fsyncs += 1
        with self._lock:
            for st in items:
                for phys in st.frees:
                    n = self._refs.get(phys, 0) - 1
                    if n <= 0:
                        self._refs.pop(phys, None)
                        self._deferred.pop(phys, None)
                        self._deferred_pending.pop(phys, None)
                        heapq.heappush(self._free, phys)
                    else:
                        self._refs[phys] = n
                for phys, content in st.defer:
                    if phys in dead:
                        continue
                    self._dev_write(phys, content)
                    self._deferred[phys] = content
                    # a LATER (still pending) batch may have re-staged
                    # this page — only retire OUR payload from the
                    # read-path overlay
                    if self._deferred_pending.get(phys) is content:
                        self._deferred_pending.pop(phys, None)
            if len(self._deferred) > DEFER_FLUSH_N:
                self._flush_deferred()
                fsyncs += 2  # device fsync + the trim's KV fsync
        return fsyncs

    # -- staging helpers ---------------------------------------------------
    def _coll_exists(self, st: _Staging, cid: CollectionId) -> bool:
        if cid in st.colls_created:
            return True
        if cid in st.colls_removed:
            return False
        return cid in self._colls

    def _get_onode(self, st: _Staging, cid, oid, create: bool) -> Onode:
        if not self._coll_exists(st, cid):
            raise NoSuchCollection(str(cid))
        key = (cid, oid)
        if key in st.onodes:
            o = st.onodes[key]
            if o is None:
                if not create:
                    raise NoSuchObject(f"{cid}/{oid}")
                o = Onode()
                st.onodes[key] = o
                st.touched.add(key)
            return o
        live = self._colls.get(cid, {}).get(oid)
        if live is None:
            if not create:
                raise NoSuchObject(f"{cid}/{oid}")
            o = Onode()
        else:
            o = live.copy()
        st.onodes[key] = o
        st.touched.add(key)
        return o

    def _free_page(self, st: _Staging, phys: int) -> None:
        if phys >= 0:  # HOLE and blob sentinels are not device pages
            st.frees.append(phys)

    def _blob_raw(self, st: _Staging | None, o: Onode,
                  bid: int) -> bytes:
        """Decompress one blob (crc-verified per compressed page)."""
        import zlib
        b = o.blobs[bid]
        parts = [self._read_page(st, phys, crc)
                 for phys, crc in b["pages"]]
        comp = b"".join(parts)[: b["clen"]]
        return zlib.decompress(comp)

    def _unblob_range(self, st: _Staging, o: Onode, first: int,
                      last: int) -> None:
        """Materialise any blob overlapping logical pages
        [first, last] back to plain pages (the compressed-blob
        rewrite-on-overwrite, Compression.cc GC role)."""
        hit = set()
        for idx in range(min(first, len(o.pages)),
                         min(last + 1, len(o.pages))):
            phys = o.pages[idx][0]
            if phys <= BLOB_BASE:
                hit.add(BLOB_BASE - phys)
        for bid in hit:
            raw = self._blob_raw(st, o, bid)
            span = [i for i, (p, _c) in enumerate(o.pages)
                    if p <= BLOB_BASE and BLOB_BASE - p == bid]
            for i in span:
                off = o.pages[i][1] * PAGE
                content = raw[off: off + PAGE]
                content += b"\0" * (PAGE - len(content))
                phys = self._alloc(st)
                o.pages[i] = (phys, crc32c(content))
                st.page_data[phys] = content
                st.large.append((phys, content))
            for phys, _crc in o.blobs[bid]["pages"]:
                self._free_page(st, phys)
            del o.blobs[bid]

    def _page_content(self, st: _Staging, o: Onode, idx: int) -> bytes:
        """Current (staged-aware) content of logical page idx, zero-padded
        to PAGE."""
        if idx >= len(o.pages) or o.pages[idx][0] == HOLE:
            return b"\0" * PAGE
        phys, crc = o.pages[idx]
        if phys <= BLOB_BASE:
            raw = self._blob_raw(st, o, BLOB_BASE - phys)
            off = crc * PAGE
            content = raw[off: off + PAGE]
            return content + b"\0" * (PAGE - len(content))
        return self._read_page(st, phys, crc)

    def _put_page(self, st: _Staging, o: Onode, idx: int, content: bytes,
                  deferred: bool, crc: int | None = None) -> None:
        """Install new content for logical page idx: allocate (or reuse
        in-place on the deferred path when we are the sole owner) and
        route the payload to the right write path."""
        while len(o.pages) <= idx:
            o.pages.append((HOLE, 0))
        old_phys, _old_crc = o.pages[idx]
        if crc is None:
            crc = crc32c(content)
        in_place = (deferred and old_phys != HOLE
                    and self._refs.get(old_phys, 0) == 1
                    and old_phys not in (p for p, _ in st.large))
        if in_place:
            phys = old_phys
        else:
            phys = self._alloc(st)
            self._free_page(st, old_phys)
        o.pages[idx] = (phys, crc)
        st.page_data[phys] = content
        if deferred:
            st.defer.append((phys, content))
        else:
            st.large.append((phys, content))

    def _write_range(self, st: _Staging, o: Onode, offset: int,
                     data: bytes) -> None:
        if not data:
            return
        deferred = len(data) <= self.defer_limit
        end = offset + len(data)
        first, last = offset // PAGE, (end - 1) // PAGE
        # a write over a compressed range first materialises the blob
        # (partial overwrite of compressed data is read-modify-write)
        self._unblob_range(st, o, first, last)
        if self.compression and not deferred and \
                self._try_compress(st, o, offset, data):
            return
        ref_b = copy_b = 0
        # per-page csums for the whole-page span in ONE native sweep
        # (a ctypes round-trip per 4K page dominates MiB-scale ingest)
        full_first = first if offset % PAGE == 0 else first + 1
        full_last = last if end % PAGE == 0 else last - 1
        full_crcs: list[int] = []
        if full_last >= full_first:
            full_crcs = crc32c_blocks(
                data[full_first * PAGE - offset: (full_last + 1) * PAGE
                     - offset], PAGE)
        for idx in range(first, last + 1):
            pstart = idx * PAGE
            lo = max(offset, pstart) - pstart
            hi = min(end, pstart + PAGE) - pstart
            if lo == 0 and hi == PAGE:
                # whole-page run: a slice of the caller's buffer — for
                # a memoryview payload this is BY REFERENCE (detached
                # at the buffered write() syscall in _prepare, so the
                # rx frame never pins past the enqueue)
                content = data[pstart - offset: pstart - offset + PAGE]
                if isinstance(content, memoryview):
                    ref_b += PAGE
                else:
                    copy_b += PAGE
                self._put_page(st, o, idx, content, deferred,
                               crc=full_crcs[idx - full_first])
            else:
                old = bytearray(self._page_content(st, o, idx))
                old[lo:hi] = data[pstart + lo - offset: pstart + hi - offset]
                content = bytes(old)
                copy_b += PAGE
                self._put_page(st, o, idx, content, deferred)
        if ref_b:
            self._book("store_ingest_ref_bytes", ref_b)
        if copy_b:
            self._book("store_ingest_copy_bytes", copy_b)
        o.size = max(o.size, end)

    def _try_compress(self, st: _Staging, o: Onode, offset: int,
                      data: bytes) -> bool:
        """Blob-compress the aligned whole-page run of this write when
        it spans enough pages AND actually saves space (required_ratio)
        — the Compression.cc inline path.  Unaligned head/tail bytes
        fall through to the plain path.  Returns True when the WHOLE
        write was consumed."""
        import zlib
        if offset % PAGE or len(data) % PAGE:
            return False  # keep it simple: only fully aligned writes
        n = len(data) // PAGE
        if n < COMPRESS_MIN_PAGES:
            return False
        comp = zlib.compress(data, 1)
        cpages = (len(comp) + PAGE - 1) // PAGE
        if cpages > n * COMPRESS_RATIO:
            return False  # not compressible enough to bother
        first = offset // PAGE
        bid = (max(o.blobs) + 1) if o.blobs else 0
        blob_pages = []
        for i in range(cpages):
            chunk = comp[i * PAGE: (i + 1) * PAGE]
            chunk += b"\0" * (PAGE - len(chunk))
            phys = self._alloc(st)
            blob_pages.append((phys, crc32c(chunk)))
            st.page_data[phys] = chunk
            st.large.append((phys, chunk))
        o.blobs[bid] = {"pages": blob_pages, "clen": len(comp),
                        "raw": len(data)}
        while len(o.pages) < first + n:
            o.pages.append((HOLE, 0))
        for i in range(n):
            self._free_page(st, o.pages[first + i][0])
            o.pages[first + i] = (BLOB_BASE - bid, i)
        o.size = max(o.size, offset + len(data))
        return True

    def _zero_range(self, st: _Staging, o: Onode, offset: int,
                    length: int) -> None:
        if length <= 0:
            o.size = max(o.size, offset)
            return
        end = offset + length
        first, last = offset // PAGE, (end - 1) // PAGE
        self._unblob_range(st, o, first, last)
        for idx in range(first, last + 1):
            pstart = idx * PAGE
            lo = max(offset, pstart) - pstart
            hi = min(end, pstart + PAGE) - pstart
            if idx >= len(o.pages):
                break  # beyond current pages: holes already read as zero
            if lo == 0 and hi == PAGE:
                self._free_page(st, o.pages[idx][0])
                o.pages[idx] = (HOLE, 0)
            elif o.pages[idx][0] != HOLE:
                old = bytearray(self._page_content(st, o, idx))
                old[lo:hi] = b"\0" * (hi - lo)
                self._put_page(st, o, idx, bytes(old), True)
        o.size = max(o.size, end)

    def _truncate(self, st: _Staging, o: Onode, size: int) -> None:
        if size < o.size:
            keep = (size + PAGE - 1) // PAGE
            self._unblob_range(st, o, max(0, keep - 1),
                               len(o.pages) - 1)
            for phys, _crc in o.pages[keep:]:
                self._free_page(st, phys)
            del o.pages[keep:]
            tail = size % PAGE
            if tail and keep <= len(o.pages) and keep >= 1 \
                    and o.pages[keep - 1][0] != HOLE:
                # zero the bytes past the new size inside the tail page so
                # a later grow reads zeros (MemStore truncate semantics)
                old = bytearray(self._page_content(st, o, keep - 1))
                old[tail:] = b"\0" * (PAGE - tail)
                self._put_page(st, o, keep - 1, bytes(old), True)
        o.size = size

    def _remove_onode(self, st: _Staging, cid, oid) -> None:
        o = self._get_onode(st, cid, oid, create=False)
        for phys in o.phys_pages():
            self._free_page(st, phys)
        # drop the omap rows here, while the (possibly staged) key set is
        # known — a later re-create in the same tx must not inherit them
        okey = _onode_key(cid, oid)
        for k in o.omap:
            st.kv.rm(_P_OMAP, f"{okey}\x00{k}")
        st.onodes[(cid, oid)] = None
        st.touched.add((cid, oid))

    def _stage(self, st: _Staging, op) -> None:
        kind = op[0]
        if kind == TxOp.CREATE_COLLECTION:
            st.colls_created.add(op[1])
            st.colls_removed.discard(op[1])
            st.kv.put(_P_COLL, f"{op[1].pool}.{op[1].pg_seed:x}", b"1")
            return
        if kind == TxOp.REMOVE_COLLECTION:
            cid = op[1]
            if not self._coll_exists(st, cid):
                raise NoSuchCollection(str(cid))
            # every object goes: committed ones AND ones created earlier
            # in this same transaction (staged onodes)
            doomed = set(self._colls.get(cid, {}))
            doomed.update(o for (c, o), onode in st.onodes.items()
                          if c == cid and onode is not None)
            for oid in doomed:
                if st.onodes.get((cid, oid), "absent") is not None:
                    self._remove_onode(st, cid, oid)
            st.colls_removed.add(cid)
            st.colls_created.discard(cid)
            st.kv.rm(_P_COLL, f"{cid.pool}.{cid.pg_seed:x}")
            return
        cid, oid = op[1], op[2]
        if kind == TxOp.TOUCH:
            self._get_onode(st, cid, oid, create=True)
        elif kind == TxOp.WRITE:
            o = self._get_onode(st, cid, oid, create=True)
            # contiguous(): single-buffer payloads (the rx-carved wire
            # path) arrive as a zero-copy view — whole aligned pages
            # slice out of it by reference straight into the buffered
            # device write; partial pages rebuild (copy) as ever
            self._write_range(st, o, op[3], op[4].contiguous())
        elif kind == TxOp.ZERO:
            o = self._get_onode(st, cid, oid, create=True)
            self._zero_range(st, o, op[3], op[4])
        elif kind == TxOp.TRUNCATE:
            o = self._get_onode(st, cid, oid, create=True)
            self._truncate(st, o, op[3])
        elif kind == TxOp.REMOVE:
            self._remove_onode(st, cid, oid)
        elif kind == TxOp.SETATTRS:
            o = self._get_onode(st, cid, oid, create=True)
            o.attrs.update(op[3])
        elif kind == TxOp.RMATTR:
            o = self._get_onode(st, cid, oid, create=False)
            o.attrs.pop(op[3], None)
        elif kind == TxOp.OMAP_SETKEYS:
            o = self._get_onode(st, cid, oid, create=True)
            o.omap.update(op[3])
            okey = _onode_key(cid, oid)
            for k, v in op[3].items():
                e = Encoder(); _enc_value(e, v)
                st.kv.put(_P_OMAP, f"{okey}\x00{k}", e.tobytes())
        elif kind == TxOp.OMAP_RMKEYS:
            o = self._get_onode(st, cid, oid, create=False)
            okey = _onode_key(cid, oid)
            for k in op[3]:
                o.omap.pop(k, None)
                st.kv.rm(_P_OMAP, f"{okey}\x00{k}")
        elif kind == TxOp.CLONE:
            src = self._get_onode(st, cid, op[2], create=False)
            dst_oid = op[3]
            dst = self._get_onode(st, cid, dst_oid, create=True)
            for phys in dst.phys_pages():  # clone fully replaces dst
                self._free_page(st, phys)
            dst.size = src.size
            dst.attrs = dict(src.attrs)
            dst.pages = list(src.pages)
            dst.blobs = {i: {"pages": list(b["pages"]),
                             "clen": b["clen"], "raw": b["raw"]}
                         for i, b in src.blobs.items()}
            for phys in src.phys_pages():  # share pages, bump refs
                self._refs[phys] = self._refs.get(phys, 0) + 1
                st.allocs.append(phys)  # rollback undoes the bump
            dst_key = _onode_key(cid, dst_oid)
            for k in dst.omap:
                st.kv.rm(_P_OMAP, f"{dst_key}\x00{k}")
            dst.omap = dict(src.omap)
            for k, v in dst.omap.items():
                e = Encoder(); _enc_value(e, v)
                st.kv.put(_P_OMAP, f"{dst_key}\x00{k}", e.tobytes())
        else:  # pragma: no cover
            raise StoreError(f"unknown tx op {kind}")

    def _flush_deferred(self) -> None:
        if not self._deferred:
            return
        self._dev.flush()
        os.fsync(self._dev.fileno())
        tx = KVTransaction()
        for phys in self._deferred:
            tx.rm(_P_DEFER, str(phys))
        self._kv.submit(tx)
        self._deferred.clear()

    # ------------------------------------------------------------ reads
    def _onode(self, cid, oid) -> Onode:
        coll = self._colls.get(cid)
        if coll is None:
            raise NoSuchCollection(str(cid))
        o = coll.get(oid)
        if o is None:
            raise NoSuchObject(f"{cid}/{oid}")
        return o

    def read(self, cid, oid, offset: int = 0,
             length: int | None = None) -> BufferList:
        with self._lock:
            o = self._onode(cid, oid)
            end = o.size if length is None else min(offset + length, o.size)
            if offset >= end:
                return BufferList(b"")
            first, last = offset // PAGE, (end - 1) // PAGE
            parts = []
            blob_cache: dict[int, bytes] = {}
            for idx in range(first, last + 1):
                if idx < len(o.pages) and o.pages[idx][0] != HOLE:
                    phys, crc = o.pages[idx]
                    try:
                        if phys <= BLOB_BASE:
                            bid = BLOB_BASE - phys
                            raw = blob_cache.get(bid)
                            if raw is None:
                                raw = self._blob_raw(None, o, bid)
                                blob_cache[bid] = raw
                            seg = raw[crc * PAGE: crc * PAGE + PAGE]
                            parts.append(seg + b"\0" * (PAGE - len(seg)))
                        else:
                            parts.append(self._read_page(None, phys,
                                                         crc))
                    except StoreError:
                        raise StoreError(
                            f"checksum mismatch on {cid}/{oid}")
                else:
                    parts.append(b"\0" * PAGE)
            blob = b"".join(parts)
            lo = offset - first * PAGE
            return BufferList(blob[lo:lo + (end - offset)])

    def deep_verify(self, cid, oid) -> bool:
        """Re-read every page from the device and verify its checksum
        (deep-scrub primitive; returns False on rot)."""
        with self._lock:
            try:
                o = self._onode(cid, oid)
            except (NoSuchCollection, NoSuchObject):
                return True
            entries = [(p, c) for p, c in o.pages if p >= 0]
            for b in o.blobs.values():
                entries.extend(b["pages"])
            for phys, crc in entries:
                data = self._deferred_pending.get(phys)
                if data is None:
                    data = self._deferred.get(phys)
                if data is None:
                    data = self._dev_read(phys)
                if crc32c(data) != crc:
                    return False
            return True

    def stat(self, cid, oid) -> dict:
        with self._lock:
            o = self._onode(cid, oid)
            return {"size": o.size, "attrs": len(o.attrs),
                    "omap": len(o.omap)}

    def exists(self, cid, oid) -> bool:
        with self._lock:
            return oid in self._colls.get(cid, {})

    def getattrs(self, cid, oid) -> dict[str, bytes]:
        with self._lock:
            return dict(self._onode(cid, oid).attrs)

    def omap_get(self, cid, oid) -> dict[str, bytes]:
        with self._lock:
            return dict(self._onode(cid, oid).omap)

    def list_objects(self, cid) -> list[ObjectId]:
        with self._lock:
            coll = self._colls.get(cid)
            if coll is None:
                raise NoSuchCollection(str(cid))
            return sorted(coll)

    def list_collections(self) -> list[CollectionId]:
        with self._lock:
            return sorted(self._colls)

    # ------------------------------------------------------- diagnostics
    def fsck(self) -> dict:
        """Cross-check allocator vs onode page maps (BlueStore fsck
        role).  Returns counters; raises nothing."""
        with self._lock:
            referenced: dict[int, int] = {}
            for coll in self._colls.values():
                for o in coll.values():
                    for phys in o.phys_pages():
                        referenced[phys] = referenced.get(phys, 0) + 1
            free = set(self._free)
            leaked = [p for p in range(self._npages)
                      if p not in referenced and p not in free]
            double = [p for p in referenced if p in free]
            bad_refs = {p: (self._refs.get(p), n)
                        for p, n in referenced.items()
                        if self._refs.get(p) != n}
            return {"pages": self._npages, "referenced": len(referenced),
                    "free": len(free), "leaked": leaked,
                    "double_booked": double, "bad_refcounts": bad_refs}
