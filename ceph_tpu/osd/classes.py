"""Object classes: server-side compute in the OSD IO path.

The capability of the reference's ClassHandler + src/cls/* (dlopen'd
object classes — lock, version, cmpomap, ... — whose methods run
INSIDE the OSD against the object, ref src/osd/ClassHandler.cc and the
`call` op in PrimaryLogPG::do_osd_ops): a registry of named classes;
a method receives a context exposing the object's data/omap and QUEUES
mutations, which the primary then applies through the normal
replicated write path (so class effects replicate and log like any
other write).

Built-ins mirror the most-used reference classes:
- lock: advisory object locks in omap (cls_lock)
- version: a cas-guarded version counter in omap (cls_version)
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from ..msg.wire import pack_value as pack, unpack_value as unpack


class ClsError(Exception):
    def __init__(self, code: int, what: str):
        super().__init__(what)
        self.code = code


class ClsContext:
    """What a class method sees: read the object, queue mutations."""

    def __init__(self, data: bytes, omap: dict, exists: bool):
        self._data = data
        self._omap = dict(omap)
        self.exists = exists
        # queued effects, applied atomically by the primary afterwards
        self.new_data: bytes | None = None
        self.omap_set: dict[str, bytes] = {}
        self.omap_rm: set[str] = set()

    def read(self) -> bytes:
        return self._data

    def write(self, data: bytes) -> None:
        self.new_data = bytes(data)

    def omap_get(self, key: str) -> bytes | None:
        if key in self.omap_rm:
            return None
        if key in self.omap_set:
            return self.omap_set[key]
        return self._omap.get(key)

    def omap_all(self) -> dict:
        out = {k: v for k, v in self._omap.items()
               if k not in self.omap_rm}
        out.update(self.omap_set)
        return out

    def set_omap(self, key: str, value: bytes) -> None:
        self.omap_rm.discard(key)
        self.omap_set[key] = bytes(value)

    def rm_omap(self, key: str) -> None:
        self.omap_set.pop(key, None)
        self.omap_rm.add(key)


_CLASSES: dict[str, dict[str, Callable]] = {}
_LOCK = threading.Lock()


def register_class(cls_name: str, method: str):
    """Register `cls_name.method` (the cls_register_cxx_method role)."""

    def deco(fn):
        with _LOCK:
            _CLASSES.setdefault(cls_name, {})[method] = fn
        return fn

    return deco


def call(cls_name: str, method: str, ctx: ClsContext, inp) -> object:
    with _LOCK:
        fn = _CLASSES.get(cls_name, {}).get(method)
    if fn is None:
        raise ClsError(-22, f"no class method {cls_name}.{method}")
    return fn(ctx, inp if inp is not None else {})


def registered() -> dict[str, list[str]]:
    with _LOCK:
        return {c: sorted(m) for c, m in sorted(_CLASSES.items())}


# ---------------------------------------------------------------- cls_lock
_LOCK_KEY = "lock.%s"


@register_class("lock", "lock")
def _cls_lock(ctx: ClsContext, inp) -> object:
    name, owner, exclusive = inp["name"], inp["owner"], \
        bool(inp.get("exclusive", True))
    raw = ctx.omap_get(_LOCK_KEY % name)
    state = unpack(raw) or {"exclusive": exclusive, "owners": []}
    owners = list(state["owners"])
    if owners:
        if state["exclusive"] or exclusive:
            if owners != [owner]:
                raise ClsError(-16, f"lock {name!r} held by {owners}")
        elif owner in owners:
            pass  # re-entrant shared
        else:
            owners.append(owner)
    else:
        owners = [owner]
        state["exclusive"] = exclusive
    state["owners"] = owners
    state["stamp"] = time.time()
    ctx.set_omap(_LOCK_KEY % name, pack(state))
    return {"owners": owners}


@register_class("lock", "unlock")
def _cls_unlock(ctx: ClsContext, inp) -> object:
    name, owner = inp["name"], inp["owner"]
    state = unpack(ctx.omap_get(_LOCK_KEY % name))
    if not state or owner not in state["owners"]:
        raise ClsError(-2, f"{owner!r} does not hold lock {name!r}")
    state["owners"] = [o for o in state["owners"] if o != owner]
    if state["owners"]:
        ctx.set_omap(_LOCK_KEY % name, pack(state))
    else:
        ctx.rm_omap(_LOCK_KEY % name)
    return {}


@register_class("lock", "break_lock")
def _cls_break_lock(ctx: ClsContext, inp) -> object:
    ctx.rm_omap(_LOCK_KEY % inp["name"])
    return {}


@register_class("lock", "info")
def _cls_lock_info(ctx: ClsContext, inp) -> object:
    state = unpack(ctx.omap_get(_LOCK_KEY % inp["name"]))
    return state or {}


# ------------------------------------------------------------- cls_version
_VER_KEY = "version.v"


@register_class("version", "read")
def _cls_ver_read(ctx: ClsContext, inp) -> object:
    return {"ver": unpack(ctx.omap_get(_VER_KEY)) or 0}


@register_class("version", "set")
def _cls_ver_set(ctx: ClsContext, inp) -> object:
    ctx.set_omap(_VER_KEY, pack(int(inp["ver"])))
    return {}


@register_class("version", "inc")
def _cls_ver_inc(ctx: ClsContext, inp) -> object:
    cur = unpack(ctx.omap_get(_VER_KEY)) or 0
    expect = inp.get("expect")
    if expect is not None and cur != expect:
        raise ClsError(-125, f"version is {cur}, expected {expect}")
    ctx.set_omap(_VER_KEY, pack(cur + 1))
    return {"ver": cur + 1}
