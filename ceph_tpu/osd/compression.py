"""Inline store compression: the at-rest half of the PR's seam.

Reference semantics (BlueStore ``bluestore_compression_*`` +
per-pool ``compression_*`` pool options, src/os/bluestore/BlueStore.cc
_do_write_data compression decision):

- ``none``: never compress;
- ``passive``: compress only whole-object ingest writes (the store's
  hinted path — partial overwrites and extent updates stay raw);
- ``aggressive``: compress every whole-object write (partial
  overwrites still decompress the blob first — extent arithmetic
  happens in raw space — and the next full rewrite re-compresses).

The decision is strictly AT-REST and local to ``_apply_write``:
everything on the wire — client data, EC chunks, recovery pushes,
scrub repair pushes — is RAW bytes.  Each OSD compresses its own
store per the (map-shared) pool policy with a deterministic codec, so
replicas land byte-identical and replica digest compare in scrub
stays meaningful.

Stored-extent metadata rides the attr dict: ``cz`` = algorithm name,
``crl`` = raw (uncompressed) length.  The stored digest ``d`` is
always computed over the STORED bytes, so deep scrub verifies
compressed extents without inflating them; ``len`` keeps logical
(raw) semantics everywhere.

Telemetry (registered zeroed by the daemon): ``compress_blobs`` /
``compress_rejected`` count decisions, ``bluestore_compressed_
original`` / ``bluestore_compressed_allocated`` accumulate raw vs
stored bytes of accepted blobs — allocated/original is the live
compression ratio.
"""

from __future__ import annotations

from ..compress.registry import factory

#: per-pool option names (ec_profile / pool-options dict keys); each
#: falls back to the osd_compression_* config default
POOL_OPTS = ("compression_mode", "compression_algorithm",
             "compression_required_ratio", "compression_min_blob_size")

MODES = ("none", "passive", "aggressive")

#: perf counters the daemon registers zeroed (stable exporter schema)
COUNTERS = ("compress_blobs", "compress_rejected",
            "compress_decompress",
            "bluestore_compressed_original",
            "bluestore_compressed_allocated")


class CompressionPolicy:
    """One pool's resolved compression decision."""

    __slots__ = ("mode", "algorithm", "required_ratio",
                 "min_blob_size", "_codec")

    def __init__(self, mode: str, algorithm: str,
                 required_ratio: float, min_blob_size: int):
        if mode not in MODES:
            raise ValueError(f"bad compression_mode {mode!r}")
        self.mode = mode
        self.algorithm = algorithm
        self.required_ratio = float(required_ratio)
        self.min_blob_size = int(min_blob_size)
        # fail at policy-build time, not in the write path
        self._codec = factory(algorithm) if mode != "none" else None

    @classmethod
    def from_pool(cls, pool, cfg) -> "CompressionPolicy | None":
        """Resolve a pool's policy: pool-option overrides (the
        ec_profile dict carries them as strings, replicated pools'
        pass-through profile included) over the osd_compression_*
        defaults.  Returns None when the resolved mode is none — the
        write path pays one attribute test, nothing else."""
        prof = getattr(pool, "ec_profile", None) or {}
        mode = str(prof.get("compression_mode",
                            cfg.get("osd_compression_mode")))
        if mode == "none":
            return None
        return cls(
            mode,
            str(prof.get("compression_algorithm",
                         cfg.get("osd_compression_algorithm"))),
            float(prof.get("compression_required_ratio",
                           cfg.get("osd_compression_required_ratio"))),
            int(prof.get("compression_min_blob_size",
                         cfg.get("osd_compression_min_blob_size"))))

    def maybe_compress(self, data: bytes, perf=None):
        """(stored_bytes, {"cz", "crl"}) when the blob compresses well
        enough to keep, else None (store raw; reads pay nothing)."""
        n = len(data)
        if n < self.min_blob_size:
            return None
        stored = self._codec.compress(bytes(data))
        if len(stored) > n * self.required_ratio:
            if perf is not None:
                perf.inc("compress_rejected")
            return None
        if perf is not None:
            perf.inc("compress_blobs")
            perf.inc("bluestore_compressed_original", n)
            perf.inc("bluestore_compressed_allocated", len(stored))
        return stored, {"cz": self._codec.name, "crl": n}


def decompress(stored: bytes, algorithm: str, raw_len: int,
               perf=None) -> bytes:
    """Inflate one stored blob back to its raw bytes (bounded by the
    recorded raw length — a corrupt frame cannot balloon)."""
    try:
        raw = factory(str(algorithm)).decompress(bytes(stored),
                                                 max_out=int(raw_len))
    except ValueError:
        raise
    except Exception as e:  # noqa: BLE001 - codec-specific error types
        raise ValueError(f"decompress ({algorithm}) failed: {e!r}")
    if len(raw) != int(raw_len):
        raise ValueError(
            f"decompressed length {len(raw)} != recorded {raw_len}")
    if perf is not None:
        perf.inc("compress_decompress")
    return raw


def validate_pool_opts(profile: dict) -> None:
    """Mon-side pool-option validation (pool create / set): reject a
    profile whose compression options cannot build a policy — a bad
    algorithm name must fail the command, not every OSD's write
    path."""
    mode = str(profile.get("compression_mode", "none"))
    if mode not in MODES:
        raise ValueError(f"compression_mode must be one of {MODES}")
    if mode == "none":
        return
    alg = str(profile.get("compression_algorithm", "czlib"))
    factory(alg)  # raises on unknown plugin
    rr = float(profile.get("compression_required_ratio", 0.875))
    if not 0.0 <= rr <= 1.0:
        raise ValueError("compression_required_ratio must be in [0, 1]")
    mb = int(profile.get("compression_min_blob_size", 4096))
    if mb < 0:
        raise ValueError("compression_min_blob_size must be >= 0")
