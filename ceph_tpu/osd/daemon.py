"""OSD daemon: the data-plane server.

The role of the reference's OSD + PrimaryLogPG + PGBackend stack
(src/osd/OSD.cc op ingress :7690->dequeue :9979; PrimaryLogPG::do_op :2588
/ do_osd_ops :6163; ReplicatedBackend primary-copy 2PC; ECBackend shard
fan-out ECCommon.cc:950-1090; heartbeats OSD.cc:5823; peering/recovery
PeeringState — SURVEY.md §2.5) collapsed into one single-dispatch-thread
daemon per OSD:

- client ops arrive on the messenger dispatch thread and run as
  non-blocking state machines (pending write/read tables keyed by tid —
  the in_progress_ops role of ECCommon);
- replicated pools: primary applies locally, fans MSubWrite to replicas,
  acks the client when all commit (primary-copy 2PC);
- EC pools: primary splits+encodes the stripe through the pool's EC plugin
  (the TPU kernels underneath), fans shard writes, and reads/decodes with
  reconstruction when shards are missing (degraded reads);
- heartbeats ping peers; silence past the grace window produces failure
  reports to the monitor (adaptive grace is monitor-side);
- on map change the primary runs recovery-lite: inventory peers
  (MPGQuery/MPGInfo), push stale/missing whole objects, and rebuild EC
  shards onto spare devices from k survivors.  (Log-based delta recovery
  and rollback generations are the next widening step; versions are
  tracked per object now.)
"""

from __future__ import annotations

import collections
import contextlib
import itertools
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from .. import ec
from ..ec.batcher import ECBatcher
from ..ec.stripe import StripeInfo, plan_write
from ..mon.maps import OSDMap
from ..msg.messages import (MFailureReport, MLeaseRegister, MMapPush,
                            MMonSubscribe,
                            MNotifyAck, MOSDBoot, MOSDOp, MOSDOpReply,
                            MOSDPing, MOSDPingReply, MPGInfo, MPGList,
                            MPGListReply, MPGPull,
                            MOSDPGTemp,
                            MPGPush, MPGQuery, MPGRollback,
                            MRecoveryReserve, MStatsReport,
                            MSubDelta, MSubPartialWrite, MSubRead,
                            MSubReadN, MSubReadReply, MSubReadReplyN,
                            MSubWrite, MSubWriteReply, MWatchNotify,
                            PgId)
from ..utils.reserver import AsyncReserver
from ..msg.messenger import Dispatcher, Messenger, Network, Policy
from ..ops.native import crc32c as native_crc32c
from ..utils.config import Config, default_config
from ..utils.event_log import EventLog
from ..utils.interval import IntervalSet
from ..utils.log import dout
from ..utils.metrics_history import MetricsHistory
from ..utils.perf import CounterType, global_perf
from ..utils.tracked_op import OpTracker
from ..utils.tracer import Tracer
from ..msg.messages import (MScrubMap, MScrubRequest, MScrubShard)
from .objectstore import (CollectionId, NoSuchObject, ObjectId, ObjectStore,
                          StoreError, Transaction)
from ..ec.arena import DeviceArena
from .extent_cache import ECExtentCache, register_read_scaleout_counters
from .intervals import INTERVALS_KEY, Interval, LES_KEY, PastIntervals
from . import compression
from .objops import ObjOpsMixin
from .pglog import PGLOG_OID, LogEntry, PGLog
from .scheduler import (ClassParams, PHASE_NONE, ShardedScheduler,
                        current_service)
from .scrub import FaultInjection, ScrubMixin
from .snaps import SnapMixin, split_vname, to_oid, vname, vname_of

EIO, ENOENT, ESTALE, EAGAIN, EINVAL, EACCES = -5, -2, -116, -11, -22, -13


@dataclass
class _PendingWrite:
    client: str
    client_tid: int
    acks_needed: int
    version: int
    failed: int = 0
    retry: int = 0  # version-conflict sub-op refusals (client retries)
    lock_key: tuple | None = None  # per-object write lock to release
    span: object = None  # op span closed when the client reply leaves
    qphase: int = 0  # mclock phase served under (rides the reply)
    pq_ctx: object = None  # perf-query booking (async reply drains)
    stamp: float = field(default_factory=time.time)


@dataclass
class _PendingRead:
    client: str | None
    client_tid: int
    pool: int
    oid: str
    total_shards: int
    chunks: dict = field(default_factory=dict)  # shard -> np.uint8 array
    attrs: dict = field(default_factory=dict)   # merged shard attrs (len/v)
    shard_vers: dict = field(default_factory=dict)  # shard -> version attr
    shard_attrs: dict = field(default_factory=dict)  # shard -> its attrs
    omaps: dict = field(default_factory=dict)  # shard -> replicated omap
    replies: int = 0
    offset: int = 0
    length: int = 0
    row_base: int = 0      # ro byte addr of the first row covered (range
    row_len: int = 0       # reads); row_len = shard-stream bytes per shard
    stat_only: bool = False  # reply with the object length, not data
    # recovery reads carry a completion callback instead of a client
    on_done: object = None
    # sub-chunk repair reads (CLAY MSR) need EVERY helper's slices, not
    # just k chunks: completion waits for all replies
    want_all: bool = False
    span: object = None    # op span (traced reads): decode stage parent
    qphase: int = 0  # mclock phase served under (rides the reply)
    pq_ctx: object = None  # perf-query booking (async reply drains)
    # balanced (non-primary) serve: a torn/no-agreed-k-set outcome
    # bounces ESTALE back to the client (re-target the primary) instead
    # of the primary path's requery + EAGAIN
    balanced: bool = False
    # object-write sequence at fan-out (the PR-5 read barrier): the
    # hot-tier admission fence — bytes fetched before a write landed
    # must never be admitted as current
    wmarker: int = 0
    stamp: float = field(default_factory=time.time)


class _SpanConn:
    """Send-handle that closes the op's span when the client reply
    goes out (whatever async path produced it)."""

    def __init__(self, conn, span):
        self._conn = conn
        self._span = span

    def send(self, msg) -> bool:
        if isinstance(msg, MOSDOpReply):
            self._span.tag("result", msg.result)
            self._span.finish()
        return self._conn.send(msg)


class _PhaseConn:
    """Send-handle that stamps the mclock service phase onto the
    client reply (the dmclock feedback channel: qphase tells the
    tenant's ServiceTracker whether this op consumed reservation or
    proportional share).  Wraps once at dispatch so every reply path —
    including async EC ack drains on other threads — carries it."""

    def __init__(self, conn, phase: int):
        self._conn = conn
        self._phase = phase

    def send(self, msg) -> bool:
        if isinstance(msg, MOSDOpReply) and not msg.qphase:
            msg.qphase = self._phase
        return self._conn.send(msg)


class _PerfQueryCtx:
    """One client op's perf-query attribution record, shared between
    the wrapped conn (direct ``conn.send`` replies) and the pending
    write/read drains (``_handle_sub_write_reply`` / ``_finish_ec_read``
    reply via ``messenger.send_message`` and never see the wrapped
    conn — the same split the span/qphase stashes exist for).
    ``finish`` is one-shot: whichever reply edge fires books the op,
    the other finds ``_done`` set — no double count however the op
    completes.  Allocated ONLY when queries are active — the unqueried
    dispatch path stays a single ``pq.active`` attribute check with
    zero allocations (the exemplar/tracer discipline, gated by
    bench.py --ec-batch)."""

    __slots__ = ("_pq", "_tenant", "_pool", "_pgid", "_op", "_oid",
                 "_bytes_in", "_t0", "_done")

    def __init__(self, pq, tenant: str, pool: int, pgid,
                 op: str, oid: str, bytes_in: int):
        self._pq = pq
        self._tenant = tenant
        self._pool = pool
        self._pgid = pgid
        self._op = op
        self._oid = oid
        self._bytes_in = bytes_in
        self._t0 = time.perf_counter()
        self._done = False

    def finish(self, bytes_out: int) -> None:
        if self._done:
            return
        self._done = True
        self._pq.observe(
            self._tenant, self._pool, self._pgid, self._op, self._oid,
            self._bytes_in, bytes_out,
            (time.perf_counter() - self._t0) * 1e6)


class _PerfQueryConn:
    """Send-handle that books a client op into the active perf queries
    when its reply goes out over the dispatch conn: the reply edge is
    the one point where latency AND bytes_out are both known.  Async
    drains (which bypass the conn) finish the same one-shot ctx off
    the pending entry instead."""

    __slots__ = ("_conn", "_ctx")

    def __init__(self, conn, ctx: _PerfQueryCtx):
        self._conn = conn
        self._ctx = ctx

    def send(self, msg) -> bool:
        if isinstance(msg, MOSDOpReply):
            self._ctx.finish(len(msg.data))
        return self._conn.send(msg)


class _ClientConn:
    """Send-handle towards a client entity (for re-entrant op paths)."""

    def __init__(self, daemon: "OSDDaemon", client: str):
        self._daemon = daemon
        self._client = client

    def send(self, msg) -> bool:
        return self._daemon.messenger.send_message(self._client, msg)


#: perf counters the sub-read aggregator maintains on the OSD's
#: registry — ALWAYS registered (zeroed) even when read coalescing is
#: off, so `perf dump` and the exporter expose one stable schema
READ_AGG_COUNTERS = ("ec_read_msgs", "ec_read_fetches",
                     "ec_read_coalesced_subreads", "ec_read_dup_hits",
                     "ec_read_union_merges", "ec_read_stale_rejects",
                     "ec_read_flush_window", "ec_read_flush_size",
                     "ec_read_flush_idle",
                     # recovery-class lanes (repair-plane sub-chunk
                     # fetches riding the aggregator): sub-reads
                     # submitted and MSubReadN messages sent, so the
                     # msgs-per-helper drop on a wide storm is a
                     # counter fact, not a code-reading exercise
                     "ec_read_repair_subreads", "ec_read_repair_msgs")
READ_AGG_HISTOGRAMS = ("ec_read_fetches_per_msg",
                       "ec_read_subreads_per_msg")


class _ReadFetch:
    """One wire fetch riding an MSubReadN: a (pgid, oid, shard,
    extents) store read on the peer, possibly shared by several
    pending reads (duplicate collapse / union-range merge)."""

    __slots__ = ("fid", "pgid", "oid", "shard", "extents", "waiters",
                 "tspans", "fspan_id", "stamp", "marker", "klass")

    def __init__(self, fid, pgid, oid, shard, extents, marker=0,
                 klass="client"):
        self.fid = fid
        self.pgid = pgid
        self.oid = oid
        self.shard = shard
        self.klass = klass
        self.extents = extents      # None (whole shard) or merged
        # union: tuple of disjoint sorted (off, len)
        self.waiters: list = []     # [(tid, requested extents|None)]
        self.tspans: list = []      # ec-read-wait spans (traced ops)
        self.fspan_id = 0           # flush span id once sent
        self.stamp = time.time()
        # read barrier: the daemon's object-write sequence observed at
        # creation — a later read may ride this fetch IN FLIGHT only if
        # its object saw no acked write since (read-after-write)
        self.marker = marker


def _merge_extents(a: tuple, b: tuple) -> tuple:
    """Union of two interval sets: overlapping/touching (off, len)
    ranges coalesce, so N small reads of one hot shard object become
    ONE store read covering them all."""
    iv = IntervalSet()
    for off, ln in (*a, *b):
        iv.insert(off, ln)
    return tuple((s, e - s) for s, e in iv)


def _extents_cover(union: tuple | None, want: tuple | None) -> bool:
    """Whether a fetch for `union` can serve a request for `want`
    (whole-shard fetches serve anything; a ranged fetch serves ranges
    fully inside its merged intervals)."""
    if union is None:
        return True
    if want is None:
        return False
    iv = IntervalSet((off, off + ln) for off, ln in union)
    return all(iv.contains(off, ln) for off, ln in want)


def _carve_extents(union: tuple | None, data: bytes,
                   want: tuple | None) -> bytes:
    """Slice one waiter's requested extents out of the fetch's reply
    buffer.  The peer zero-pads every requested slice to its length
    (absent tail bytes of a padded stripe row are zeros), so carving
    from the union buffer is byte-identical to a direct ranged read."""
    if want == union or want is None:
        return data
    parts = []
    if union is None:
        # whole-shard buffer: direct offsets, zero-padded per slice
        for off, ln in want:
            seg = data[off:off + ln]
            if len(seg) < ln:
                # bytes(seg): seg may be a carved memoryview (the rx
                # zero-copy path), which cannot concatenate in place
                seg = bytes(seg) + b"\0" * (ln - len(seg))
            parts.append(seg)
        return b"".join(parts)
    bases = []  # start offset of each union interval in the buffer
    pos = 0
    for io, il in union:
        bases.append(pos)
        pos += il
    for off, ln in want:
        for (io, il), base in zip(union, bases):
            if io <= off and off + ln <= io + il:
                parts.append(data[base + off - io: base + off - io + ln])
                break
        else:  # cannot happen: waiters are merged into the union
            parts.append(b"\0" * ln)
    return b"".join(parts)


class SubReadAggregator:
    """Per-(peer, pg) MSubRead coalescing (the message half of the EC
    read pipeline; same spirit as the ECBatcher's folded launches).

    Concurrent sub-reads headed to the same OSD for the same pg queue
    here for a small window (``ec_read_window_us``) and leave as ONE
    ``MSubReadN``; the peer answers every item in one
    ``MSubReadReplyN``.  Lanes split by pg — not just peer — because
    the vectorized message carries its pgid for the peer's sharded op
    queue: the whole batch executes on that pg's scheduler shard,
    serialized against the pg's write applies exactly like a plain
    ``MSubRead`` (a pg-less message would land on the default shard
    and could read a stripe mid-apply).  Two further
    collapses ride the queue: a read identical to (or covered by) an
    in-flight fetch of the same ``(pgid, oid, shard)`` attaches as a
    waiter instead of refetching (duplicate collapse), and overlapping
    extents for one shard object merge into a union range so N small
    reads of a hot object become one store read.  ``window_us == 0``
    is pass-through — the daemon sends plain per-op ``MSubRead``s,
    bit-identical to the unbatched path.

    Unlike the ECBatcher no submitter blocks: the fan-out is already
    async (replies route through ``_on_shard_read``), so flushing is
    driven by a per-peer one-shot timer (armed by the first queued
    fetch) or a size threshold (``ec_read_max_items``).

    Tracing: a traced op's sub-reads get ``ec-read-wait`` spans
    (queued -> flushed, ``flush_span``/``flush_reason``/``dup``
    cross-tags) and each flush ONE shared ``ec-read-flush`` span —
    the same fan-in reconstruction contract as the batcher's
    ``ec-batch-wait``/``ec-flush`` pair."""

    def __init__(self, daemon: "OSDDaemon", *, window_us: float = 150.0,
                 max_items: int = 64, perf=None):
        self._daemon = daemon
        self.window_us = float(window_us)
        self.max_items = int(max_items)
        self._perf = perf
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._fids = itertools.count(1)
        # lane = (peer, pgid): one MSubReadN never mixes pgs
        self._queued: dict[tuple, list[_ReadFetch]] = {}
        self._qindex: dict[tuple, _ReadFetch] = {}
        # lane -> monotonic flush deadline, drained by ONE persistent
        # flusher thread (started lazily on the first submit): a
        # threading.Timer per lane-window costs a thread spawn per
        # flush, which on a loaded box dwarfs the window itself
        self._deadlines: dict[tuple, float] = {}
        self._flusher: threading.Thread | None = None
        self._inflight: dict[int, _ReadFetch] = {}
        self._inflight_keys: dict[tuple, list[_ReadFetch]] = {}
        # persistent completion pool (lazily created): multi-delivery
        # replies fan their completions here so same-signature decodes
        # coalesce in the ECBatcher — a thread spawn per completion
        # costs more than the window on a loaded box
        self._pool = None
        self._stopped = False

    @staticmethod
    def _key(peer, pgid, oid, shard, whole: bool,
             klass: str = "client") -> tuple:
        return (peer, pgid, oid, shard, whole, klass)

    def _inc(self, name: str, n: int = 1) -> None:
        if self._perf is not None:
            self._perf.inc(name, n)

    # ------------------------------------------------------------ submit
    def submit(self, peer: str, tid: int, pgid, oid: str, shard: int,
               extents: list | None, trace: tuple | None = None,
               klass: str = "client") -> None:
        """Queue one sub-read for `peer`; the reply reaches the
        daemon's _on_shard_read exactly as a plain MSubReadReply
        would.  ``klass`` splits lanes (and rides the MSubReadN) so a
        recovery storm's repair-plane fetches coalesce per helper yet
        queue under the peer's recovery reservation — client and
        recovery reads never share a wire message."""
        want = (None if extents is None
                else tuple((int(o), int(ln)) for o, ln in extents))
        if klass == "recovery":
            self._inc("ec_read_repair_subreads")
        key = self._key(peer, pgid, oid, shard, want is None, klass)
        tspan = None
        if trace is not None:
            tracer, ctx = trace
            tspan = tracer.start("ec-read-wait", parent=ctx, peer=peer,
                                 shard=shard)
        # a ranged read can also ride a WHOLE-shard fetch of the same
        # shard object (the whole stream covers any slice; recovery
        # whole-reads and client range reads of one hot object meet
        # here), so ranged lookups consult the whole-shard key too
        keys = (key,) if want is None else (
            key, self._key(peer, pgid, oid, shard, True, klass))
        flush_peer = False
        with self._lock:
            if self._stopped:
                if tspan is not None:
                    tspan.tag("stopped", 1)
                    tspan.finish()
                return
            # duplicate collapse vs an IN-FLIGHT fetch: the wire read
            # is already on its way — ride its reply.  Read barrier:
            # the fetch may predate an acked write (its reply could
            # carry pre-write bytes for ALL k shards and pass version
            # agreement), so a read only rides if the object saw no
            # acked write since the fetch was created — otherwise it
            # pays for a fresh wire fetch, exactly like the per-op path
            for k in keys:
                for f in self._inflight_keys.get(k, ()):
                    if not _extents_cover(f.extents, want):
                        continue
                    if self._daemon._obj_written_since((pgid, oid),
                                                      f.marker):
                        self._inc("ec_read_stale_rejects")
                        continue
                    f.waiters.append((tid, want))
                    self._inc("ec_read_dup_hits")
                    if tspan is not None:
                        tspan.tag("dup", 1)
                        if f.fspan_id:
                            tspan.tag("flush_span", f.fspan_id)
                        tspan.finish()
                    return
            f = None
            for k in keys:
                f = self._qindex.get(k)
                if f is not None:
                    break
            if f is not None:
                # queued fetch for the same shard object: collapse —
                # identical/covered extents are a pure dup hit, others
                # merge into a union range (one store read on the peer)
                if want is not None and not _extents_cover(f.extents,
                                                           want):
                    f.extents = _merge_extents(f.extents, want)
                    self._inc("ec_read_union_merges")
                else:
                    self._inc("ec_read_dup_hits")
                f.waiters.append((tid, want))
                if tspan is not None:
                    f.tspans.append(tspan)
            else:
                f = _ReadFetch(next(self._fids), pgid, oid, shard, want,
                               marker=self._daemon._obj_write_marker(),
                               klass=klass)
                f.waiters.append((tid, want))
                if tspan is not None:
                    f.tspans.append(tspan)
                lane = (peer, pgid, klass)
                q = self._queued.setdefault(lane, [])
                q.append(f)
                self._qindex[key] = f
                if len(q) >= self.max_items:
                    self._deadlines.pop(lane, None)
                    flush_peer = True
                elif len(q) == 1:
                    self._deadlines[lane] = (time.monotonic()
                                             + self.window_us * 1e-6)
                    if self._flusher is None:
                        self._flusher = threading.Thread(
                            target=self._flush_loop, daemon=True,
                            name=f"ec-read-agg-{self._daemon.name}")
                        self._flusher.start()
                    self._cv.notify_all()
        if flush_peer:
            self._flush((peer, pgid, klass), reason="size")

    def _flush_loop(self) -> None:
        """The single flusher: sleeps to the EARLIEST lane deadline,
        flushes every due lane, repeats.  One thread per aggregator —
        never one per window."""
        while True:
            due = []
            with self._cv:
                while not self._stopped:
                    if not self._deadlines:
                        self._cv.wait()
                        continue
                    now = time.monotonic()
                    due = [ln for ln, d in self._deadlines.items()
                           if d <= now]
                    if due:
                        for ln in due:
                            self._deadlines.pop(ln, None)
                        break
                    self._cv.wait(min(self._deadlines.values()) - now)
                if self._stopped:
                    return
            for lane in due:
                self._flush(lane)

    # ------------------------------------------------------------- flush
    def _flush(self, lane: tuple, reason: str | None = None) -> None:
        peer, pgid, klass = lane
        with self._lock:
            self._deadlines.pop(lane, None)
            fetches = self._queued.pop(lane, [])
            for f in fetches:
                self._qindex.pop(
                    self._key(peer, f.pgid, f.oid, f.shard,
                              f.extents is None, f.klass), None)
                self._inflight[f.fid] = f
                self._inflight_keys.setdefault(
                    self._key(peer, f.pgid, f.oid, f.shard,
                              f.extents is None, f.klass), []).append(f)
        if not fetches:
            return
        n_subreads = sum(len(f.waiters) for f in fetches)
        if reason is None:
            reason = ("window" if len(fetches) > 1 or n_subreads > 1
                      else "idle")
        fspan = None
        tops = [sp for f in fetches for sp in f.tspans]
        if tops:
            lead = tops[0]
            fspan = lead._tracer.start(
                "ec-read-flush", parent=lead.ctx, peer=peer,
                n_items=len(fetches), n_subreads=n_subreads,
                reason=reason)
            for f in fetches:
                f.fspan_id = fspan.span_id
                for sp in f.tspans:
                    sp.tag("flush_span", fspan.span_id)
                    sp.tag("flush_reason", reason)
                    sp.finish()
                f.tspans = []
        self._inc("ec_read_msgs")
        self._inc("ec_read_fetches", len(fetches))
        self._inc("ec_read_coalesced_subreads", n_subreads)
        self._inc(f"ec_read_flush_{reason}")
        if klass == "recovery":
            self._inc("ec_read_repair_msgs")
        if self._perf is not None:
            self._perf.hinc("ec_read_fetches_per_msg", len(fetches))
            self._perf.hinc("ec_read_subreads_per_msg", n_subreads)
        items = [(f.fid, f.oid, f.shard,
                  None if f.extents is None else list(f.extents))
                 for f in fetches]
        try:
            sent = self._daemon.messenger.send_message(
                peer, MSubReadN(items, pgid, klass=klass))
        except Exception:  # noqa: BLE001 - racing daemon shutdown
            sent = False
        if fspan is not None:
            fspan.tag("sent", bool(sent))
            fspan.finish()
        if not sent:
            # peer gone: no reply will ever come — drop the fetches now
            # (the pending reads complete from the surviving shards or
            # expire through the normal sweep, same as a dropped
            # MSubRead)
            with self._lock:
                for f in fetches:
                    self._drop_locked(peer, f)

    def _drop_locked(self, peer: str, f: _ReadFetch) -> None:
        self._inflight.pop(f.fid, None)
        key = self._key(peer, f.pgid, f.oid, f.shard, f.extents is None,
                        f.klass)
        lst = self._inflight_keys.get(key)
        if lst is not None:
            if f in lst:
                lst.remove(f)
            if not lst:
                self._inflight_keys.pop(key, None)

    # ------------------------------------------------------------- reply
    def on_reply(self, peer: str, items: list) -> None:
        """Route one MSubReadReplyN: resolve each fetch, carve every
        waiter's slices out of the union buffer, and deliver through
        the daemon's normal shard-read completion.  When one reply
        completes MANY pending reads their completions run on their
        own threads, so degraded decodes triggered by the same wire
        message coalesce in the ECBatcher instead of serializing
        behind each other's batch windows."""
        resolved = []  # (fetch, shard, result, data, attrs)
        with self._lock:
            for fid, shard, result, data, attrs in items:
                f = self._inflight.get(fid)
                if f is None:
                    continue
                self._drop_locked(peer, f)
                resolved.append((f, shard, result, data, attrs))
        # carve OUTSIDE the lock: the per-waiter slice copies are the
        # expensive part and must not stall concurrent submit()/flush
        # traffic on this OSD (a dropped fetch's waiter list is ours
        # alone once it leaves the in-flight index)
        deliveries = []  # (tid, shard, result, data, attrs)
        for f, shard, result, data, attrs in resolved:
            for tid, want in f.waiters:
                payload = (_carve_extents(f.extents, data, want)
                           if result == 0 else data)
                deliveries.append((tid, shard, result, payload, attrs))
        if len(deliveries) <= 1:
            for d in deliveries:
                self._daemon._on_shard_read(*d)
            return
        # fan the completions out without blocking the dispatch worker
        # (a degraded completion sits out the decode batch window), on
        # persistent pool threads so same-signature decodes triggered
        # by ONE wire message coalesce in the ECBatcher instead of
        # serializing
        pool = self._pool
        if pool is None:
            from concurrent.futures import ThreadPoolExecutor
            with self._lock:
                if self._stopped:
                    pool = None  # shutting down: never recreate
                else:
                    if self._pool is None:
                        self._pool = ThreadPoolExecutor(
                            max_workers=8,
                            thread_name_prefix=(
                                f"ec-read-{self._daemon.name}"))
                    pool = self._pool
        if pool is None:
            for d in deliveries:
                self._daemon._on_shard_read(*d)
            return
        for d in deliveries:
            try:
                pool.submit(self._daemon._on_shard_read, *d)
            except RuntimeError:
                # stop() shut the pool down between our read of
                # self._pool and this submit: deliver inline so no
                # pending read silently hangs until the sweep
                self._daemon._on_shard_read(*d)

    # ---------------------------------------------------------- lifecycle
    def pending(self) -> int:
        with self._lock:
            return (sum(len(q) for q in self._queued.values())
                    + len(self._inflight))

    def sweep(self, now: float, max_age: float) -> None:
        """Heartbeat-thread GC: drop in-flight fetches whose peer died
        after the send (their waiters' pending reads expire through
        the daemon's own sweep; this only frees the fetch state)."""
        with self._lock:
            for fid, f in list(self._inflight.items()):
                if now - f.stamp > max_age:
                    self._inflight.pop(fid, None)
            for key, lst in list(self._inflight_keys.items()):
                lst[:] = [f for f in lst if f.fid in self._inflight]
                if not lst:
                    self._inflight_keys.pop(key, None)

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._deadlines.clear()
            pool, self._pool = self._pool, None
            self._cv.notify_all()
        if pool is not None:
            pool.shutdown(wait=False)


class OSDDaemon(ObjOpsMixin, ScrubMixin, SnapMixin, Dispatcher):
    def __init__(self, osd_id: int, network: Network,
                 mon: str = "mon.0", store: ObjectStore | None = None,
                 cfg: Config | None = None, host: str | None = None,
                 mons: list | None = None, auth=None):
        self.osd_id = osd_id
        # cephx gate (OSD::ms_verify_authorizer + OSDCap enforcement
        # role): a ServiceVerifier for the "osd" service, or None for
        # an authorization-free cluster
        self.auth = auth
        self.name = f"osd.{osd_id}"
        self.host = host or f"host{osd_id}"
        self._mons = list(mons) if mons else [mon]
        self._mon_idx = 0
        self.mon = self._mons[0]
        self.cfg = cfg or default_config()
        self.store = store or ObjectStore.create("memstore")
        # KV metadata tier: fill unset knobs (backend, memtable/cache
        # budgets, background maintenance) from config and land the
        # maintenance telemetry on kv.<daemon> — before mount, which
        # is what opens the KV (a no-op for KV-less backends)
        self.store.configure_kv(self.cfg, name=self.name)
        self.store.mount()
        # async group-commit pipeline (store_sync_commit=on pins the
        # inline path): queue_transaction returns after the in-RAM
        # apply; client/EC/recovery commit replies ride the on_commit
        # continuations (commit_barrier) so op workers never block on
        # a device fsync, and N concurrent writers share one
        # (mclock-only: completion continuations re-enter through the
        # sharded scheduler to keep the per-PG serialization invariant;
        # fifo's inline dispatch has no shard to route them to)
        self._store_async = str(
            self.cfg["store_sync_commit"]).lower() not in (
            "on", "true", "1", "yes") \
            and self.cfg["osd_op_queue"] == "mclock"
        if self._store_async:
            self.store.enable_async(
                name=self.name,
                throttle_bytes=self.cfg["store_throttle_bytes"],
                throttle_ops=self.cfg["store_throttle_ops"],
                window_us=self.cfg["store_batch_window_us"],
                window_min_us=self.cfg["store_batch_window_min_us"],
                window_max_us=self.cfg["store_batch_window_max_us"],
                target_txns=self.cfg["store_batch_target_txns"],
                adaptive=str(self.cfg["store_batch_adaptive"]).lower()
                == "on")
        # fifo op-queue mode executes client ops INLINE on the dispatch
        # thread with no per-PG serialization — it is only safe with
        # exactly one worker (mclock mode re-serializes through the
        # ShardedScheduler, so it gets the full worker count)
        n_workers = (1 if self.cfg["osd_op_queue"] == "fifo"
                     else self.cfg["ms_dispatch_workers"])
        self.messenger = Messenger(
            network, self.name,
            Policy.stateless_server(self.cfg["osd_client_message_cap"]),
            workers=n_workers)
        self.messenger.add_dispatcher(self)
        # dedicated heartbeat endpoint (the hb_front/hb_back messenger
        # role, src/ceph_osd.cc:550-630): liveness probes must never queue
        # behind bulk shard IO on the data dispatch thread
        self.hb_messenger = Messenger(network, f"{self.name}.hb",
                                      Policy.lossless_peer())
        self.hb_messenger.add_dispatcher(self)
        self.osdmap: OSDMap | None = None
        self._tids = itertools.count(1)
        # pending tables are touched by the dispatch thread AND the
        # heartbeat sweep; ownership transfers happen under this lock
        self._pending_lock = threading.Lock()
        self._pending_writes: dict[int, _PendingWrite] = {}
        self._pending_reads: dict[int, _PendingRead] = {}
        self._pg_versions: dict[PgId, int] = {}
        self._ec_codecs: dict[int, ec.ErasureCode] = {}
        self._stripes: dict[int, StripeInfo] = {}
        self._pglogs: dict[PgId, PGLog] = {}
        self._pg_lc: dict[PgId, int] = {}  # last-complete contiguity pt
        # past-intervals peering state (PeeringState.h:1485 PastIntervals
        # + last_epoch_started fence): membership history per PG, durable
        # in the PG meta omap, driving the prior-set query on promotion
        self._past_intervals: dict[PgId, PastIntervals] = {}
        self._pg_les: dict[PgId, int] = {}
        self._peering_epoch: dict[PgId, int] = {}  # epoch of the round
        # non-blocking fence rounds: after recovery drains, the les
        # fence needs one clean round of answers — but routine recovery
        # completion must not re-block client IO, so these rounds drain
        # a shadow waiting set instead of the peering gate
        self._fence_round: dict[PgId, set[int]] = {}
        # epoch the in-flight sub-op was minted under by its primary —
        # per-thread because non-mclock dispatch runs handlers on the
        # connection reader threads concurrently
        self._sub_epoch = threading.local()
        # peering reconciliation: collected peer inventories + log
        # positions this round
        self._peer_invs: dict[PgId, dict[int, dict]] = {}
        self._peer_lcs: dict[PgId, dict[int, int]] = {}
        self._reconcile_at: dict[PgId, float] = {}
        # hot shard extents for the partial-write pipeline
        # (ECExtentCache role): serves the delta path's old-byte reads,
        # the rmw row reads and hot-object client reads.  The attached
        # DeviceArena is the device half of the stripe plane: runs a
        # jax-pool read feeds back into a folded launch stay HBM-
        # resident under ec_arena_max_bytes instead of re-staging per
        # op (the BENCH_SWEEP staging wall), and every invalidation
        # path below evicts the device copy with the host one
        self._ec_arena = DeviceArena(self.cfg["ec_arena_max_bytes"])
        self._ec_cache = ECExtentCache(
            arena=self._ec_arena,
            on_evict=lambda: self.perf.inc("ec_read_tier_evict"))
        # hot-read tier admission state (zipf-aware second-hit
        # promotion): an object's first read only RECORDS it here; the
        # second read within the LRU window admits its shards into
        # _ec_cache so later reads assemble from cache/HBM.  Bounded
        # LRU — a scan workload churns through without admitting.
        self._tier_seen: collections.OrderedDict = collections.OrderedDict()
        self._tier_lock = threading.Lock()
        # read-lease state (this OSD as the GRANTING server, primary or
        # balanced holder): per-object read-rate EWMA drives the grant
        # decision; _lease_grants tracks outstanding grants so a write
        # can fan "_lease" revokes.  On a balanced holder the grant is
        # also registered at the primary (MLeaseRegister) — the primary
        # orders writes, so it must know every grant.
        self._lease_lock = threading.Lock()
        self._read_ewma: collections.OrderedDict = collections.OrderedDict()
        self._lease_grants: dict[tuple, dict[str, float]] = {}
        # (pgid, oid) -> count of sub-writes currently being applied on
        # THIS shard holder (guarded by _wbar_lock): the hot-tier
        # admission fence for balanced holders, where the primary-only
        # _obj_locks registry can't see writes in flight
        self._subw_inflight: dict[tuple, int] = {}
        self._hb_last: dict[int, float] = {}
        self._last_map = time.time()  # osd_beacon staleness clock
        self._hb_thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._tombstones: dict[PgId, dict[str, int]] = {}
        # peering-lite state (PeeringState FSM role): PGs whose primary is
        # waiting for member inventories block IO with EAGAIN, and objects
        # the primary knows it is behind on stay blocked until pulled
        self._peering: dict[PgId, set[int]] = {}
        self._stale_objects: dict[PgId, dict[str, int]] = {}
        # epoch whose FULL application (collections ensured, PGs
        # split/merged) has completed — self.osdmap.epoch moves at the
        # START of _handle_map, and peering answers must not race the
        # split/merge window in between
        self._applied_epoch = 0
        # freshly-split PGs (parents and children): their members share
        # the parent's last-complete, so the LEAN peering path would
        # skip the inventory exchange that redistributes shards — force
        # full inventories until one round closes clean
        self._split_fresh: set[PgId] = set()
        # per-object write serialization for multi-phase EC ops (the obc
        # lock / ECExtentCache ordering role): queued thunks per key
        self._obj_locks: dict[tuple, object] = {}
        # read barrier for the sub-read aggregator's in-flight dup
        # collapse: last acked-write sequence per (pgid, oid), bounded
        # LRU; _obj_wfloor upper-bounds every evicted entry so a miss
        # stays conservative (see _obj_written_since)
        self._wbar_lock = threading.Lock()
        self._obj_wseq = 0
        self._obj_wlast: collections.OrderedDict = collections.OrderedDict()
        self._obj_wfloor = 0
        self._requery_at: dict[tuple, float] = {}
        self._requery_timers: dict[tuple, object] = {}
        self._pending_scrubs: dict = {}
        # background deep-scrub state per hosted PG (cursor persists in
        # the PG's scrub meta object; this is only the pacing side)
        self._scrub_auto: dict = {}
        # recovery reservations + initiation throttle (AsyncReserver /
        # osd_max_backfills / osd_recovery_max_active roles): bulk
        # recovery data movement queues behind a per-PG local
        # reservation, a per-(PG,target) remote grant, and a bounded
        # in-flight op count with optional sleep pacing
        self._local_reserver = AsyncReserver(self.cfg["osd_max_backfills"])
        self._remote_reserver = AsyncReserver(self.cfg["osd_max_backfills"])
        self._local_waiting: dict[PgId, list] = {}
        self._remote_waiting: dict[tuple, list] = {}
        self._remote_held: set = set()
        self._remote_pending_at: dict[tuple, float] = {}
        self._recovery_q: collections.deque = collections.deque()
        self._recovery_inflight = 0
        self._recovery_pg_ops: dict[PgId, int] = {}
        self.inject = FaultInjection()
        # slow-op complaint threshold + historic ring are operator
        # knobs (the reference's osd_op_complaint_time /
        # osd_op_history_size), not hardcoded tracker defaults.  The
        # on_slow hook is the flight recorder: an op crossing the
        # complaint time (at finish or mid-flight via the tick sweep)
        # journals a slow_op cluster event after its trace — sampled
        # or retroactively promoted — is already retained.
        self.op_tracker = OpTracker(
            history_size=self.cfg["osd_op_history_size"],
            slow_op_seconds=self.cfg["osd_op_complaint_time"],
            on_slow=self._note_slow_op)
        # cluster event journal (LogClient role): PG state transitions,
        # recovery progress, scrub results and batcher regime changes
        # emitted here ride the stats reports to the mon, which merges
        # them into the cluster log (`dump_cluster_log` / event_tool)
        self.events = EventLog(self.name,
                               keep=self.cfg["osd_event_log_size"])
        # per-PG recovery storm accounting feeding the recovery channel
        # (and, through the mon, the mgr progress module): ops scheduled
        # vs completed since the storm opened; guarded by _pending_lock
        self._rec_progress: dict[PgId, dict] = {}
        self._init_objops()
        self._init_snaps()
        self._handlers = {
            MScrubRequest: self._handle_scrub_request,
            MScrubShard: self._handle_scrub_shard,
            MScrubMap: self._handle_scrub_map,
            MMapPush: self._handle_map,
            MOSDOp: self._handle_client_op,
            MSubWrite: self._handle_sub_write,
            MSubPartialWrite: self._handle_sub_partial_write,
            MSubDelta: self._handle_sub_delta,
            MSubWriteReply: self._handle_sub_write_reply,
            MSubRead: self._handle_sub_read,
            MSubReadN: self._handle_sub_read_n,
            MSubReadReply: self._handle_sub_read_reply,
            MSubReadReplyN: self._handle_sub_read_reply_n,
            MPGList: self._handle_pg_list,
            MOSDPing: self._handle_ping,
            MOSDPingReply: self._handle_ping_reply,
            MPGQuery: self._handle_pg_query,
            MPGInfo: self._handle_pg_info,
            MPGPull: self._handle_pg_pull,
            MPGPush: self._handle_pg_push,
            MPGRollback: self._handle_pg_rollback,
            MRecoveryReserve: self._handle_recovery_reserve,
            MNotifyAck: self._handle_notify_ack,
            MLeaseRegister: self._handle_lease_register,
        }
        self.perf = global_perf().create(self.name)
        # head-sampled distributed tracing: trace_sample_rate draws the
        # root decision (config-LIVE via the observer — `config set`
        # over the admin socket retunes a running daemon), and the
        # trace_sampled/trace_dropped/trace_leaked counters land on
        # this registry so the exporter and metrics history see them
        self.tracer = Tracer(self.name,
                             sample_rate=self.cfg["trace_sample_rate"],
                             perf=self.perf)
        self.cfg.observe("trace_sample_rate",
                         lambda _n, v: self.tracer.set_sample_rate(v))
        # recovery-storm root spans (per-PG, opened at storm start,
        # finished at recovery_done) — guarded by _pending_lock
        self._rec_spans: dict[PgId, object] = {}
        # metrics history: periodic snapshots of this daemon's perf
        # registries (its own + its messengers'), sampled on the
        # heartbeat tick and shipped inside the stats reports for the
        # mon to merge (utils/metrics_history.py)
        self.metrics_history = MetricsHistory(
            keep=self.cfg["metrics_history_keep"],
            downsample_age=self.cfg["metrics_history_downsample_age"])
        self._metrics_sampled_at = 0.0
        # dynamic perf queries (telemetry/perf_query): the attribution
        # accumulator bank on the client-op dispatch path, converged
        # from the OSDMap's perf_queries tail; snapshots ride the
        # stats reports for the mon to merge
        from ..telemetry.perf_query import PerfQuerySet
        self.perf_queries = PerfQuerySet()
        # admin-socket directory for cross-daemon trace collection
        # (the PR-7 shared resolver); set by the harness / osd_main
        # when admin sockets exist
        self.asok_dir: str | None = None
        self.perf.add_many(["op_w", "op_r", "op_rw_bytes", "subop_w",
                            "subop_r", "recovery_push", "recovery_delta",
                            "rollbacks", "failure_reports",
                            "scrubs", "scrub_errors", "ec_cache_hit",
                            "ec_cache_miss", "ec_read_cache_hit",
                            "ec_rmw_cache_serves", "map_inc", "map_full",
                            "snap_trims",
                            # repair-bandwidth accounting: bytes fetched
                            # over the wire to rebuild shards vs bytes
                            # of shard actually rebuilt — the repair-
                            # bytes-per-lost-byte ratio per daemon —
                            # plus how rebuilds were served (narrow
                            # locality set / sub-chunk ranges / whole-
                            # shard wide) and narrow attempts that had
                            # to retry wide
                            "recovery_fetch_bytes",
                            "recovery_rebuilt_bytes",
                            "recovery_narrow_rebuilds",
                            "recovery_subchunk_rebuilds",
                            "recovery_wide_retries",
                            # continuous folded deep scrub (osd/scrub.py
                            # auto-scrub scheduler + the ECBatcher
                            # verify op kind)
                            "scrub_verified_bytes",
                            "scrub_verify_launches",
                            "scrub_mismatches",
                            "scrub_digest_missing",
                            "scrub_auto_chunks"])
        # inline store compression decision/ratio telemetry
        self.perf.add_many(compression.COUNTERS)
        # read scale-out: hot-tier admission telemetry, lease
        # grant/revoke flow, balanced (non-primary) read serving —
        # shared schema with tools/prom_rules.py's rate rules
        register_read_scaleout_counters(self.perf)
        self.perf.add("op_lat", CounterType.TIME)
        # end-to-end client-op latency as a pow2 histogram (the SLO
        # `client_op` signal); sampled ops pin exemplars on buckets.
        # The tracker predates the registry, hence the late bind.
        self.perf.add("op_lat_us", CounterType.HISTOGRAM)
        self.op_tracker.bind_perf(self.perf, "op_lat_us")
        # cross-op EC batching (ec/batcher.py): concurrent stripe
        # encodes/decodes sharing a (matrix, k, m) signature coalesce
        # into ONE folded kernel launch within a small window; engaged
        # per codec by _ec_batch_on (jax backend only by default), with
        # the window self-sizing from the observed ops-per-launch when
        # ec_batch_adaptive is on and the folded launch fanning across
        # the device mesh per the codec's ec_shard resolution.  The
        # batcher registers its launch/flush/shard counters on this
        # OSD's perf registry — zeroed even when batching is off, so
        # `perf dump` and the exporter expose one stable schema.
        self._ec_batcher = ECBatcher(
            window_us=self.cfg["ec_batch_window_us"],
            max_bytes=self.cfg["ec_batch_max_bytes"],
            adaptive=self.cfg["ec_batch_adaptive"] == "on",
            target_ops=self.cfg["ec_batch_target_ops"],
            window_min_us=self.cfg["ec_batch_window_min_us"],
            window_max_us=self.cfg["ec_batch_window_max_us"],
            perf=self.perf, events=self.events)
        # sub-read aggregator (the message half of the EC read
        # pipeline): concurrent MSubReads headed to one peer coalesce
        # into MSubReadN wire messages within ec_read_window_us, with
        # duplicate in-flight fetches collapsed and overlapping hot-
        # object extents merged into union ranges.  Engaged per pool by
        # _ec_read_coalesce_on; counters registered zeroed regardless,
        # one stable perf/exporter schema.
        self.perf.add_many(READ_AGG_COUNTERS)
        for h in READ_AGG_HISTOGRAMS:
            self.perf.add(h, CounterType.HISTOGRAM)
        self._read_agg = SubReadAggregator(
            self, window_us=self.cfg["ec_read_window_us"],
            max_items=self.cfg["ec_read_max_items"], perf=self.perf)
        # op scheduler (OpScheduler/mClockScheduler role): the messenger
        # thread classifies+enqueues; ONE dequeue worker executes
        # handlers, preserving single-threaded handler semantics while
        # recovery/scrub traffic is QoS-shaped against client ops
        # peering traffic (MPGQuery/MPGInfo/MPGRollback) is deliberately
        # NOT background: client IO blocks on peering completing, so
        # throttling it would be a priority inversion (the reference
        # serves peering at immediate priority).  Recovery QoS shapes
        # the BULK payload movement: pushes and pulls.
        # end-to-end class tagging: client sub-reads queue as client
        # work on the serving peer, recovery shard fetches (MSubRead
        # klass="recovery") and pushes/pulls as recovery — so a rebuild
        # storm's READS are shaped by the same knobs as its pushes.
        # Sub-writes and replies stay system: they complete client ops
        # already admitted under the client class, and double-queueing
        # the commit path behind a limit would just inflate latency.
        self._op_classes = {
            MOSDOp: "client",
            MSubRead: "client", MSubReadN: "client",
            MPGPush: "recovery", MPGPull: "recovery",
            MScrubRequest: "scrub", MScrubShard: "scrub",
            MScrubMap: "scrub",
        }
        self._use_mclock = self.cfg["osd_op_queue"] == "mclock"
        # always constructed (zeroed QoS counter schema even under
        # fifo); per-class served/dropped/depth/qwait land on self.perf.
        # Tenant profiles arrive with the OSDMap (we have none at
        # construction); unknown tenants ride the default profile and
        # counter cardinality is LRU-bounded by osd_qos_max_tenants.
        self.scheduler = ShardedScheduler(
            self._run_scheduled, self._mclock_params(),
            shards=self.cfg["osd_op_num_shards"],
            name=f"mclock-{self.name}", perf=self.perf,
            max_tenants=self.cfg["osd_qos_max_tenants"])

    def _mclock_params(self) -> dict[str, ClassParams]:
        """Current (R, W, L) per QoS class from config — built at
        construction and re-read by the `reset_mclock` verb so a
        reservation sweep can retune a LIVE daemon."""
        return {
            "client": ClassParams(self.cfg["osd_mclock_client_res"],
                                  self.cfg["osd_mclock_client_wgt"],
                                  self.cfg["osd_mclock_client_lim"]),
            "recovery": ClassParams(
                self.cfg["osd_mclock_recovery_res"],
                self.cfg["osd_mclock_recovery_wgt"],
                self.cfg["osd_mclock_recovery_lim"]),
            "scrub": ClassParams(self.cfg["osd_mclock_scrub_res"],
                                 self.cfg["osd_mclock_scrub_wgt"],
                                 self.cfg["osd_mclock_scrub_lim"]),
            # system (maps, sub-ops, replies): effectively unthrottled
            "system": ClassParams(1e9, 1e6, 0.0),
        }

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        if self._use_mclock:
            self.scheduler.start()
        self.messenger.start()
        self.hb_messenger.start()
        net = self.messenger.network
        self.messenger.send_message(
            self.mon,
            MOSDBoot(self.osd_id, self.host, net.addr_of(self.name),
                     hb_addr=net.addr_of(self.hb_messenger.name)))
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, name=f"hb-{self.name}", daemon=True)
        self._hb_thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._pending_lock:
            timers = list(self._requery_timers.values())
            self._requery_timers.clear()
        for t in timers:
            t.cancel()  # a dead daemon must not keep querying peers
        self._read_agg.stop()
        # drain the store commit pipeline: queued acks fire (or are
        # dropped by the dead messenger) before the sockets close, and
        # the per-store registry leaves the global collection with us
        if self._store_async:
            self.store.disable_async()
        self.messenger.shutdown()
        self.hb_messenger.shutdown()
        if self._use_mclock:
            self.scheduler.shutdown()
        # leave the global collection like the messengers and KV tier
        # do: a later daemon reusing this name (same-process restart,
        # or the next test cluster) must start from zeroed counters,
        # not inherit this incarnation's trace_sampled/op counts
        global_perf().remove(self.name)

    # -------------------------------------------------- admin socket verbs
    def admin_command(self, cmd: str, **kw):
        """Per-daemon operator commands (the AdminSocket capability,
        src/common/admin_socket.cc: perf dump, dump_ops_in_flight, ...)."""
        if cmd == "perf dump":
            return self.perf.dump()
        if cmd == "dump_ops_in_flight":
            return self.op_tracker.dump_ops_in_flight()
        if cmd == "dump_tracing":
            tid = kw.get("trace_id")
            return self.tracer.dump(int(tid) if tid is not None
                                    else None)
        if cmd == "dump_historic_ops":
            return self.op_tracker.dump_historic_ops()
        if cmd == "dump_slow_ops":
            return self.op_tracker.slow_ops()
        if cmd == "dump_historic_slow_ops":
            # the flight-recorder face: each traced entry carries its
            # full merged trace — local ring + every peer daemon's via
            # the shared admin-socket resolver — so "what did this
            # slow op actually do" is answerable after the fact.
            # `max` tail-caps the entries; peers are queried ONCE for
            # the whole trace-id set (a slow-op storm must not turn
            # this verb into entries x peers serial round-trips)
            entries = [dict(d)
                       for d in self.op_tracker.dump_historic_slow_ops()]
            cap = int(kw.get("max", 0) or 0)
            if cap and len(entries) > cap:
                entries = entries[-cap:]
            if kw.get("traces", True):
                tids = {int(d["trace_id"]) for d in entries
                        if d.get("trace_id")}
                index = self._collect_traces(tids) if tids else {}
                for d in entries:
                    tid = d.get("trace_id")
                    if tid:
                        d["trace"] = index.get(int(tid), [])
            return entries
        if cmd == "dump_metrics_history":
            return self.metrics_history.dump(
                registry=kw.get("registry"),
                max_samples=int(kw.get("max", 0) or 0))
        if cmd == "metrics_query":
            return self.metrics_history.query(
                kw.get("registry") or self.name, kw["counter"],
                since_s=float(kw.get("since_s", 60.0)),
                until_s=float(kw.get("until_s", 0.0)),
                start_ts=(float(kw["start_ts"])
                          if kw.get("start_ts") is not None else None),
                end_ts=(float(kw["end_ts"])
                        if kw.get("end_ts") is not None else None))
        if cmd == "dump_kernel_profile":
            from ..utils.perf import kernel_profiler
            return kernel_profiler().dump()
        if cmd == "dump_perf_queries":
            # the dynamic perf-query accumulator bank: active specs +
            # this daemon's cumulative rows (the mon's merged view is
            # `perf query report`)
            return self.perf_queries.dump()
        if cmd == "dump_events":
            return self.events.recent(
                n=int(kw["max"]) if kw.get("max") else None,
                channel=kw.get("channel"))
        if cmd == "dump_messenger":
            return {"data": self.messenger.dump_state(),
                    "hb": self.hb_messenger.dump_state()}
        if cmd == "dump_kv_stats":
            # the KV metadata tier's maintenance face (memtable seal
            # depth, level shape, stall/cache tallies); None-shaped for
            # backends without one (memstore/filestore)
            return {"store": type(self.store).__name__.lower(),
                    "kv": self.store.kv_stats()}
        if cmd == "config show":
            return self.cfg.dump()
        if cmd == "dump_op_queue":
            return {"mode": "mclock" if self._use_mclock else "fifo",
                    "shards": len(self.scheduler.shards),
                    "depth": self.scheduler.queue_depth(),
                    "depths": self.scheduler.queue_depths(),
                    "served": dict(self.scheduler.served),
                    "dropped": dict(self.scheduler.dropped),
                    "tenants": self.scheduler.tenant_depths(),
                    "tenant_served":
                        dict(self.scheduler.tenant_served)}
        if cmd == "reset_mclock":
            # re-read osd_mclock_* from config and retune the LIVE
            # scheduler (the reservation-sweep knob AND the adaptive
            # controller's actuator: `config set` the new values, then
            # this verb applies them without a restart); the tenant
            # profile book re-pushes from the current map too
            params = self._mclock_params()
            for klass, p in params.items():
                self.scheduler.set_params(klass, p)
            if self.osdmap is not None:
                from ..qos.profiles import params_from_map
                self.scheduler.set_tenant_profiles(params_from_map(
                    getattr(self.osdmap, "qos_profiles", {})))
            return {"applied": {k: {"reservation": p.reservation,
                                    "weight": p.weight,
                                    "limit": p.limit}
                                for k, p in params.items()}}
        if cmd == "config set":
            self.cfg.set(kw["name"], kw["value"])
            return {"success": True}
        if cmd == "status":
            return {"osd": self.osd_id,
                    "epoch": self.osdmap.epoch if self.osdmap else 0,
                    "num_pgs": sum(1 for _ in self._pools_pgs_for_me()),
                    "pending_writes": len(self._pending_writes),
                    "pending_reads": len(self._pending_reads)}
        raise ValueError(f"unknown admin command {cmd!r}")

    # ------------------------------------------------------------- dispatch
    def ms_dispatch(self, conn, msg) -> bool:
        handler = self._handlers.get(type(msg))
        if handler is None:
            return False
        # heartbeats stay inline on their own messenger thread: liveness
        # must never queue behind the op scheduler
        if not self._use_mclock or isinstance(msg, (MOSDPing,
                                                    MOSDPingReply)):
            self._sub_epoch.v = 0  # fresh epoch pin per dispatched op
            handler(conn, msg)
            return True
        # a message-carried class wins (recovery-tagged MSubReads);
        # the static table covers everything else
        klass = getattr(msg, "klass", None) \
            or self._op_classes.get(type(msg), "system")
        if klass not in ("client", "recovery", "scrub", "system"):
            klass = "system"  # never KeyError on a peer's future tag
        force = False
        if klass == "system" and isinstance(
                msg, (MSubWrite, MSubPartialWrite, MSubDelta)) \
                and getattr(msg, "tenant", ""):
            # tenant-tagged replication sub-ops: the shard OSD queues
            # the apply under the originating op's tenant so replica-
            # side load is shaped like the primary's.  force — the
            # commit path has no retry; a QUEUE_CAP drop would wedge
            # the primary's pending write forever.
            klass = "client"
            force = True
        # tenant-tagged client ops land in per-tenant dmclock
        # sub-queues; the shipped (delta, rho) pair advances the
        # tenant's clocks multi-server-correctly (qos/dmclock.py)
        tenant = getattr(msg, "tenant", "") if klass == "client" else ""
        tags = (getattr(msg, "qdelta", 0),
                getattr(msg, "qrho", 0)) if tenant else None
        # sampled-trace ops stamp their trace_id on the queue-wait
        # entry so the mclock_qwait_us_* bucket they land in carries
        # the exemplar
        tr = getattr(msg, "trace", None)
        self.scheduler.enqueue(klass, (handler, conn, msg),
                               key=self._shard_key(msg),
                               tenant=tenant or None, tags=tags,
                               force=force,
                               trace_id=tr[0] if tr else None)
        return True

    def _shard_key(self, msg):
        """Sharded-OpWQ routing key: EVERYTHING about one PG — client
        ops, sub-ops, acks, pushes — executes on one shard, so the
        single-worker ordering every handler was written under still
        holds per PG while distinct PGs run in parallel.  (Object-level
        keys are NOT enough: two objects of one PG would race on the
        unlocked PGLog/lc state, and a sub-op ack could outrun the
        primary's own local apply.)"""
        pgid = getattr(msg, "pgid", None)
        if pgid is not None:
            return (pgid.pool, pgid.seed)
        if isinstance(msg, MOSDOp):
            if self.osdmap is not None and \
                    msg.pool in self.osdmap.pools:
                return (msg.pool,
                        self.osdmap.object_to_pg(msg.pool, msg.oid))
            return (msg.pool, 0)  # no map yet: handler EAGAINs anyway
        return None  # maps/boot/admin: the stable default shard

    def _run_scheduled(self, klass: str, item) -> None:
        handler, conn, msg = item
        self._sub_epoch.v = 0  # fresh epoch pin per dispatched op
        if isinstance(msg, MOSDOp):
            # the scheduler worker published what it is serving just
            # before this call (same thread): remember the phase so
            # the reply can carry it back to the dmclock client
            phase = current_service()[1]
            if phase != PHASE_NONE:
                msg._qos_phase = phase
        handler(conn, msg)

    # ------------------------------------------------------------- mapping
    def _handle_map(self, conn, msg: MMapPush) -> None:
        # ANY push — even a stale/equal epoch answering a beacon
        # re-subscribe — proves the mon link is alive; without this a
        # quiescent cluster's beacons rotate monitors forever
        self._last_map = time.time()
        old = self.osdmap
        from ..mon.maps import apply_map_push
        newmap, request = apply_map_push(old, msg)
        if newmap is None:
            # inc we cannot use: ask for a full map (no map yet — the
            # boot race where our own boot-commit's inc arrives first)
            # or the missing chain (gap)
            if request == "full":
                self.messenger.send_message(
                    self.mon, MMonSubscribe("osdmap"))
            elif request == "chain":
                self.messenger.send_message(
                    self.mon, MMonSubscribe("osdmap",
                                            have_epoch=old.epoch))
            return
        self.perf.inc("map_full" if msg.map_bytes else "map_inc")
        if old is not None and newmap.epoch <= old.epoch:
            return
        self.osdmap = newmap
        self._last_map = time.time()
        # tenant QoS profiles ride the map like pool options: push the
        # committed book into the live schedulers on change (unknown
        # tenants keep falling into the default profile)
        new_profiles = getattr(newmap, "qos_profiles", {})
        if old is None or getattr(old, "qos_profiles",
                                  {}) != new_profiles:
            from ..qos.profiles import params_from_map
            self.scheduler.set_tenant_profiles(
                params_from_map(new_profiles))
        # dynamic perf queries converge the same way: the committed
        # query set rides the map; unchanged specs keep their
        # accumulators counting
        new_queries = getattr(newmap, "perf_queries", {})
        if old is None or getattr(old, "perf_queries",
                                  {}) != new_queries:
            self.perf_queries.set_queries(new_queries)
        # drop cached extents only for CACHED PGs whose membership
        # actually changed (an unrelated epoch bump must not cold the
        # cache, and the check is O(cached PGs), not O(cluster PGs))
        if old is None:
            self._ec_cache.clear()
        else:
            for pgid in self._ec_cache.pgids():
                if pgid.pool not in newmap.pools or \
                        pgid.pool not in old.pools or \
                        pgid.seed >= old.pools[pgid.pool].pg_num:
                    self._ec_cache.invalidate(pgid)
                    continue
                if newmap.pg_to_up_osds(pgid.pool, pgid.seed) != \
                        old.pg_to_up_osds(pgid.pool, pgid.seed):
                    self._ec_cache.invalidate(pgid)
        dout("osd", 5)("%s: map epoch %d", self.name, newmap.epoch)
        # learn peer addresses from the map (wire transports; no-op
        # in-proc) — the OSDMap is the address book, as in the reference
        net = self.messenger.network
        for peer, info in newmap.osds.items():
            if info.addr:
                net.set_addr(f"osd.{peer}", info.addr)
            if info.hb_addr:
                net.set_addr(f"osd.{peer}.hb", info.hb_addr)
        # forget heartbeat stamps for peers that (re)joined: a stale
        # pre-death stamp must not flash a revived daemon back down
        for peer, info in newmap.osds.items():
            was_up = old is not None and old.osds.get(peer) is not None \
                and old.osds[peer].up
            if info.up and not was_up:
                self._hb_last.pop(peer, None)
            if not info.up:
                self._hb_last.pop(peer, None)
        # if the map says I am down but I am alive, re-assert (osd re-boot)
        # if the map says I am down — or does not know me at all (my boot
        # was dropped during a mon election) — re-assert
        me = newmap.osds.get(self.osd_id)
        if (me is None or not me.up) and not self._stop.is_set():
            self.messenger.send_message(
                self.mon,
                MOSDBoot(self.osd_id, self.host, net.addr_of(self.name),
                         hb_addr=net.addr_of(self.hb_messenger.name)))
        self._ensure_collections()
        self._reservation_map_change(newmap)
        if old is None or newmap.epoch > old.epoch:
            self._split_pgs(old, newmap)
            self._merge_pgs(old, newmap)
            self._applied_epoch = newmap.epoch
            self._note_intervals()
            self._start_recovery()
            self._notify_demoted(old)
            self._snap_trim_check()

    def _reservation_map_change(self, newmap: OSDMap) -> None:
        """A recovery target marked down can never grant: fail its
        waiting ops open NOW (the sweep's timeout is the slow path for
        silent deaths the map has not caught yet)."""
        rescued = []
        with self._pending_lock:
            for key in list(self._remote_waiting):
                _pg, target = key
                o = newmap.osds.get(target)
                if o is None or not o.up:
                    self._remote_pending_at.pop(key, None)
                    self._remote_held.add(key)
                    rescued.append((key[0],
                                    self._remote_waiting.pop(key)))
        for pgid, thunks in rescued:
            for t, nb in thunks:
                self._recovery_enqueue(pgid, t, nb)

    def _notify_demoted(self, old: OSDMap | None) -> None:
        """If I hold objects for PGs I am no longer an up member of, tell
        the current primary what I have (the MNotifyRec / past-intervals
        role): my stranded shards can then be migrated, not lost.  Only
        PGs whose membership actually dropped me are scanned."""
        for cid in self.store.list_collections():
            if cid.pool not in self.osdmap.pools:
                continue
            pool = self.osdmap.pools[cid.pool]
            if cid.pg_seed >= pool.pg_num:
                continue
            up = self.osdmap.pg_to_up_osds(cid.pool, cid.pg_seed)
            if self.osd_id in [u for u in up if u is not None]:
                continue
            if old is not None and cid.pool in old.pools \
                    and cid.pg_seed < old.pools[cid.pool].pg_num \
                    and old.pools[cid.pool].pg_num == pool.pg_num:
                # (a just-split child seed did not EXIST in the old map,
                # and across ANY pg_num change a fold/split just moved
                # objects into this collection — in both cases the old
                # up set says nothing about what we now hold, so fall
                # through and notify the primary of our shards.  A
                # merge-target primary may have closed its peering
                # round against PRE-fold answers; this notify is what
                # heals that hole.)
                old_up = old.pg_to_up_osds(cid.pool, cid.pg_seed)
                if self.osd_id not in [u for u in old_up if u is not None]:
                    continue  # was not a member before either: no change
            primary = self._primary_of(up)
            if primary is None or primary == self.osd_id:
                continue
            pgid = PgId(cid.pool, cid.pg_seed)
            inv = self._inventory(pgid)
            if inv:
                ents = self._pglog(pgid).entries()  # one decode
                self.messenger.send_message(
                    f"osd.{primary}",
                    MPGInfo(pgid, self.osd_id, -2, inv,
                            dict(self._tombstones.get(pgid, {})),
                            head_epoch=ents[-1].epoch if ents else 0,
                            log_evs={e.version: e.epoch
                                     for e in ents},
                            les=self._les(pgid)))

    def _pools_pgs_for_me(self):
        """(pool, pg_seed, up_set, my_positions) for PGs mapping to me."""
        if self.osdmap is None:
            return
        for pool_id, pool in self.osdmap.pools.items():
            for seed in range(pool.pg_num):
                up = self.osdmap.pg_to_up_osds(pool_id, seed)
                if self.osd_id in [u for u in up if u is not None]:
                    yield pool_id, seed, up

    def _ensure_collections(self) -> None:
        have = set(self.store.list_collections())
        for pool_id, seed, _up in self._pools_pgs_for_me():
            cid = CollectionId(pool_id, seed)
            if cid not in have:
                tx = Transaction().create_collection(cid)
                self.store.queue_transaction(tx)

    def _primary_of(self, up: list) -> int | None:
        for u in up:
            if u is not None:
                return u
        return None

    # ----------------------------------------------------------- client ops
    # op -> required cap bits (OSDCap semantics: r read, w mutate,
    # x object-class execution)
    _READ_OPS = frozenset({"read", "stat", "omap_get", "list_snaps",
                           "multi_read", "getxattrs"})
    _EXEC_OPS = frozenset({"call"})

    def _auth_denied(self, m: MOSDOp, pool_name: str) -> str | None:
        """Why this op must be refused (None = authorized).  Ticket
        signature/expiry, per-op proof under the ticket's session key,
        then the entity's caps against the pool."""
        import hmac as _hmac

        from ..auth.cephx import op_proof
        vt = self.auth.verify(m.ticket)
        if vt is None:
            return "no/invalid/expired osd ticket"
        want = op_proof(vt.session_key, m.tid, m.pool, m.oid, m.op,
                        m.offset, m.length, m.data)
        if not _hmac.compare_digest(want, m.proof):
            return "bad op proof"
        if m.op in self._READ_OPS:
            need = "r"
        elif m.op in self._EXEC_OPS:
            need = "x"
        else:
            need = "w"
        if not vt.caps.allows(need, pool=pool_name):
            return (f"entity {vt.entity} lacks caps {need!r} on pool "
                    f"{pool_name}")
        return None

    def _handle_client_op(self, conn, m: MOSDOp) -> None:
        if self.osdmap is None or m.pool not in self.osdmap.pools:
            # the client's map may be AHEAD of ours (pool just created,
            # our push still in flight): EAGAIN retries; only a pool
            # unknown at the client's own epoch is truly ENOENT
            my_epoch = self.osdmap.epoch if self.osdmap else 0
            err = EAGAIN if m.epoch > my_epoch else ENOENT
            conn.send(MOSDOpReply(m.tid, err, epoch=my_epoch))
            return
        pool = self.osdmap.pools[m.pool]
        if self.auth is not None:
            why = self._auth_denied(m, pool.name)
            if why is not None:
                dout("osd", 2)("osd.%d: op %s from %s DENIED: %s",
                               self.osd_id, m.op, m.client, why)
                conn.send(MOSDOpReply(m.tid, EACCES,
                                      epoch=self.osdmap.epoch))
                return
        seed = self.osdmap.object_to_pg(m.pool, m.oid)
        up = self.osdmap.pg_to_up_osds(m.pool, seed)
        pgid = PgId(m.pool, seed)
        balanced = False
        if self._primary_of(up) != self.osd_id:
            # balanced reads (pool read_policy=balance): a non-primary
            # shard holder serves plain head reads itself — everything
            # else (writes, snap reads, pools that did not opt in)
            # bounces ESTALE so the client re-targets the primary
            if self._balanced_read_ok(m, pool, up):
                balanced = True
            else:
                conn.send(MOSDOpReply(m.tid, ESTALE,
                                      epoch=self.osdmap.epoch))
                return
        # peering gate: block IO until inventories (and the objects we are
        # known to be behind on) have caught up — read-your-writes safety
        if pgid in self._peering or (
                m.oid in self._stale_objects.get(pgid, ())):
            conn.send(MOSDOpReply(m.tid, EAGAIN, epoch=self.osdmap.epoch))
            return
        if m.op in self._LEASE_REVOKE_OPS:
            # write choke point (primary only — mutations never ride the
            # balanced path): drop + notify every outstanding read lease
            # on the object BEFORE the mutation dispatches, so a leased
            # client's staleness window is revoke-latency, not TTL
            self._lease_revoke(pgid, m.oid)
        if m.trace:
            # distributed span (tracer.h role): the op's span on THIS
            # daemon; closed when the client reply leaves, however many
            # async stages the op spans.  Sub-ops fan out under its ctx.
            span = self.tracer.start(f"osd-op {m.op}", parent=m.trace,
                                     oid=m.oid, pg=str(pgid))
        else:
            # head sampling for context-less ops (a client that does
            # not trace): None at zero cost when the rate is 0; a
            # propagating root with probability trace_sample_rate; or
            # an unsampled local span the flight recorder can promote
            # retroactively if this op turns slow
            span = self.tracer.sample_root(f"osd-op {m.op}", oid=m.oid,
                                           pg=str(pgid))
        # an unsampled span is op-owned (nothing else will close it);
        # sampled spans close when the client reply leaves (_SpanConn)
        own_span = span is not None and not span.sampled
        if span is not None and span.sampled:
            m._span = span
            conn = _SpanConn(conn, span)
        qphase = getattr(m, "_qos_phase", PHASE_NONE)
        if qphase != PHASE_NONE:
            # dmclock feedback: the reply carries the phase this op was
            # served under, whichever async path eventually sends it
            conn = _PhaseConn(conn, qphase)
        if self.perf_queries.active:
            # dynamic perf queries: attribution accumulates at the
            # reply edge; queries-off cost is this one attr check.
            # The ctx ALSO rides the op (m._pq_ctx -> pending entry)
            # so async drains that reply via the messenger book it.
            pq_ctx = _PerfQueryCtx(self.perf_queries, m.tenant,
                                   m.pool, pgid, m.op, m.oid,
                                   len(m.data))
            m._pq_ctx = pq_ctx
            conn = _PerfQueryConn(conn, pq_ctx)
        self.perf.inc("op_rw_bytes", len(m.data))
        with self.op_tracker.create(f"{m.op} {m.oid}", span=span) as op:
            if pool.kind == "ec":
                if m.op in ("write", "write_full"):
                    self.perf.inc("op_w")
                    key = (pgid, m.oid)
                    full = m.op == "write_full"

                    def wthunk(conn=conn, m=m, pgid=pgid, key=key,
                               full=full):
                        up2 = self.osdmap.pg_to_up_osds(
                            pgid.pool, pgid.seed)
                        self._ec_write(conn, m, pgid, up2, full=full,
                                       lock_key=key)

                    self._obj_lock(key, wthunk)
                elif m.op == "read":
                    self.perf.inc("op_r")
                    self._ec_read(conn, m, pgid, up, balanced=balanced)
                elif m.op == "remove":
                    key = (pgid, m.oid)

                    def rthunk(conn=conn, m=m, pgid=pgid, key=key):
                        up2 = self.osdmap.pg_to_up_osds(
                            pgid.pool, pgid.seed)
                        self._ec_remove(conn, m, pgid, up2, lock_key=key)

                    self._obj_lock(key, rthunk)
                elif m.op == "stat":
                    self._stat(conn, m, pgid, shard=0)
                elif m.op in self.EXTENDED_OPS:
                    self._handle_extended_op(conn, m, pgid, up)
                else:
                    conn.send(MOSDOpReply(m.tid, EINVAL,
                                          epoch=self.osdmap.epoch))
            else:
                if m.op in ("write", "write_full"):
                    self.perf.inc("op_w")
                    self._rep_write(conn, m, pgid, up,
                                    full=m.op == "write_full")
                elif m.op == "read":
                    self.perf.inc("op_r")
                    self._rep_read(conn, m, pgid, balanced=balanced)
                elif m.op == "remove":
                    self._rep_remove(conn, m, pgid, up)
                elif m.op == "stat":
                    self._stat(conn, m, pgid, shard=-1)
                elif m.op in self.EXTENDED_OPS:
                    self._handle_extended_op(conn, m, pgid, up)
                else:
                    conn.send(MOSDOpReply(m.tid, EINVAL,
                                          epoch=self.osdmap.epoch))
            op.mark("dispatched")
        if own_span:
            # idempotent; a span promoted mid-dispatch (slow-op
            # retention) closes into the done ring here
            span.finish()

    # -- per-object write serialization ------------------------------------
    def _obj_lock(self, key: tuple, thunk) -> None:
        """Run thunk now if the object is idle, else queue it.  Queue
        state is guarded by _pending_lock because the sweep (heartbeat
        thread) can release locks; thunks run outside the lock."""
        with self._pending_lock:
            q = self._obj_locks.get(key)
            if q is None:
                q = collections.deque()
                self._obj_locks[key] = q
            q.append(thunk)
            run = len(q) == 1
        if run:
            self._run_locked_thunk(key, thunk)

    def _run_locked_thunk(self, key: tuple, thunk) -> None:
        """Run a queued write; a thrown thunk must release the lock or
        every later write to the object wedges behind it forever."""
        self._sub_epoch.v = 0  # fresh epoch pin per deferred op
        try:
            thunk()
        except Exception:
            self._obj_unlock(key)
            raise

    def _obj_unlock(self, key: tuple | None) -> None:
        if key is None:
            return
        nxt = None
        with self._pending_lock:
            q = self._obj_locks.get(key)
            if not q:
                return
            q.popleft()
            if q:
                nxt = q[0]
            else:
                del self._obj_locks[key]
        if nxt:
            self._run_locked_thunk(key, nxt)  # start the next queued write

    # -- read barrier (aggregator in-flight dup collapse) ------------------
    _OBJ_WLAST_CAP = 4096

    def _obj_write_marker(self) -> int:
        """Current object-write sequence (racy read is fine: a stale
        low value only makes _obj_written_since more conservative)."""
        return self._obj_wseq

    def _note_obj_write(self, key: tuple | None) -> None:
        """Record an acked (or torn) write to `key` = (pgid, oid).
        MUST run before the client sees the ack: an aggregator fetch
        created before this point may carry pre-write bytes, so reads
        issued after the ack must not ride it."""
        if key is None:
            return
        with self._wbar_lock:
            self._obj_wseq += 1
            self._obj_wlast[key] = self._obj_wseq
            self._obj_wlast.move_to_end(key)
            while len(self._obj_wlast) > self._OBJ_WLAST_CAP:
                _, seq = self._obj_wlast.popitem(last=False)
                if seq > self._obj_wfloor:
                    self._obj_wfloor = seq

    def _obj_written_since(self, key: tuple, marker: int) -> bool:
        """Whether (pgid, oid) saw an acked write after sequence
        `marker`.  An evicted entry answers via the floor — possibly a
        false positive (rejecting a safe ride), never a false negative
        (4096 distinct objects must be written within one fetch's
        lifetime for the floor to pass a clean fetch's marker)."""
        with self._wbar_lock:
            return self._obj_wlast.get(key, self._obj_wfloor) > marker

    def _next_version(self, pgid: PgId) -> int:
        # reachable from the dispatch thread AND the heartbeat sweep (via
        # _obj_unlock -> queued write thunk): the RMW must be atomic or two
        # writes in one PG can mint the same version
        with self._pending_lock:
            v = self._pg_versions.get(pgid, 0) + 1
            self._pg_versions[pgid] = v
            return v

    def _record_tombstone(self, pgid: PgId, name: str, version: int) -> None:
        """Deletion marker so recovery never resurrects removed objects
        (the role of PGLog delete entries)."""
        ts = self._tombstones.setdefault(pgid, {})
        ts[name] = max(ts.get(name, 0), version)

    # -- replicated pool ---------------------------------------------------
    def _rep_write(self, conn, m: MOSDOp, pgid: PgId, up: list,
                   full: bool = True) -> None:
        # snapshots: clone-on-first-write-after-snap + SnapSet upkeep,
        # staged into the SAME transaction as the write (make_writeable)
        snap_tx, rider = self._snap_prepare(pgid, m)
        version = self._next_version(pgid)
        cid = CollectionId(pgid.pool, pgid.seed)
        existed = self.store.exists(cid, ObjectId(m.oid))
        was_whiteout = existed and self._head_whiteout(cid, m.oid)
        extra_attrs = {"wh": 0} if was_whiteout else {}
        partial = not full and (m.offset > 0 or (
            existed and m.offset + len(m.data) < self._obj_raw_size(
                cid, ObjectId(m.oid))))
        if partial:
            self._apply_partial(pgid, m.oid, -1, [(m.offset, m.data)],
                                version, create_ok=True, pre_tx=snap_tx,
                                extra_attrs=extra_attrs)
            if existed:
                op, payload, off = "write_partial", m.data, m.offset
            else:
                # object just created here: replicas may lack it entirely,
                # so replicate the full (zero-prefixed) content instead of
                # a partial they could not apply (raw: the wire never
                # carries compressed bytes)
                payload, _ = self._read_obj_raw(cid, ObjectId(m.oid))
                op, off = "write", 0
        else:
            op, payload, off = "write", m.data, 0
            self._apply_write(pgid, m.oid, -1, m.data,
                              dict(extra_attrs, v=version,
                                   len=len(m.data)), pre_tx=snap_tx)
        peers = [u for u in up if u is not None and u != self.osd_id]
        tid = next(self._tids)
        if not peers:
            # single-copy pool: the client reply IS the durability ack —
            # it rides the commit pipeline's finisher (inline when sync)
            self.store.commit_barrier(lambda: conn.send(
                MOSDOpReply(m.tid, 0, version=version,
                            epoch=self.osdmap.epoch)))
            return
        # +1 ack for the primary's own store commit (the barrier below)
        self._pending_writes[tid] = _PendingWrite(
            m.client, m.tid, len(peers) + 1, version)
        self._pending_writes[tid].span = getattr(m, '_span', None)
        self._pending_writes[tid].qphase = getattr(m, '_qos_phase', 0)
        self._pending_writes[tid].pq_ctx = getattr(m, '_pq_ctx', None)
        self._local_commit_ack(tid, pgid)
        sub_attrs = dict(extra_attrs)
        if rider is not None:
            sub_attrs["_snap"] = rider
        for peer in peers:
            self.messenger.send_message(
                f"osd.{peer}",
                MSubWrite(tid, pgid, m.oid, -1, version, op, payload,
                          attrs=dict(sub_attrs), offset=off,
                          epoch=self._entry_epoch(),
                          trace=self._tctx(m), tenant=m.tenant))

    def _rep_read(self, conn, m: MOSDOp, pgid: PgId,
                  balanced: bool = False) -> None:
        cid = CollectionId(pgid.pool, pgid.seed)
        try:
            # snapid resolution (find_object_context): head, a clone, or
            # a whiteout'd ENOENT
            target = self._snap_resolve(cid, m.oid, m.snapid)
            if target is None:
                # balanced: this replica may simply not have caught up
                # (recovery lag outside the _stale_objects inventory) —
                # bounce to the primary, whose answer is authoritative,
                # instead of fabricating ENOENT
                err = ESTALE if balanced else ENOENT
                if balanced:
                    self.perf.inc("balanced_read_bounce")
                conn.send(MOSDOpReply(m.tid, err,
                                      epoch=self.osdmap.epoch))
                return
            try:
                data = self._inflate(
                    self.store.read(cid, target).to_bytes(),
                    self.store.getattrs(cid, target))
            except ValueError:
                conn.send(MOSDOpReply(m.tid, EIO,
                                      epoch=self.osdmap.epoch))
                return
            if m.length:
                data = data[m.offset:m.offset + m.length]
            elif m.offset:
                data = data[m.offset:]
            if balanced:
                self.perf.inc("balanced_read_serve")
            lease = self._lease_maybe_grant(pgid, m.oid, m.client,
                                            whole=not m.length
                                            and not m.offset
                                            and not m.snapid)
            conn.send(MOSDOpReply(m.tid, 0, data=data,
                                  epoch=self.osdmap.epoch, lease=lease))
        except NoSuchObject:
            if balanced:
                self.perf.inc("balanced_read_bounce")
                conn.send(MOSDOpReply(m.tid, ESTALE,
                                      epoch=self.osdmap.epoch))
                return
            conn.send(MOSDOpReply(m.tid, ENOENT, epoch=self.osdmap.epoch))

    def _rep_remove(self, conn, m: MOSDOp, pgid: PgId, up: list) -> None:
        cid = CollectionId(pgid.pool, pgid.seed)
        if not self.store.exists(cid, ObjectId(m.oid)) or \
                self._head_whiteout(cid, m.oid):
            conn.send(MOSDOpReply(m.tid, ENOENT, epoch=self.osdmap.epoch))
            return
        # a head with clones (or a live SnapContext that first needs a
        # clone) must leave its SnapSet behind: whiteout, not remove
        snap_tx, rider = self._snap_prepare(pgid, m)
        ss = self._load_ss(cid, m.oid)
        # whiteout only when clones actually exist (or one is being
        # staged right now) — a snapc alone must not leave a permanent
        # zero-clone whiteout behind
        whiteout = bool((ss or {}).get("clones")) or (
            rider is not None and rider.get("clone", -1) >= 0)
        version = self._next_version(pgid)
        if whiteout:
            self._apply_whiteout(pgid, m.oid, version, pre_tx=snap_tx)
            sub_op, sub_attrs = "whiteout", (
                {"_snap": rider} if rider is not None else {})
        else:
            self._apply_remove(pgid, m.oid, -1, version)
            sub_op, sub_attrs = "remove", {}
        peers = [u for u in up if u is not None and u != self.osd_id]
        tid = next(self._tids)
        if not peers:
            self.store.commit_barrier(lambda: conn.send(
                MOSDOpReply(m.tid, 0, version=version,
                            epoch=self.osdmap.epoch)))
            return
        # +1 ack: the local whiteout/remove commit (barrier below)
        self._pending_writes[tid] = _PendingWrite(
            m.client, m.tid, len(peers) + 1, version)
        self._pending_writes[tid].span = getattr(m, '_span', None)
        self._pending_writes[tid].qphase = getattr(m, '_qos_phase', 0)
        self._pending_writes[tid].pq_ctx = getattr(m, '_pq_ctx', None)
        self._local_commit_ack(tid, pgid)
        for peer in peers:
            self.messenger.send_message(
                f"osd.{peer}",
                MSubWrite(tid, pgid, m.oid, -1, version, sub_op,
                          attrs=dict(sub_attrs),
                          epoch=self._entry_epoch(),
                          trace=self._tctx(m), tenant=m.tenant))

    def _stat(self, conn, m: MOSDOp, pgid: PgId, shard: int) -> None:
        cid = CollectionId(pgid.pool, pgid.seed)
        oid = ObjectId(m.oid, shard=shard)
        # EC stat probes local shards first (primary may hold any shard)
        candidates = [oid] if shard < 0 else [
            ObjectId(m.oid, shard=s)
            for s in range(self.osdmap.pools[pgid.pool].size)]
        for cand in candidates:
            try:
                attrs = self.store.getattrs(cid, cand)
            except NoSuchObject:
                continue
            if attrs.get("wh"):
                # whiteout head (any shard): logically deleted — and
                # authoritatively so; never fall through to the remote
                # stat fan (peers hold the same whiteout)
                conn.send(MOSDOpReply(m.tid, ENOENT,
                                      epoch=self.osdmap.epoch))
                return
            size = int(attrs.get("len", 0))
            conn.send(MOSDOpReply(m.tid, 0,
                                  data=size.to_bytes(8, "little"),
                                  epoch=self.osdmap.epoch))
            return
        if shard >= 0:
            # recovery window: this primary holds nothing yet — ask the
            # shard holders (stat must agree with the readable object)
            up = self.osdmap.pg_to_up_osds(pgid.pool, pgid.seed)
            tid = next(self._tids)
            pr = _PendingRead(m.client, m.tid, pgid.pool, m.oid,
                              total_shards=sum(1 for u in up
                                               if u is not None),
                              stat_only=True)
            pr.qphase = getattr(m, '_qos_phase', 0)
            pr.pq_ctx = getattr(m, '_pq_ctx', None)
            self._pending_reads[tid] = pr
            self._fan_shard_reads(tid, pgid, m.oid, up)
            return
        conn.send(MOSDOpReply(m.tid, ENOENT, epoch=self.osdmap.epoch))

    # -- EC pool -----------------------------------------------------------
    def _pool_codec(self, pool_id: int) -> ec.ErasureCode:
        codec = self._ec_codecs.get(pool_id)
        if codec is None:
            pool = self.osdmap.pools[pool_id]
            profile = dict(pool.ec_profile)
            plugin = profile.pop("plugin", self.cfg["ec_plugin"])
            profile.setdefault("backend", self.cfg["ec_backend"])
            # device fan-out for folded batch launches (mesh-sharded
            # flushes); pool ec-profile key 'shard' wins over the option
            profile.setdefault("shard", self.cfg["ec_shard"])
            # kernel realization / per-signature auto-tuning (profile
            # key 'kernel' wins over the ec_kernel option)
            profile.setdefault("kernel", self.cfg["ec_kernel"])
            codec = ec.factory(plugin, profile)
            self._ec_codecs[pool_id] = codec
        return codec

    def _ec_batch_on(self, codec) -> bool:
        """Whether this codec's stripe work routes through the cross-op
        batcher: pool ec-profile key 'batch' wins, then the ec_batch
        option; 'auto' engages on the jax backend only (numpy/native
        launches are cheap CPU calls — coalescing would only add the
        window latency) and only under the sharded mclock scheduler —
        fifo mode runs client ops inline on ONE dispatch thread, so a
        second op can never be in flight to coalesce with and the
        window would be pure added latency."""
        mode = str(codec.profile.get("batch",
                                     self.cfg["ec_batch"])).lower()
        if mode in ("on", "true", "1", "yes"):
            return True
        if mode in ("off", "false", "0", "no"):
            return False
        return (getattr(codec, "_backend", None) == "jax"
                and self._use_mclock)

    def _ec_encode(self, codec, streams, with_csums: bool, m=None):
        """One encode launch for one op — or, when batching is engaged,
        a slot in a folded launch shared with concurrent ops.  Returns
        (parity, csums); csums is None when the codec has no fused path
        and with_csums was not requested.  A traced op (``m`` carries a
        span) wraps the call in an ``ec-encode`` span whose children —
        ``ec-batch-wait`` + the shared ``ec-flush`` — decompose where
        the encode time went (window wait vs launch)."""
        span = getattr(m, "_span", None) if m is not None else None
        if self._ec_batch_on(codec):
            if span is not None:
                with self.tracer.start("ec-encode",
                                       parent=span.ctx) as sp:
                    return self._ec_batcher.encode(
                        codec, streams, with_csums=with_csums,
                        trace=(self.tracer, sp.ctx))
            return self._ec_batcher.encode(codec, streams,
                                           with_csums=with_csums)
        with (self.tracer.start("ec-encode", parent=span.ctx)
              if span is not None else contextlib.nullcontext()):
            if with_csums:
                enc_csum = getattr(codec, "encode_chunks_with_csums",
                                   None)
                if enc_csum is not None:
                    return enc_csum(streams)
            return codec.encode_chunks(streams), None

    def _ec_decode(self, codec, want, chunks, span=None):
        """Decode wanted shards — coalesced with concurrent decodes of
        the same erasure signature when batching is engaged.  ``span``
        (the op's span, when traced) wraps the call in an ``ec-decode``
        span with the same batch-wait/flush decomposition underneath."""
        if self._ec_batch_on(codec):
            if span is not None:
                with self.tracer.start("ec-decode",
                                       parent=span.ctx) as sp:
                    return self._ec_batcher.decode(
                        codec, want, chunks,
                        trace=(self.tracer, sp.ctx))
            return self._ec_batcher.decode(codec, want, chunks)
        with (self.tracer.start("ec-decode", parent=span.ctx)
              if span is not None else contextlib.nullcontext()):
            return codec.decode(want, chunks)

    def _ec_repair(self, codec, lost: int, helpers: dict, L: int,
                   span=None):
        """Sub-chunk MSR repair (CLAY) — coalesced with concurrent
        repairs of the same lost shard when batching is engaged (a
        storm rebuilding one downed OSD's shard across many objects is
        exactly one repair signature)."""
        if self._ec_batch_on(codec):
            if span is not None:
                with self.tracer.start("ec-repair",
                                       parent=span.ctx) as sp:
                    return self._ec_batcher.repair(
                        codec, lost, helpers, L,
                        trace=(self.tracer, sp.ctx))
            return self._ec_batcher.repair(codec, lost, helpers, L)
        with (self.tracer.start("ec-repair", parent=span.ctx)
              if span is not None else contextlib.nullcontext()):
            return codec.repair_chunk(lost, helpers, L)

    def _rec_trace(self, pgid: PgId) -> tuple:
        """Wire trace context of this PG's recovery-storm root span —
        () when the storm was not sampled.  Rides MPGPush/MPGPull so
        peers parent their per-push/pull apply spans under the storm
        root (cross-daemon recovery waterfalls)."""
        with self._pending_lock:
            sp = self._rec_spans.get(pgid)
        return sp.ctx if sp is not None else ()

    # ----------------------------------------------------------- pg log
    def _pglog(self, pgid: PgId) -> PGLog:
        pl = self._pglogs.get(pgid)
        if pl is None:
            pl = PGLog(self.store, CollectionId(pgid.pool, pgid.seed))
            self._pglogs[pgid] = pl
        return pl

    def _lc(self, pgid: PgId) -> int:
        """last-complete: highest version through which this OSD has seen
        EVERY pg mutation gaplessly (the log's authority point)."""
        lc = self._pg_lc.get(pgid)
        if lc is None:
            cid = CollectionId(pgid.pool, pgid.seed)
            try:
                raw = self.store.omap_get(cid, PGLOG_OID).get("_lc")
                lc = int.from_bytes(raw, "little") if raw else 0
            except Exception:  # noqa: BLE001 - no log object yet
                lc = 0
            self._pg_lc[pgid] = lc
        return lc

    def _set_lc(self, pgid: PgId, lc: int,
                tx: Transaction | None = None) -> None:
        self._pg_lc[pgid] = lc
        cid = CollectionId(pgid.pool, pgid.seed)
        own = tx is None
        if own:
            tx = Transaction()
        if not self.store.exists(cid, PGLOG_OID):
            tx.touch(cid, PGLOG_OID)
        tx.omap_setkeys(cid, PGLOG_OID,
                        {"_lc": lc.to_bytes(8, "little")})
        if own:
            self.store.queue_transaction(tx)

    @staticmethod
    def _tctx(m) -> tuple:
        """Trace context for sub-ops of this client op (the ZTracer
        child-span propagation, ECCommon.cc:1046-1051)."""
        span = getattr(m, "_span", None)
        return span.ctx if span is not None else ()

    def _entry_epoch(self) -> int:
        """Epoch to stamp a fresh log entry with: the minting primary's
        epoch when this thread is applying a sub-op (it rode the
        message), else my own current map epoch PINNED on first use —
        one logical op must mint ONE epoch for its local entry and
        every sub-message even if a map push lands on another thread
        mid-fan-out (two stamps for the same version would read as a
        fork next peering round).  The pin is cleared at each dispatch/
        thunk boundary."""
        e = getattr(self._sub_epoch, "v", 0)
        if not e:
            e = self.osdmap.epoch if self.osdmap is not None else 0
            self._sub_epoch.v = e
        return e

    def _log_apply(self, tx: Transaction, pgid: PgId,
                   entry: LogEntry) -> None:
        """Append a log entry in the SAME transaction as its data write
        and advance the contiguity point when versions arrive in order
        (a gap means we missed a mutation: last-complete stays put and
        peering falls back to the inventory exchange)."""
        if entry.epoch == 0:
            entry.epoch = self._entry_epoch()
        pl = self._pglog(pgid)
        pl.append_to(tx, entry)
        pl.trim_to(tx)
        lc = self._lc(pgid)
        if entry.version == lc + 1:
            self._set_lc(pgid, entry.version, tx=tx)

    # -- past intervals + the last-epoch-started fence ---------------------
    def _pi(self, pgid: PgId) -> PastIntervals:
        pi = self._past_intervals.get(pgid)
        if pi is None:
            cid = CollectionId(pgid.pool, pgid.seed)
            try:
                raw = self.store.omap_get(cid, PGLOG_OID).get(
                    INTERVALS_KEY)
                pi = (PastIntervals.decode_bytes(raw) if raw
                      else PastIntervals())
            except Exception:  # noqa: BLE001 - no log object yet
                pi = PastIntervals()
            self._past_intervals[pgid] = pi
        return pi

    def _save_pi(self, pgid: PgId) -> None:
        cid = CollectionId(pgid.pool, pgid.seed)
        tx = Transaction()
        if not self.store.exists(cid, PGLOG_OID):
            tx.touch(cid, PGLOG_OID)
        tx.omap_setkeys(cid, PGLOG_OID,
                        {INTERVALS_KEY: self._pi(pgid).encode_bytes()})
        self.store.queue_transaction(tx)

    def _les(self, pgid: PgId) -> int:
        """last_epoch_started: the newest epoch this PG completed
        peering at (the interval fence — history older than it can no
        longer hold writes the current membership missed)."""
        les = self._pg_les.get(pgid)
        if les is None:
            cid = CollectionId(pgid.pool, pgid.seed)
            try:
                raw = self.store.omap_get(cid, PGLOG_OID).get(LES_KEY)
                les = int.from_bytes(raw, "little") if raw else 0
            except Exception:  # noqa: BLE001 - no log object yet
                les = 0
            self._pg_les[pgid] = les
        return les

    def _set_les(self, pgid: PgId, les: int) -> None:
        if les <= self._les(pgid):
            return
        self._pg_les[pgid] = les
        cid = CollectionId(pgid.pool, pgid.seed)
        tx = Transaction()
        if not self.store.exists(cid, PGLOG_OID):
            tx.touch(cid, PGLOG_OID)
        tx.omap_setkeys(cid, PGLOG_OID,
                        {LES_KEY: les.to_bytes(8, "little")})
        self.store.queue_transaction(tx)
        # the fence moved: trim history that can no longer matter
        pi = self._pi(pgid)
        pi.trim_to(les)
        self._save_pi(pgid)

    # ------------------------------------------------------------ pg split
    def _split_pgs(self, old: OSDMap | None, new: OSDMap) -> None:
        """A pool's pg_num grew: rehash every LOCAL parent collection
        into its children (OSD::split_pgs role, ref src/osd/OSD.h:1999
        + the OSDMap stable-mod split math in src/osd/OSDMap.cc).

        With modulo placement and new_pg_num a multiple of old_pg_num,
        an object at parent seed s moves to exactly one child seed in
        {s + k*old_pg_num} — so each holder of the parent can split
        LOCALLY, with no cross-daemon traffic.  Children inherit the
        parent's PGLog subsequence (entries for their objects), its
        last-complete point, its les fence and its PastIntervals
        (PGLog::split_into semantics): the child primaries then peer
        against the parent's membership history and the normal
        recovery/notify machinery moves shards to their CRUSH homes."""
        if old is None:
            return
        for pool_id, pool in new.pools.items():
            oldp = old.pools.get(pool_id)
            if oldp is None or pool.pg_num <= oldp.pg_num:
                continue
            oldn = oldp.pg_num
            for cid in list(self.store.list_collections()):
                if cid.pool != pool_id or cid.pg_seed >= oldn:
                    continue
                self._split_collection(pool_id, cid.pg_seed, oldn,
                                       pool.pg_num)
            # Force the next peering round of every PG of the grown
            # pool I lead to exchange FULL inventories: split members
            # inherit the parent's last-complete, so the lean path
            # would hide the shard redistribution entirely.
            for seed in range(pool.pg_num):
                up_s = new.pg_to_up_osds(pool_id, seed)
                if self._primary_of(up_s) == self.osd_id:
                    self._split_fresh.add(PgId(pool_id, seed))
            # Seed every NEW child PG I am an up member of with the
            # parent's membership as a maybe-active closed interval —
            # even when I never held the parent.  Without this a child
            # primary landing on a fresh OSD has an empty prior set,
            # peers trivially against nothing, and serves ENOENT while
            # the parent's holders still carry the objects.
            for child_seed in range(oldn, pool.pg_num):
                child_up = new.pg_to_up_osds(pool_id, child_seed)
                if self.osd_id not in [u for u in child_up
                                       if u is not None]:
                    continue
                parent_seed = child_seed % oldn
                parent_up = old.pg_to_up_osds(pool_id, parent_seed)
                child_pg = PgId(pool_id, child_seed)
                pi = self._pi(child_pg)
                first = min(old.epoch, new.epoch - 1)
                if not any(i.first == first and i.up == list(parent_up)
                           for i in pi.intervals):
                    pi.intervals.insert(0, Interval(
                        first, new.epoch - 1, list(parent_up),
                        self._primary_of(parent_up)))
                    self._save_pi(child_pg)

    def _split_collection(self, pool_id: int, parent_seed: int,
                          oldn: int, newn: int) -> None:
        from ..parallel.placement import pg_of_object
        from .snaps import split_vname
        parent_pg = PgId(pool_id, parent_seed)
        parent_cid = CollectionId(pool_id, parent_seed)
        # objects that re-hash away from the parent, grouped by child
        moves: dict[int, list[ObjectId]] = {}
        try:
            oids = list(self.store.list_objects(parent_cid))
        except Exception:  # noqa: BLE001 - collection vanished
            return
        for oid in oids:
            if oid.shard <= -2:
                continue  # PG metadata (pglog/snapmapper) stays put
            seed = pg_of_object(oid.name, newn)
            if seed != parent_seed:
                moves.setdefault(seed, []).append(oid)
        if not moves:
            return
        dout("osd", 2)("osd.%d: splitting pg %s: %d objects -> %s",
                       self.osd_id,
                       parent_pg,
                       sum(len(v) for v in moves.values()),
                       sorted(moves))
        parent_log = self._pglog(parent_pg).entries()
        parent_lc = self._lc(parent_pg)
        parent_les = self._les(parent_pg)
        parent_pi_raw = self._pi(parent_pg).encode_bytes()
        parent_tomb = self._tombstones.get(parent_pg, {})
        have = set(self.store.list_collections())
        moved_versions = []
        for child_seed, oids in sorted(moves.items()):
            child_pg = PgId(pool_id, child_seed)
            child_cid = CollectionId(pool_id, child_seed)
            tx = Transaction()
            if child_cid not in have:
                tx.create_collection(child_cid)
                have.add(child_cid)
            for oid in oids:
                data = self.store.read(parent_cid, oid)
                tx.touch(child_cid, oid)
                if data:
                    tx.write(child_cid, oid, 0, data)
                attrs = self.store.getattrs(parent_cid, oid)
                if attrs:
                    tx.setattrs(child_cid, oid, dict(attrs))
                omap = self.store.omap_get(parent_cid, oid)
                if omap:
                    tx.omap_setkeys(child_cid, oid, dict(omap))
                tx.remove(parent_cid, oid)
            # the child's slice of the parent's log (split_into): the
            # version numbering keeps the parent's sequence (gaps are
            # fine — peering falls back to inventories across gaps)
            child_log = self._pglog(child_pg)
            for e in parent_log:
                if pg_of_object(split_vname(e.oid)[0], newn) \
                        == child_seed:
                    child_log.append_to(tx, e)
                    moved_versions.append(e.version)
            meta = {"_lc": parent_lc.to_bytes(8, "little"),
                    LES_KEY: parent_les.to_bytes(8, "little"),
                    INTERVALS_KEY: parent_pi_raw}
            if not self.store.exists(child_cid, PGLOG_OID):
                tx.touch(child_cid, PGLOG_OID)
            tx.omap_setkeys(child_cid, PGLOG_OID, meta)
            self.store.queue_transaction(tx)
            # in-memory state for the child: fresh decodes + filtered
            # tombstones (cheap; loaded lazily elsewhere anyway)
            self._pglogs.pop(child_pg, None)
            self._pg_lc[child_pg] = parent_lc
            self._pg_les[child_pg] = parent_les
            self._past_intervals.pop(child_pg, None)
            tomb = {k: v for k, v in parent_tomb.items()
                    if pg_of_object(split_vname(k[0])[0], newn)
                    == child_seed}
            if tomb:
                self._tombstones[child_pg] = tomb
        # rewrite the parent: drop moved log entries + tombstones so a
        # later delta-replay cannot resurrect moved objects here
        if moved_versions:
            tx = Transaction()
            from .pglog import _key as _log_key
            tx.omap_rmkeys(parent_cid, PGLOG_OID,
                           [_log_key(v) for v in moved_versions])
            self.store.queue_transaction(tx)
            self._pglogs.pop(parent_pg, None)
        if parent_tomb:
            keep = {k: v for k, v in parent_tomb.items()
                    if pg_of_object(split_vname(k[0])[0], newn)
                    == parent_seed}
            self._tombstones[parent_pg] = keep
        self._ec_cache.invalidate(parent_pg)

    def _merge_pgs(self, old: OSDMap | None, new: OSDMap) -> None:
        """A pool's pg_num SHRANK (to a divisor of the old value): every
        source PG with seed >= new_pg_num folds into seed % new_pg_num
        (the pg merge of OSDMap.cc; with modulo placement and new | old,
        h % new == (h % old) % new, so each source merges whole into
        exactly one surviving PG).  Matching the reference's merge
        semantics, the combined PG's log is NOT continuable: logs reset,
        the les fence drops, PastIntervals of both halves concatenate,
        and the next peering round runs on full inventories (the
        _split_fresh force).

        Folding is keyed on LOCAL collections out of range of the NEW
        map — not on observing the shrink epoch — so an OSD that was
        down across the merge still folds its strays on revival
        (out-of-range seeds are invisible to _notify_demoted and would
        otherwise leak forever)."""
        for pool_id, pool in new.pools.items():
            newn = pool.pg_num
            by_target: dict[int, list[int]] = {}
            for cid in list(self.store.list_collections()):
                if cid.pool == pool_id and cid.pg_seed >= newn:
                    by_target.setdefault(cid.pg_seed % newn,
                                         []).append(cid.pg_seed)
            for tgt_seed, src_seeds in sorted(by_target.items()):
                self._merge_sources(pool_id, tgt_seed,
                                    sorted(src_seeds))
            oldp = old.pools.get(pool_id) if old is not None else None
            if oldp is None or newn >= oldp.pg_num:
                continue
            oldn = oldp.pg_num
            for seed in range(newn):
                up_s = new.pg_to_up_osds(pool_id, seed)
                if self._primary_of(up_s) == self.osd_id:
                    self._split_fresh.add(PgId(pool_id, seed))
                if self.osd_id not in [u for u in up_s
                                       if u is not None]:
                    continue
                # Seed every surviving PG I am a member of with each
                # folded source's OLD membership as a maybe-active
                # interval: a target primary that never held a source
                # collection would otherwise peer with an empty prior
                # set and serve ENOENT while the source's holders still
                # carry the objects (the same hole the split fix
                # closes for children).
                tgt_pg = PgId(pool_id, seed)
                pi = self._pi(tgt_pg)
                changed = False
                first = min(old.epoch, new.epoch - 1)
                for src_seed in range(seed + newn, oldn, newn):
                    src_up = old.pg_to_up_osds(pool_id, src_seed)
                    if any(i.first == first and i.up == list(src_up)
                           for i in pi.intervals):
                        continue
                    pi.intervals.insert(0, Interval(
                        first, new.epoch - 1, list(src_up),
                        self._primary_of(src_up)))
                    changed = True
                if changed:
                    self._save_pi(tgt_pg)

    def _merge_sources(self, pool_id: int, tgt_seed: int,
                       src_seeds: list[int]) -> None:
        """Fold every listed source collection into the target in ONE
        transaction, with one log reset and one PastIntervals rewrite
        however many sources share the target."""
        tgt_pg = PgId(pool_id, tgt_seed)
        tgt_cid = CollectionId(pool_id, tgt_seed)
        have = set(self.store.list_collections())
        tx = Transaction()
        if tgt_cid not in have:
            tx.create_collection(tgt_cid)
        tgt_pi = self._pi(tgt_pg)
        moved = 0
        with self._pending_lock:
            vmax = self._pg_versions.get(tgt_pg, 0)
        for src_seed in src_seeds:
            src_pg = PgId(pool_id, src_seed)
            src_cid = CollectionId(pool_id, src_seed)
            try:
                oids = list(self.store.list_objects(src_cid))
            except Exception:  # noqa: BLE001 - collection vanished
                continue
            for oid in oids:
                if oid.shard <= -2:
                    continue  # PG meta dies with the source
                data = self.store.read(src_cid, oid)
                tx.touch(tgt_cid, oid)
                if data:
                    tx.write(tgt_cid, oid, 0, data)
                attrs = self.store.getattrs(src_cid, oid)
                if attrs:
                    tx.setattrs(tgt_cid, oid, dict(attrs))
                omap = self.store.omap_get(src_cid, oid)
                if omap:
                    tx.omap_setkeys(tgt_cid, oid, dict(omap))
                tx.remove(src_cid, oid)
                moved += 1
            # concatenate membership history; the source's open
            # interval closes at the epoch before this map
            src_pi = self._pi(src_pg)
            tgt_pi.intervals.extend(src_pi.intervals)
            if src_pi.cur_up:
                tgt_pi.intervals.append(Interval(
                    src_pi.cur_first,
                    max(src_pi.cur_first, self.osdmap.epoch - 1),
                    list(src_pi.cur_up), src_pi.cur_primary))
            tx.remove_collection(src_cid)
            self._pglogs.pop(src_pg, None)
            self._past_intervals.pop(src_pg, None)
            with self._pending_lock:
                vmax = max(vmax, self._pg_versions.pop(src_pg, 0))
            src_tomb = self._tombstones.pop(src_pg, {})
            if src_tomb:
                tgt = self._tombstones.setdefault(tgt_pg, {})
                for k, v in src_tomb.items():
                    tgt[k] = max(tgt.get(k, -1), v)
            self._ec_cache.invalidate(src_pg)
        # the merged log is un-continuable: reset the TARGET's entries
        # and contiguity point; peering rebuilds authority from full
        # inventories (version floors recover from object "v" attrs)
        try:
            logkeys = [k for k in self.store.omap_get(tgt_cid,
                                                      PGLOG_OID)
                       if not k.startswith("_")]
        except Exception:  # noqa: BLE001 - no log object yet
            logkeys = []
        if logkeys:
            tx.omap_rmkeys(tgt_cid, PGLOG_OID, logkeys)
        if not self.store.exists(tgt_cid, PGLOG_OID):
            tx.touch(tgt_cid, PGLOG_OID)
        tx.omap_setkeys(tgt_cid, PGLOG_OID, {
            "_lc": (0).to_bytes(8, "little"),
            LES_KEY: (0).to_bytes(8, "little"),
            INTERVALS_KEY: tgt_pi.encode_bytes()})
        self.store.queue_transaction(tx)
        dout("osd", 2)("osd.%d: merged pgs %s into %s (%d objects)",
                       self.osd_id,
                       [f"{pool_id}.{s:x}" for s in src_seeds],
                       tgt_pg, moved)
        self._pglogs.pop(tgt_pg, None)
        self._pg_lc[tgt_pg] = 0
        self._pg_les[tgt_pg] = 0
        with self._pending_lock:
            self._pg_versions[tgt_pg] = vmax
        self._ec_cache.invalidate(tgt_pg)

    def _note_intervals(self) -> None:
        """Record membership changes for every PG I host or hold data
        for (PastIntervals::check_new_interval role) — durably, in the
        PG meta omap, so a revived OSD still knows who served while it
        was away."""
        if self.osdmap is None:
            return
        mine = {(pool_id, seed)
                for pool_id, seed, _up in self._pools_pgs_for_me()}
        for cid in self.store.list_collections():
            if cid.pool in self.osdmap.pools and \
                    cid.pg_seed < self.osdmap.pools[cid.pool].pg_num:
                mine.add((cid.pool, cid.pg_seed))
        for pool_id, seed in mine:
            up = self.osdmap.pg_to_up_osds(pool_id, seed)
            pgid = PgId(pool_id, seed)
            pi = self._pi(pgid)
            if pi.note(self.osdmap.epoch, up, self._primary_of(up)):
                self._save_pi(pgid)

    def _pool_stripe(self, pool_id: int) -> StripeInfo:
        """The pool's stripe geometry (ECUtil stripe_info_t role): a FIXED
        page-aligned chunk_size from the profile's stripe_unit, so objects
        are many interleaved stripe rows, not one unbounded stripe."""
        si = self._stripes.get(pool_id)
        if si is None:
            codec = self._pool_codec(pool_id)
            pool = self.osdmap.pools[pool_id]
            unit = int(pool.ec_profile.get(
                "stripe_unit", self.cfg["osd_ec_stripe_unit"]))
            si = StripeInfo(codec.k, codec.m, unit)
            self._stripes[pool_id] = si
        return si

    def _ec_object_len(self, pgid: PgId, oid: str) -> int | None:
        cid = CollectionId(pgid.pool, pgid.seed)
        for shard in range(self.osdmap.pools[pgid.pool].size):
            try:
                attrs = self.store.getattrs(cid, ObjectId(oid, shard=shard))
                if "len" in attrs:
                    return int(attrs["len"])
            except NoSuchObject:
                continue
        return None

    def _ec_write(self, conn, m: MOSDOp, pgid: PgId, up: list,
                  full: bool = True, lock_key: tuple | None = None) -> None:
        codec = self._pool_codec(pgid.pool)
        pool = self.osdmap.pools[pgid.pool]
        alive = [u for u in up if u is not None]
        if len(alive) < max(pool.min_size, codec.k):
            # below min_size: refuse the write (EAGAIN -> client retries
            # until recovery restores redundancy) rather than accepting
            # data with no margin to survive the next failure
            conn.send(MOSDOpReply(m.tid, EAGAIN, epoch=self.osdmap.epoch))
            self._obj_unlock(lock_key)
            return
        total = None if full else self._ec_object_len(pgid, m.oid)
        si = self._pool_stripe(pgid.pool)
        # snapshots: shard-wise clone-on-first-write-after-snap — the
        # rider rides every shard mutation of this op (make_writeable)
        _ign, rider = self._snap_prepare(pgid, m)
        if not full:
            object_size = total if total is not None else 0
            end = m.offset + len(m.data)
            if m.offset == 0 and end >= object_size:
                pass  # covers the whole object: same as write_full below
            else:
                # sub-object overwrite: the ECTransaction WritePlan
                # decision (full rows / parity delta / rmw) over the
                # stripe_info_t geometry
                plan = plan_write(si, object_size, m.offset, len(m.data),
                                  codec.get_flags())
                padded_end = si.object_chunk_size(object_size) * si.k
                if plan.mode == "full_stripe":
                    row0, nrows = si.rows_of_range(m.offset, len(m.data))
                    buf = bytearray(nrows * si.stripe_width)
                    start = m.offset - row0 * si.stripe_width
                    buf[start:start + len(m.data)] = m.data
                    self._ec_write_rows(
                        conn, m, pgid, up, codec, si, row0, bytes(buf),
                        max(object_size, end), create=object_size == 0,
                        prev_version=self._ec_object_version(pgid, m.oid)
                        if object_size else -1,
                        lock_key=lock_key, rider=rider)
                elif (plan.mode == "parity_delta" and end <= padded_end
                        and None not in up):
                    # delta only valid against rows that exist on EVERY
                    # shard; growth into new rows and degraded sets fall
                    # back to row-rmw
                    self._ec_partial_write(conn, m, pgid, up, codec, si,
                                           object_size, lock_key,
                                           rider=rider)
                else:
                    self._ec_rmw_rows(conn, m, pgid, up, codec, si,
                                      object_size, lock_key,
                                      rider=rider)
                return
        version = self._next_version(pgid)
        # whole-object (re)write: scatter the buffer into the RAID-0
        # shard streams and encode ALL rows in ONE kernel launch (the
        # batching seam of ECUtil::shard_extent_map_t::encode).  The
        # per-shard CRC32C rides the same pass (Checksummer.h:13 role):
        # on the jax backend both leave the device together, and every
        # shard holder stores the pre-computed digest instead of
        # re-sweeping the bytes on CPU.
        self._ec_cache.invalidate(pgid, m.oid)  # version moves past it
        streams = si.ro_scatter(m.data)
        parity, csums = self._ec_encode(codec, streams, with_csums=True,
                                        m=m)
        # write-through data AND parity streams at the new version: the
        # rewrite just produced the authoritative bytes, so hot-object
        # reads, rmw old-byte reads and the delta path's old-parity
        # reads all serve from cache (the failure paths — local below,
        # remote ack drain — invalidate)
        for shard in range(codec.chunk_count):
            if up[shard] is not None:
                chunk = streams[shard] if shard < codec.k \
                    else parity[shard - codec.k]
                self._ec_cache.write(pgid, m.oid, shard, 0,
                                     chunk.tobytes(), version=version,
                                     length=len(m.data))
        attrs = {"v": version, "len": len(m.data)}
        if self._ec_whiteout(pgid, m.oid):
            attrs["wh"] = 0  # write resurrects a whiteout'd head
        sub_attrs = dict(attrs)
        if rider is not None:
            sub_attrs["_snap"] = rider
        tid = next(self._tids)
        # the pending entry must exist BEFORE any sub-op leaves: with
        # sharded dispatch a reply can be processed on another shard
        # worker ahead of this handler's next line (round-4 regression:
        # late registration dropped the ack and the op timed out)
        remote = sum(1 for s, o in enumerate(up)
                     if o is not None and o != self.osd_id)
        if remote:
            pw = _PendingWrite(m.client, m.tid, remote, version,
                               lock_key=lock_key)
            pw.span = getattr(m, '_span', None)
            pw.qphase = getattr(m, '_qos_phase', 0)
            pw.pq_ctx = getattr(m, '_pq_ctx', None)
            self._pending_writes[tid] = pw
        for shard, osd in enumerate(up):
            if osd is None:
                continue  # degraded write: hole shard skipped
            chunk = streams[shard] if shard < codec.k \
                else parity[shard - codec.k]
            # zero-copy wire path: a contiguous staged chunk (the
            # batcher's single metered d2h output) rides the frame by
            # reference — Encoder.blob refs the memoryview, sendmsg
            # gathers it; nothing mutates a flush output after the
            # fact.  Non-contiguous scatter views still flatten here.
            data = chunk.data if chunk.flags.c_contiguous \
                else chunk.tobytes()
            if csums is not None:
                attrs = dict(attrs, dcsum=int(csums[shard]))
                sub_attrs = dict(sub_attrs, dcsum=int(csums[shard]))
            if osd == self.osd_id:
                pre = (self._snap_apply_rider(pgid, m.oid, rider,
                                              shard=shard)
                       if rider is not None else None)
                tctx = self._tctx(m)
                try:
                    if tctx:
                        with self.tracer.start("sub-write write",
                                               parent=tctx, shard=shard,
                                               oid=m.oid) as sp, \
                                self.tracer.start("store-commit",
                                                  parent=sp.ctx):
                            self._apply_write(pgid, m.oid, shard, data,
                                              attrs, pre_tx=pre)
                    else:
                        self._apply_write(pgid, m.oid, shard, data,
                                          attrs, pre_tx=pre)
                except BaseException:
                    # the write-through above published these bytes at
                    # the new version; a failed local apply must not
                    # leave them serveable (the lock is released by
                    # _run_locked_thunk's unwind)
                    self._ec_cache.invalidate(pgid, m.oid)
                    raise
            else:
                self.messenger.send_message(
                    f"osd.{osd}",
                    MSubWrite(tid, pgid, m.oid, shard, version, "write",
                              data, dict(sub_attrs),
                              epoch=self._entry_epoch(),
                              trace=self._tctx(m), tenant=m.tenant))
        if remote == 0:
            conn.send(MOSDOpReply(m.tid, 0, version=version,
                                  epoch=self.osdmap.epoch))
            self._obj_unlock(lock_key)
            return

    # -- EC partial writes (parity delta / rmw; ECTransaction WritePlan) ---
    def _ec_object_version(self, pgid: PgId, oid: str) -> int:
        """The primary's local view of the object's version (any local
        shard's v attr; -1 if it holds none)."""
        cid = CollectionId(pgid.pool, pgid.seed)
        best = -1
        for shard in range(self.osdmap.pools[pgid.pool].size):
            try:
                attrs = self.store.getattrs(cid, ObjectId(oid, shard=shard))
                best = max(best, int(attrs.get("v", 0)))
            except NoSuchObject:
                continue
        return best

    def _ec_write_rows(self, conn, m: MOSDOp, pgid: PgId, up: list, codec,
                       si: StripeInfo, row0: int, row_bytes: bytes,
                       new_len: int, create: bool = False,
                       prev_version: int = -1,
                       lock_key: tuple | None = None,
                       rider: dict | None = None) -> None:
        """Encode and store whole stripe rows [row0, row0+n) — the
        full-stripe branch of the WritePlan: no reads; every shard
        (parity included) takes an extent write at the row offsets,
        conditional on prev_version (a stale shard refuses with EAGAIN
        and the client retries once recovery has caught it up)."""
        version = self._next_version(pgid)
        self._ec_cache.invalidate(pgid, m.oid)  # version moves past it
        streams = si.ro_scatter(row_bytes)
        parity, _csums = self._ec_encode(codec, streams,
                                         with_csums=False, m=m)
        base = row0 * si.chunk_size
        tid = next(self._tids)
        remote = sum(1 for o in up
                     if o is not None and o != self.osd_id)
        pw = None
        if remote:
            # registered BEFORE any send: a reply may run on another
            # shard worker immediately (sharded-dispatch ordering).
            # +1 ack for the primary's own store commit (the barrier
            # registered after the local tallies below).
            pw = _PendingWrite(m.client, m.tid, remote + 1, version,
                               lock_key=lock_key)
            pw.span = getattr(m, '_span', None)
            pw.qphase = getattr(m, '_qos_phase', 0)
            pw.pq_ctx = getattr(m, '_pq_ctx', None)
            self._pending_writes[tid] = pw
        local_failed = local_retry = 0
        for shard, osd in enumerate(up):
            if osd is None or osd != self.osd_id:
                continue
            chunk = streams[shard] if shard < codec.k \
                else parity[shard - codec.k]
            ext = [(base, chunk.tobytes())]
            pre = (self._snap_apply_rider(pgid, m.oid, rider,
                                          shard=shard)
                   if rider else None)
            code = self._apply_partial(pgid, m.oid, shard, ext, version,
                                       create_ok=create,
                                       total_len=new_len,
                                       prev_version=prev_version,
                                       pre_tx=pre)
            if code == EAGAIN:
                local_retry += 1
            elif code != 0:
                local_failed += 1
        if pw is not None:
            # local tallies land before any send, so a full ack drain
            # computes the true result; the commit barrier fires the
            # +1 local ack once those applies are durable
            pw.failed += local_failed
            pw.retry += local_retry
            self._local_commit_ack(tid, pgid)
        # write-through the freshly encoded rows, parity included (the
        # device-resident stripe plane's hot-read feed: the next
        # overlapping read or rmw of these rows serves from cache —
        # device-side on a jax pool — instead of fanning to the
        # stores); failure paths invalidate (below for local-only
        # writes, the sub-write ack drain for remote ones).  This MUST
        # precede the sends: a remote shard can fail and drain every
        # ack (invalidating) before this thread resumes, and a
        # write-through landing after that invalidation would re-
        # publish the failed write's bytes with no one left to drop
        # them.
        for shard in range(codec.chunk_count):
            if up[shard] is not None:
                chunk = streams[shard] if shard < codec.k \
                    else parity[shard - codec.k]
                self._ec_cache.write(pgid, m.oid, shard, base,
                                     chunk.tobytes(), version=version,
                                     length=new_len)
        for shard, osd in enumerate(up):
            if osd is None or osd == self.osd_id:
                continue
            chunk = streams[shard] if shard < codec.k \
                else parity[shard - codec.k]
            ext = [(base, chunk.tobytes())]
            self.messenger.send_message(
                f"osd.{osd}",
                MSubPartialWrite(tid, pgid, m.oid, shard, version, ext,
                                 total_len=new_len, create=create,
                                 prev_version=prev_version,
                                 epoch=self._entry_epoch(),
                                 snap=rider or {},
                                 trace=self._tctx(m),
                                 tenant=m.tenant))
        if remote == 0:
            result = EIO if local_failed else (EAGAIN if local_retry else 0)
            if result != 0:
                self._ec_cache.invalidate(pgid, m.oid)

            def _finish_local() -> None:
                # parity-delta fallback arrives over a bare _ClientConn
                # (no dispatch wrappers): book the one-shot ctx here —
                # harmless when conn IS wrapped (finish dedups)
                ctx = getattr(m, "_pq_ctx", None)
                if ctx is not None:
                    ctx.finish(0)
                conn.send(MOSDOpReply(m.tid, result, version=version,
                                      epoch=self.osdmap.epoch))
                self._obj_unlock(lock_key)
            # the client reply (and the unlock's next-thunk run) wait
            # for durability, on the pg's shard
            self._on_store_commit(pgid, _finish_local)

    def _ec_partial_write(self, conn, m: MOSDOp, pgid: PgId, up: list,
                          codec, si: StripeInfo, object_size: int,
                          lock_key: tuple | None = None,
                          rider: dict | None = None) -> None:
        """Parity-delta overwrite: read ONLY the old bytes being replaced,
        write the new bytes to their data-shard extents, and fold
        coef*delta into every parity shard at the same shard offsets — no
        stripe re-encode, no k-wide read (ECUtil.cc:519-566 role)."""
        segs = si.ro_range_segments(m.offset, len(m.data))
        per_shard: dict[int, list] = {}
        for shard, soff, ln, ro in segs:
            per_shard.setdefault(shard, []).append((soff, ln, ro))
        new_len = max(object_size, m.offset + len(m.data))
        tid = next(self._tids)

        def on_old(pr) -> None:
            if pr is None or any(s not in pr.chunks for s in per_shard):
                ctx = getattr(m, "_pq_ctx", None)
                if ctx is not None:
                    ctx.finish(0)
                self.messenger.send_message(
                    m.client, MOSDOpReply(m.tid, EIO,
                                          epoch=self.osdmap.epoch))
                self._obj_unlock(lock_key)
                return
            vers = {pr.shard_vers.get(s) for s in per_shard}
            if len(vers) != 1 or None in vers:
                # touched shards disagree on version (stale revived
                # shard): deltas computed from those bytes would poison
                # parity — take the row-rmw path, which decodes from a
                # version-agreed set instead
                self._ec_rmw_rows(_ClientConn(self, m.client), m, pgid,
                                  up, codec, si, object_size, lock_key,
                                  rider=rider)
                return
            prev = vers.pop()
            version = self._next_version(pgid)
            wtid = next(self._tids)
            remote_n = sum(1 for o in up
                           if o is not None and o != self.osd_id)
            pw = None
            if remote_n:
                # registered before any send (sharded-dispatch rule);
                # +1 ack for the primary's own store commit
                pw = _PendingWrite(m.client, m.tid, remote_n + 1,
                                   version, lock_key=lock_key)
                pw.span = getattr(m, '_span', None)
                pw.qphase = getattr(m, '_qos_phase', 0)
                pw.pq_ctx = getattr(m, '_pq_ctx', None)
                self._pending_writes[wtid] = pw
            deltas: dict[int, list[tuple[int, bytes]]] = {}
            news: dict[int, list[tuple[int, bytes]]] = {}
            for shard, exts in per_shard.items():
                blob = pr.chunks[shard]
                pos = 0
                for soff, ln, ro in exts:
                    old = np.asarray(blob[pos:pos + ln], dtype=np.uint8)
                    if old.size < ln:  # reading past a short shard: zeros
                        old = np.concatenate(
                            [old, np.zeros(ln - old.size, np.uint8)])
                    new = np.frombuffer(
                        m.data[ro - m.offset: ro - m.offset + ln],
                        dtype=np.uint8)
                    delta = codec.encode_delta(old, new)
                    deltas.setdefault(shard, []).append(
                        (soff, delta.tobytes()))
                    news.setdefault(shard, []).append((soff, new.tobytes()))
                    pos += ln
            local_failed = local_retry = 0

            def tally(code: int) -> None:
                nonlocal local_failed, local_retry
                if code == EAGAIN:
                    local_retry += 1
                elif code != 0:
                    local_failed += 1

            flat = [(ds, soff, dbytes) for ds, lst in deltas.items()
                    for soff, dbytes in lst]
            # LOCAL applies first (their tallies must be recorded on the
            # pending entry before any ack can drain it)
            for shard, osd in enumerate(up):
                if osd != self.osd_id:
                    continue
                pre = (self._snap_apply_rider(pgid, m.oid, rider,
                                              shard=shard)
                       if rider else None)
                if shard < codec.k:
                    tally(self._apply_partial(pgid, m.oid, shard,
                                              news.get(shard, []),
                                              version, total_len=new_len,
                                              prev_version=prev,
                                              pre_tx=pre))
                else:
                    tally(self._apply_delta_local(pgid, m.oid, shard,
                                                  flat, version,
                                                  total_len=new_len,
                                                  prev_version=prev,
                                                  pre_tx=pre))
            if pw is not None:
                pw.failed += local_failed
                pw.retry += local_retry
                self._local_commit_ack(wtid, pgid)
            # cache maintenance BEFORE any send (a remote failure can
            # drain every ack — invalidating — before this thread
            # resumes; a write-through landing after that would re-
            # publish the failed bytes): drop cached PARITY runs (the
            # deltas are applied shard-locally by the parity holders,
            # so the primary never sees the resulting parity — cached
            # parity bytes from an earlier full/row write would be
            # stale at the advanced version), then refill the data-
            # shard runs just written (the next overlapping overwrite
            # skips the read fan); failure paths invalidate
            self._ec_cache.drop_shards(
                pgid, m.oid, range(codec.k, codec.chunk_count))
            for shard, lst in news.items():
                for soff, nb in lst:
                    self._ec_cache.write(pgid, m.oid, shard, soff, nb,
                                         version=version,
                                         length=new_len)
            # data shards: new bytes (touched) or version bump (untouched)
            for shard, osd in enumerate(up):
                if osd is None or osd == self.osd_id:
                    continue
                if shard < codec.k:
                    self.messenger.send_message(
                        f"osd.{osd}",
                        MSubPartialWrite(wtid, pgid, m.oid, shard, version,
                                         news.get(shard, []),
                                         total_len=new_len,
                                         prev_version=prev,
                                         epoch=self._entry_epoch(),
                                         snap=rider or {},
                                         trace=self._tctx(m),
                                         tenant=m.tenant))
                else:
                    # parity: one delta message covering all data deltas
                    self.messenger.send_message(
                        f"osd.{osd}",
                        MSubDelta(wtid, pgid, m.oid, shard, version,
                                  list(flat), total_len=new_len,
                                  prev_version=prev,
                                  epoch=self._entry_epoch(),
                                  snap=rider or {},
                                  trace=self._tctx(m),
                                  tenant=m.tenant))
            if remote_n == 0:
                result = EIO if local_failed \
                    else (EAGAIN if local_retry else 0)
                if result != 0:
                    self._ec_cache.invalidate(pgid, m.oid)

                def _finish_local() -> None:
                    self.messenger.send_message(
                        m.client,
                        MOSDOpReply(m.tid, result, version=version,
                                    epoch=self.osdmap.epoch))
                    self._obj_unlock(lock_key)
                self._on_store_commit(pgid, _finish_local)

        # extent-cache fast path (ECExtentCache role): if EVERY touched
        # segment is cached at a known version, skip the read fan-out
        cver = self._ec_cache.version(pgid, m.oid)
        if cver is not None:
            cached: dict[int, np.ndarray] = {}
            for shard, exts in per_shard.items():
                parts = []
                for soff, ln, _ro in exts:
                    b = self._ec_cache.read(pgid, m.oid, shard, soff, ln)
                    if b is None:
                        break
                    parts.append(b)
                else:
                    cached[shard] = np.frombuffer(b"".join(parts),
                                                  dtype=np.uint8)
                    continue
                break
            if len(cached) == len(per_shard):
                self.perf.inc("ec_cache_hit")
                pr = _PendingRead(None, 0, pgid.pool, m.oid,
                                  total_shards=len(per_shard))
                pr.chunks = cached
                pr.shard_vers = {s: cver for s in per_shard}
                on_old(pr)
                return
        self.perf.inc("ec_cache_miss")
        pr = _PendingRead(None, 0, pgid.pool, m.oid,
                          total_shards=len(per_shard), on_done=on_old)
        self._pending_reads[tid] = pr
        coalesce = self._ec_read_coalesce_on(pgid.pool)
        span = getattr(m, "_span", None)
        trace = (self.tracer, span.ctx) if span is not None else None
        for shard, exts in per_shard.items():
            osd = up[shard]
            want = [(soff, ln) for soff, ln, _ro in exts]
            if osd == self.osd_id:
                self._deliver_local_shard_read(tid, pgid, m.oid, shard,
                                               want)
            elif coalesce:
                self._read_agg.submit(f"osd.{osd}", tid, pgid, m.oid,
                                      shard, want, trace=trace)
            else:
                self.messenger.send_message(
                    f"osd.{osd}", MSubRead(tid, pgid, m.oid, shard, want))

    def _ec_rmw_rows(self, conn, m: MOSDOp, pgid: PgId, up: list, codec,
                     si: StripeInfo, object_size: int,
                     lock_key: tuple | None = None,
                     rider: dict | None = None) -> None:
        """Read-modify-write over the touched stripe rows ONLY (never the
        whole object): read the rows' shard extents from >= k shards
        (decoding when degraded), merge the new bytes, re-encode the rows,
        store them (ECCommon RMWPipeline + ECExtentCache read role)."""
        row0, nrows = si.rows_of_range(m.offset, len(m.data))
        old_rows = si.object_chunk_size(object_size) // si.chunk_size
        read_rows = min(nrows, max(0, old_rows - row0))
        end = m.offset + len(m.data)
        new_len = max(object_size, end)
        if read_rows <= 0:
            # touched rows hold no live data: append-style full rows
            buf = bytearray(nrows * si.stripe_width)
            start = m.offset - row0 * si.stripe_width
            buf[start:start + len(m.data)] = m.data
            self._ec_write_rows(conn, m, pgid, up, codec, si, row0,
                                bytes(buf), new_len,
                                create=object_size == 0,
                                prev_version=self._ec_object_version(
                                    pgid, m.oid) if object_size else -1,
                                lock_key=lock_key, rider=rider)
            return
        want_len = read_rows * si.chunk_size
        ext = [(row0 * si.chunk_size, want_len)]
        tid = next(self._tids)

        def on_read(pr) -> None:
            have = dict(pr.chunks) if pr is not None else {}
            vmax = -1
            if pr is not None and pr.shard_vers:
                # merge only against a version-AGREED read set: a stale
                # revived shard's old rows must not be re-encoded into the
                # new stripe and stamped current
                vmax = max(pr.shard_vers.values())
                have = {s: c for s, c in have.items()
                        if pr.shard_vers.get(s) == vmax}
            for s in list(have):
                c = have[s]
                if c.size < want_len:
                    have[s] = np.concatenate(
                        [c, np.zeros(want_len - c.size, np.uint8)])
            if len(have) < codec.k:
                # no agreed decodable set right now: transient if a stale
                # shard is still being recovered, so let the client retry
                err = EAGAIN if (pr is not None
                                 and len(pr.chunks) >= codec.k) else EIO
                self.messenger.send_message(
                    m.client, MOSDOpReply(m.tid, err,
                                          epoch=self.osdmap.epoch))
                self._obj_unlock(lock_key)
                return
            data_ids = list(range(codec.k))
            if all(i in have for i in data_ids):
                streams = [have[i] for i in data_ids]
            else:
                dec = self._ec_decode(codec, data_ids, have,
                                      span=getattr(m, "_span", None))
                streams = [dec[i] for i in data_ids]
            old = si.ro_assemble(streams).tobytes()
            buf = bytearray(nrows * si.stripe_width)
            buf[: len(old)] = old[: len(buf)]
            start = m.offset - row0 * si.stripe_width
            buf[start:start + len(m.data)] = m.data
            self._ec_write_rows(_ClientConn(self, m.client), m, pgid, up,
                                codec, si, row0, bytes(buf), new_len,
                                prev_version=vmax, lock_key=lock_key,
                                rider=rider)

        # extent-cache serve (the stripe plane's rmw feed): the touched
        # rows' old bytes were written through by the previous write,
        # so a hot-object rmw skips the k-wide read fan-out entirely —
        # on_read sees a synthetic version-agreed k-set and proceeds
        # straight to merge + re-encode (whose encode input then stages
        # once in the batcher's device ingest)
        cver = self._ec_cache.version(pgid, m.oid)
        if cver is not None:
            cached: dict[int, np.ndarray] = {}
            for shard in range(codec.k):
                b = self._ec_cache.read(pgid, m.oid, shard,
                                        row0 * si.chunk_size, want_len)
                if b is None:
                    break
                cached[shard] = np.frombuffer(b, dtype=np.uint8)
            else:
                self.perf.inc("ec_rmw_cache_serves")
                served = _PendingRead(None, 0, pgid.pool, m.oid,
                                      total_shards=codec.k)
                served.chunks = cached
                served.shard_vers = {s: cver for s in cached}
                on_read(served)
                return
        pr = _PendingRead(None, 0, pgid.pool, m.oid,
                          total_shards=sum(1 for u in up if u is not None),
                          on_done=on_read)
        self._pending_reads[tid] = pr
        self._fan_shard_reads(tid, pgid, m.oid, up, extents=ext)

    def _apply_partial(self, pgid: PgId, oid: str, shard: int,
                       extents: list, version: int,
                       create_ok: bool = False,
                       total_len: int | None = None,
                       prev_version: int = -1,
                       pre_tx: Transaction | None = None,
                       extra_attrs: dict | None = None) -> int:
        """Apply extent overwrites to one shard chunk + refresh v/digest.
        Returns 0, ENOENT, or EAGAIN (no change on nonzero).

        ENOENT when the object is absent and create_ok is not set: a
        lagging replica/shard must NEVER fabricate a zero-filled chunk
        stamped with the new version — recovery's version gate would then
        consider it current forever.  Only the primary creating a
        genuinely new object passes create_ok.

        EAGAIN when prev_version >= 0 and the stored shard is at a
        DIFFERENT version: the primary computed these extents against
        prev_version bytes, so applying them over stale (or newer) data
        would desynchronize the stripe while stamping it current."""
        cid = CollectionId(pgid.pool, pgid.seed)
        obj = to_oid(oid, shard)
        tx = Transaction()
        if pre_tx is not None:
            tx.append(pre_tx)
        exists = self.store.exists(cid, obj)
        old_attrs: dict = {}
        if not exists:
            if not create_ok:
                return ENOENT
            tx.touch(cid, obj)
        else:
            old_attrs = dict(self.store.getattrs(cid, obj))
            if prev_version >= 0 and \
                    int(old_attrs.get("v", 0)) != prev_version:
                return EAGAIN
            # extent writes (and their rollback pre-images) operate in
            # raw space: a compressed stored blob rewrites raw first
            # and stays raw until the next whole-object ingest
            old_attrs = self._inflate_in_place(cid, obj, old_attrs)
        # stash the pre-images being overwritten (the PGLog rollback
        # generation role): a torn partial write rolls back via these
        rollback = []
        old_shard_len = -1
        if exists:
            old_shard_len = self.store.stat(cid, obj)["size"]
            for coff, data in extents:
                old = self.store.read(cid, obj, coff,
                                      len(data)).to_bytes()
                old += b"\0" * (len(data) - len(old))
                rollback.append((coff, old))
        ev = self._entry_epoch()
        for coff, data in extents:
            tx.write(cid, obj, coff, data)
        self._log_apply(tx, pgid, LogEntry(
            version, "rows", oid, shard,
            prev_version=int(old_attrs.get("v", -1)),
            rollback=rollback,
            old_len=int(old_attrs.get("len", -1)),
            old_shard_len=old_shard_len, epoch=ev))
        self.store.queue_transaction(tx)
        data = self.store.read(cid, obj).to_bytes()
        attrs = dict(self.store.getattrs(cid, obj))
        if extra_attrs:
            attrs.update(extra_attrs)
        if attrs.get("wh"):
            attrs["wh"] = 0  # extents land = the object lives again
        attrs["v"] = version
        attrs["ev"] = ev
        attrs["d"] = native_crc32c(data)
        if shard < 0:
            # replicated: the object IS the data; track its size for stat
            attrs["len"] = len(data)
        elif total_len is not None and total_len >= 0:
            # EC shards carry "len" = whole-object length; growing partial
            # writes move it forward
            attrs["len"] = max(int(attrs.get("len", 0)), total_len)
        self.store.queue_transaction(
            Transaction().setattrs(cid, obj, attrs))
        return 0

    def _apply_delta_local(self, pgid: PgId, oid: str, parity_shard: int,
                           extents: list, version: int,
                           total_len: int | None = None,
                           prev_version: int = -1,
                           pre_tx: Transaction | None = None) -> int:
        """Fold coef*delta extents into the stored parity chunk via the
        plugin's apply_delta (one chunk read/write for the whole batch).
        Returns 0, ENOENT (parity chunk absent — shard not yet
        recovered), or EAGAIN (stored version != prev_version: folding a
        delta into stale parity would poison it while stamping it
        current)."""
        codec = self._pool_codec(pgid.pool)
        cid = CollectionId(pgid.pool, pgid.seed)
        obj = ObjectId(oid, shard=parity_shard)
        if not self.store.exists(cid, obj):
            return ENOENT
        cur_attrs = dict(self.store.getattrs(cid, obj))
        if prev_version >= 0 and int(cur_attrs.get("v", 0)) != \
                prev_version:
            return EAGAIN
        # delta folds are raw-space extent arithmetic: inflate a
        # compressed parity chunk before folding into it
        self._inflate_in_place(cid, obj, cur_attrs)
        # fold deltas over ONE union-range buffer: extents from different
        # data shards overlap in parity space (same stripe row), and the
        # folds must accumulate — read the covering range once, fold all,
        # write it back (and only this range is stashed for rollback,
        # not the whole parity stream)
        lo = min(coff for _ds, coff, _d in extents)
        hi = max(coff + len(d) for _ds, coff, d in extents)
        old = self.store.read(cid, obj, lo, hi - lo).to_bytes()
        old += b"\0" * ((hi - lo) - len(old))
        buf = np.frombuffer(old, dtype=np.uint8).copy()
        for ds, coff, dbytes in extents:
            view = buf[coff - lo: coff - lo + len(dbytes)]
            codec.apply_delta(np.frombuffer(dbytes, dtype=np.uint8), ds,
                              {parity_shard: view})
        return self._apply_partial(pgid, oid, parity_shard,
                                   [(lo, buf.tobytes())], version,
                                   total_len=total_len, pre_tx=pre_tx)

    def _handle_sub_partial_write(self, conn, m: MSubPartialWrite) -> None:
        self.perf.inc("subop_w")
        self._sub_epoch.v = m.epoch
        self._subw_begin(m.pgid, m.oid)
        try:
            pre = (self._snap_apply_rider(m.pgid, m.oid, m.snap,
                                          shard=m.shard)
                   if m.snap else None)
            code = self._apply_partial(
                m.pgid, m.oid, m.shard, m.extents, m.version,
                create_ok=m.create,
                total_len=m.total_len if m.total_len >= 0 else None,
                prev_version=m.prev_version, pre_tx=pre)
        finally:
            self._sub_epoch.v = 0
            self._subw_end(m.pgid, m.oid)
        if code == 0:
            self._pg_versions[m.pgid] = max(
                self._pg_versions.get(m.pgid, 0), m.version)
            self.store.commit_barrier(lambda: conn.send(
                MSubWriteReply(m.tid, m.pgid, m.shard, self.osd_id, 0)))
        else:
            # refusal: nothing was applied, nothing to wait on
            conn.send(MSubWriteReply(m.tid, m.pgid, m.shard, self.osd_id,
                                     code))

    def _handle_sub_delta(self, conn, m: MSubDelta) -> None:
        self.perf.inc("subop_w")
        self._sub_epoch.v = m.epoch
        self._subw_begin(m.pgid, m.oid)
        try:
            pre = (self._snap_apply_rider(m.pgid, m.oid, m.snap,
                                          shard=m.parity_shard)
                   if m.snap else None)
            code = self._apply_delta_local(
                m.pgid, m.oid, m.parity_shard, m.extents, m.version,
                total_len=m.total_len if m.total_len >= 0 else None,
                prev_version=m.prev_version, pre_tx=pre)
        finally:
            self._sub_epoch.v = 0
            self._subw_end(m.pgid, m.oid)
        if code == 0:
            self._pg_versions[m.pgid] = max(
                self._pg_versions.get(m.pgid, 0), m.version)
            self.store.commit_barrier(lambda: conn.send(
                MSubWriteReply(m.tid, m.pgid, m.parity_shard,
                               self.osd_id, 0)))
        else:
            conn.send(MSubWriteReply(m.tid, m.pgid, m.parity_shard,
                                     self.osd_id, code))

    # -- read scale-out: balanced reads + client read leases ---------------
    # client ops that mutate object DATA bytes (and so must revoke
    # outstanding read leases at dispatch).  omap/xattr/watch mutations
    # deliberately absent: the leased bytes are unchanged.
    _LEASE_REVOKE_OPS = ("write", "write_full", "remove",
                         "snap_rollback", "multi_write", "call")
    _LEASE_NOTIFIER = "_lease"
    _READ_EWMA_CAP = 4096

    def _read_policy(self, pool) -> str:
        return str(pool.ec_profile.get("read_policy",
                                       "primary")).lower()

    def _balanced_read_ok(self, m: MOSDOp, pool, up: list) -> bool:
        """Whether THIS non-primary OSD may serve m under the pool's
        read_policy=balance: plain head reads only (snap reads bounce
        to the primary — clone-resolution state lives there), and only
        while the map says we hold a shard of the object's PG."""
        return (m.op == "read" and not getattr(m, "snapid", 0)
                and any(u == self.osd_id for u in up)
                and self._read_policy(pool) == "balance")

    def _lease_maybe_grant(self, pgid: PgId, oid: str, client: str,
                           whole: bool = True) -> float:
        """Advance the object's read-rate EWMA and, when it crosses
        osd_read_lease_rate on a WHOLE-object read, grant `client` a
        TTL lease (returned; 0.0 = no grant) and remember the grant so
        a write can revoke it.  A RANGED read never starts a lease but
        RIDES one the object already carries: the client joins the
        existing grant window (max outstanding expiry — never extended)
        so its cached range stays revocable, and the reply carries the
        remaining time.  On a balanced holder the grant is also
        registered at the primary — the ordering point for writes —
        fire-and-forget (a lost register is bounded by the TTL)."""
        ttl = float(self.cfg["osd_read_lease_ttl"])
        if ttl <= 0.0 or not client:
            return 0.0
        now = time.time()
        key = (pgid, oid)
        with self._lease_lock:
            rate, last = self._read_ewma.get(key, (0.0, now))
            dt = max(now - last, 1e-6)
            # dt-scaled EWMA with a ~1s time constant: rate converges
            # to the instantaneous read rate within about a second of
            # sustained traffic, so only genuinely hot objects grant
            alpha = min(1.0, dt)
            rate = (1.0 - alpha) * rate + alpha / dt
            self._read_ewma[key] = (rate, now)
            self._read_ewma.move_to_end(key)
            while len(self._read_ewma) > self._READ_EWMA_CAP:
                self._read_ewma.popitem(last=False)
            if not whole:
                g = self._lease_grants.get(key)
                horizon = max(g.values()) if g else 0.0
                if horizon <= now:
                    return 0.0
                # ride: join the object's live window, don't extend it
                g[client] = max(g.get(client, 0.0), horizon)
                expires = horizon
            else:
                if rate < float(self.cfg["osd_read_lease_rate"]):
                    return 0.0
                expires = now + ttl
                self._lease_grants.setdefault(key, {})[client] = expires
        self.perf.inc("read_lease_ride" if not whole
                      else "read_lease_grant")
        if self.osdmap is not None:
            up = self.osdmap.pg_to_up_osds(pgid.pool, pgid.seed)
            primary = self._primary_of(up)
            if primary is not None and primary != self.osd_id:
                self.messenger.send_message(
                    f"osd.{primary}",
                    MLeaseRegister(pgid, oid, client, expires))
        return expires - now

    def _handle_lease_register(self, conn, m: MLeaseRegister) -> None:
        with self._lease_lock:
            g = self._lease_grants.setdefault((m.pgid, m.oid), {})
            g[m.client] = max(g.get(m.client, 0.0), m.expires)

    def _lease_revoke(self, pgid: PgId, oid: str) -> None:
        """Drop every outstanding read lease on the object and ping
        the holders ("_lease" notify, notify_id 0 = no ack collection:
        the TTL bounds a lost ping).  Called at the primary's write
        choke point and by shard holders observing sub-writes."""
        with self._lease_lock:
            grants = self._lease_grants.pop((pgid, oid), None)
        if not grants:
            return
        now = time.time()
        for client, expires in grants.items():
            if expires <= now:
                continue
            self.perf.inc("read_lease_revoke")
            self.messenger.send_message(
                client, MWatchNotify(0, pgid.pool, oid,
                                     self._LEASE_NOTIFIER))

    def _sweep_leases(self, now: float) -> None:
        with self._lease_lock:
            for key in list(self._lease_grants):
                g = self._lease_grants[key]
                for c in [c for c, e in g.items() if e <= now]:
                    del g[c]
                if not g:
                    del self._lease_grants[key]

    # -- hot-read tier: sub-write fence + second-hit admission -------------
    def _subw_begin(self, pgid: PgId, oid: str) -> None:
        """A sub-write apply is starting on this shard holder: block
        hot-tier admission of the object until _subw_end publishes the
        write (note + invalidate), closing the window where a read
        that fetched pre-write bytes could admit them as current."""
        key = (pgid, oid)
        with self._wbar_lock:
            self._subw_inflight[key] = \
                self._subw_inflight.get(key, 0) + 1

    def _subw_end(self, pgid: PgId, oid: str) -> None:
        """Sub-write applied: publish it (write-seq note — the fence
        balanced reads and admission check against), drop any cached
        bytes the write outdated, revoke locally-issued leases, THEN
        clear the in-flight mark (order matters: an admission that
        misses the in-flight mark must see the note instead)."""
        key = (pgid, oid)
        self._note_obj_write(key)
        self._ec_cache.invalidate(pgid, oid)
        self._lease_revoke(pgid, oid)
        with self._wbar_lock:
            n = self._subw_inflight.get(key, 0) - 1
            if n > 0:
                self._subw_inflight[key] = n
            else:
                self._subw_inflight.pop(key, None)

    def _subw_busy(self, pgid: PgId, oid: str) -> bool:
        with self._wbar_lock:
            return (pgid, oid) in self._subw_inflight

    def _tier_on(self) -> bool:
        return str(self.cfg["ec_read_tier"]).lower() not in (
            "off", "false", "0", "no")

    def _tier_admit_ok(self, pgid: PgId, oid: str) -> bool:
        """Second-hit promotion (zipf-aware admission): the first read
        of an object only RECORDS it in a bounded LRU window; a repeat
        read while still in the window admits.  A one-pass scan churns
        through the window without ever admitting."""
        if not self._tier_on():
            return False
        key = (pgid, oid)
        with self._tier_lock:
            if key in self._tier_seen:
                self._tier_seen.move_to_end(key)
                return True
            self._tier_seen[key] = True
            cap = int(self.cfg["ec_read_tier_seen_cap"])
            while len(self._tier_seen) > cap:
                self._tier_seen.popitem(last=False)
            return False

    def _tier_admit(self, pr: "_PendingRead", pgid: PgId,
                    streams: list, vmax: int, total: int) -> None:
        """Admit the k data-shard streams of a just-served whole-object
        read into the extent cache (and through it the device arena).
        Fenced twice: skip while a sub-write apply is in flight, and
        UNDO if the write-seq moved past the marker captured at read
        fan-out — either way stale bytes can never sit under a
        serveable (version, length) key."""
        key = (pgid, pr.oid)
        if self._subw_busy(pgid, pr.oid):
            return
        with self._pending_lock:
            if self._obj_locks.get(key):
                return  # primary-side write pipeline active
        for shard, s in enumerate(streams):
            self._ec_cache.write(pgid, pr.oid, shard, 0,
                                 s.tobytes(), version=vmax,
                                 length=total)
        if self._subw_busy(pgid, pr.oid) or \
                self._obj_written_since(key, pr.wmarker):
            self._ec_cache.invalidate(pgid, pr.oid)
        else:
            self.perf.inc("ec_read_tier_admit")

    def _ec_read(self, conn, m: MOSDOp, pgid: PgId, up: list,
                 balanced: bool = False) -> None:
        si = self._pool_stripe(pgid.pool)
        target = m.oid
        if getattr(m, "snapid", 0):
            # snapshot read: resolve to the clone vname serving snapid
            # (find_object_context role; the shard reads then address
            # each shard's generation object via to_oid)
            target = self._ec_snap_resolve(pgid, m.oid, m.snapid)
            if target is None:
                conn.send(MOSDOpReply(m.tid, ENOENT,
                                      epoch=self.osdmap.epoch))
                return
        elif self._ec_whiteout(pgid, m.oid):
            conn.send(MOSDOpReply(m.tid, ENOENT,
                                  epoch=self.osdmap.epoch))
            return
        if target != m.oid:
            import dataclasses
            m = dataclasses.replace(m, oid=target)
        elif not getattr(m, "snapid", 0) and \
                self._ec_read_serve_cached(conn, m, pgid, si,
                                           balanced=balanced):
            return  # hot-object read served from the extent cache
        if not getattr(m, "snapid", 0) and self._tier_on():
            self.perf.inc("ec_read_tier_miss")
        tid = next(self._tids)
        extents = None
        row_base = row_len = 0
        if m.length:
            # range read: fetch only the stripe rows covering the range
            # (the shard_extent_set_t construction of a ReadPipeline op)
            row0, nrows = si.rows_of_range(m.offset, m.length)
            row_base = row0 * si.stripe_width
            row_len = nrows * si.chunk_size
            extents = [(row0 * si.chunk_size, row_len)]
        pr = _PendingRead(m.client, m.tid, pgid.pool, m.oid,
                          total_shards=sum(1 for u in up if u is not None),
                          offset=m.offset, length=m.length,
                          row_base=row_base, row_len=row_len)
        pr.span = getattr(m, "_span", None)
        pr.qphase = getattr(m, '_qos_phase', 0)
        pr.pq_ctx = getattr(m, '_pq_ctx', None)
        pr.balanced = balanced
        pr.wmarker = self._obj_write_marker()
        self._pending_reads[tid] = pr
        if pr.span is not None:
            # the fan-out stage of a traced read: local shard reads run
            # inside it, remote sub-reads queue their ec-read-wait
            # spans under it (the READ counterpart of ec-encode)
            with self.tracer.start("ec-subread-fanout",
                                   parent=pr.span.ctx, oid=m.oid) as sp:
                self._fan_shard_reads(tid, pgid, m.oid, up,
                                      extents=extents,
                                      trace=(self.tracer, sp.ctx))
        else:
            self._fan_shard_reads(tid, pgid, m.oid, up, extents=extents)

    def _ec_read_serve_cached(self, conn, m: MOSDOp, pgid: PgId,
                              si: StripeInfo,
                              balanced: bool = False) -> bool:
        """Serve a head-object client read entirely from the extent
        cache (the device-resident stripe plane's hot-read path): when
        every data shard's covering stream is cached at a known
        version, the read never fans to the stores or the wire — and
        on a jax pool the shard rows assemble IN HBM from the arena's
        device mirrors, leaving as one metered d2h copy.  Returns
        False (caller fans out) on any gap; the invalidation contract
        (recovery pushes, rollbacks, removes, map changes, failed
        writes) keeps a True serve byte-identical to the store path."""
        if str(self.cfg["ec_read_cache_serve"]).lower() in (
                "off", "false", "0", "no"):
            return False
        # write-seq fence (balanced holders have no _obj_locks view of
        # the primary's pipeline, but they DO observe sub-write applies
        # — _subw_end notes them): captured before the version check,
        # re-checked after assembly
        wmarker = self._obj_write_marker()
        if self._subw_busy(pgid, m.oid):
            return False
        with self._pending_lock:
            if self._obj_locks.get((pgid, m.oid)):
                # a write/remove is in flight on the object: its
                # write-through populated the cache at the NEW version
                # before the shard acks drained, and serving that would
                # expose bytes the client was never acked (a failed
                # drain invalidates them away again).  Fall out to the
                # store path, which the sharded op queue serializes
                # with the applies.
                return False
        total = self._ec_cache.object_len(pgid, m.oid)
        if self._ec_cache.version(pgid, m.oid) is None or not total:
            return False
        codec = self._pool_codec(pgid.pool)
        if m.length:
            row0, nrows = si.rows_of_range(m.offset, m.length)
            soff, slen = row0 * si.chunk_size, nrows * si.chunk_size
            row_base = row0 * si.stripe_width
        else:
            soff, slen = 0, si.object_chunk_size(total)
            row_base = 0
        span = getattr(m, "_span", None)
        with (self.tracer.start("ec-cache-serve", parent=span.ctx,
                                oid=m.oid)
              if span is not None else contextlib.nullcontext()):
            ro = self._ec_cached_ro(codec, si, pgid, m.oid, soff, slen)
        if ro is None:
            return False
        with self._pending_lock:
            if self._obj_locks.get((pgid, m.oid)):
                # TOCTOU re-check: a write that registered AFTER the
                # guard above may have invalidated + written through
                # its (unacked) new version while we assembled — the
                # assembled bytes are only guaranteed committed if no
                # write appeared during assembly.  (A write registering
                # after THIS check hasn't touched the cache yet, so the
                # assembled bytes are the committed pre-write state.)
                return False
        if self._subw_busy(pgid, m.oid) or \
                self._obj_written_since((pgid, m.oid), wmarker):
            # a sub-write landed (or is landing) while we assembled:
            # the bytes may be the outdated pre-write state
            return False
        self.perf.inc("ec_read_cache_hit")
        self.perf.inc("ec_read_tier_hit")
        if balanced:
            self.perf.inc("balanced_read_serve")
        if m.length:
            # identical trimming to _finish_ec_read's range leg
            limit = max(0, min(len(ro), total - row_base))
            start = m.offset - row_base
            payload = ro[:limit][start:start + m.length]
        else:
            payload = ro[:total]
            if m.offset:
                payload = payload[m.offset:]
        lease = self._lease_maybe_grant(pgid, m.oid, m.client,
                                        whole=not m.length
                                        and not m.offset)
        conn.send(MOSDOpReply(m.tid, 0, data=payload,
                              epoch=self.osdmap.epoch, lease=lease))
        return True

    def _ec_cached_ro(self, codec, si: StripeInfo, pgid: PgId, oid: str,
                      soff: int, slen: int) -> bytes | None:
        """The k data-shard streams [soff, soff+slen) interleaved back
        into ro bytes, from the extent cache only — device-assembled
        (zero-copy HBM views, one metered d2h for the payload) when
        every stream is arena-resident on a jax pool, host-assembled
        otherwise.  None = not fully cached."""
        if getattr(codec, "_backend", None) == "jax":
            devs = []
            for shard in range(codec.k):
                d = self._ec_cache.read_device(pgid, oid, shard, soff,
                                               slen)
                if d is None:
                    devs = None
                    break
                devs.append(d)
            if devs is not None:
                try:
                    import jax.numpy as jnp

                    from ..utils import staging
                    rows = slen // si.chunk_size
                    ro_dev = jnp.stack(devs).reshape(
                        codec.k, rows, si.chunk_size).transpose(
                        1, 0, 2).reshape(-1)
                    (ro,) = staging.fetch_recorded(
                        [ro_dev], sig="sync/cache-read")
                    return ro.tobytes()
                except Exception:  # noqa: BLE001 - host fall-through
                    pass
        parts = []
        for shard in range(codec.k):
            b = self._ec_cache.read(pgid, oid, shard, soff, slen)
            if b is None:
                return None
            parts.append(np.frombuffer(b, dtype=np.uint8))
        return si.ro_assemble(parts).tobytes()

    def _ec_read_coalesce_on(self, pool_id: int) -> bool:
        """Whether this pool's remote sub-reads route through the
        per-peer aggregator: pool ec-profile key 'read_coalesce' wins,
        then the ec_read_coalesce option; 'auto' engages under the
        sharded mclock scheduler (reads fan out async, so concurrent
        bursts overlap and the window buys message fan-in; a 0 window
        is always pass-through)."""
        if self._read_agg.window_us <= 0:
            return False
        codec = self._pool_codec(pool_id)
        mode = str(codec.profile.get(
            "read_coalesce", self.cfg["ec_read_coalesce"])).lower()
        if mode in ("on", "true", "1", "yes"):
            return True
        if mode in ("off", "false", "0", "no"):
            return False
        return self._use_mclock

    def _fan_shard_reads(self, tid: int, pgid: PgId, oid: str,
                         up: list, extents: list | None = None,
                         trace: tuple | None = None,
                         klass: str = "client") -> None:
        # recovery fetches bypass the client-read aggregator AND carry
        # their class on the wire: the serving peer queues them under
        # its recovery reservation/limit, not in the client lane
        coalesce = klass == "client" \
            and self._ec_read_coalesce_on(pgid.pool)
        for shard, osd in enumerate(up):
            if osd is None:
                continue
            if osd == self.osd_id:
                self._deliver_local_shard_read(tid, pgid, oid, shard,
                                               extents)
            elif coalesce:
                self._read_agg.submit(f"osd.{osd}", tid, pgid, oid,
                                      shard, extents, trace=trace)
            else:
                self.messenger.send_message(
                    f"osd.{osd}", MSubRead(tid, pgid, oid, shard,
                                           extents, klass=klass))

    def _read_shard_slices(self, cid, obj, extents: list | None) -> bytes:
        """Whole shard stream, or the concatenation of the requested
        slices read RANGED from the store (a 4K range read of a huge
        object must not materialize the whole shard), each zero-padded to
        its requested length (absent tail bytes of a padded stripe row
        are zeros)."""
        if not extents:
            return self.store.read(cid, obj).to_bytes()
        parts = []
        for off, ln in extents:
            seg = self.store.read(cid, obj, off, ln).to_bytes()
            if len(seg) < ln:
                seg += b"\0" * (ln - len(seg))
            parts.append(seg)
        return b"".join(parts)

    #: shard attrs a RANGED client sub-read ships: the verification /
    #: assembly set only (version agreement, whole-object length, the
    #: stored digest, whiteout) — user attrs, SnapSets and the rest of
    #: the attr dict stay home.  Whole-shard recovery reads keep the
    #: full dict + omap (a rebuilt shard must land WITH its metadata).
    _RANGED_READ_ATTRS = ("v", "len", "d", "dcsum", "wh")

    def _read_one_sub(self, pgid: PgId, oid: str, shard: int,
                      extents: list | None):
        """Serve one sub-read against the local store: (result, data,
        attrs) with MSubReadReply semantics — shared by the per-op and
        vectorized handlers and the local fast path."""
        cid = CollectionId(pgid.pool, pgid.seed)
        obj = to_oid(oid, shard)  # vname-aware (clone shards)
        try:
            attrs = dict(self.store.getattrs(cid, obj))
            if "cz" in attrs:
                # compressed shard: inflate (whole blob — compressed
                # extents have no ranged form) and serve RAW slices;
                # the wire carries raw bytes only, so the extent
                # metadata stays home
                raw = self._inflate(self.store.read(cid, obj).to_bytes(),
                                    attrs)
                attrs.pop("cz")
                attrs.pop("crl", None)
                if extents:
                    parts = []
                    for off, ln in extents:
                        seg = raw[off:off + ln]
                        if len(seg) < ln:
                            seg += b"\0" * (ln - len(seg))
                        parts.append(seg)
                    data = b"".join(parts)
                else:
                    data = raw
            else:
                data = self._read_shard_slices(cid, obj, extents)
            if extents is None:
                # whole-shard reads serve recovery: the object's
                # replicated omap rides along so a rebuilt shard lands
                # WITH metadata (ECOmapJournal recovery contract)
                omap = self.store.omap_get(cid, obj)
                if omap:
                    attrs["_omap"] = omap
            else:
                # ranged client reads ship only the shard-verification
                # attrs, not the whole dict
                attrs = {k: attrs[k] for k in self._RANGED_READ_ATTRS
                         if k in attrs}
            return 0, data, attrs
        except NoSuchObject:
            return ENOENT, b"", {}
        except (StoreError, ValueError):
            # checksum-poisoned shard (FileStore csum verify) or a
            # compressed blob that no longer inflates: report EIO
            # promptly so decode proceeds from the remaining shards
            return EIO, b"", {}

    def _deliver_local_shard_read(self, tid, pgid, oid, shard,
                                  extents: list | None = None) -> None:
        result, data, attrs = self._read_one_sub(pgid, oid, shard,
                                                 extents)
        self._on_shard_read(tid, shard, result, data, attrs)

    def _handle_sub_read(self, conn, m: MSubRead) -> None:
        self.perf.inc("subop_r")
        result, data, attrs = self._read_one_sub(m.pgid, m.oid, m.shard,
                                                 m.extents)
        conn.send(MSubReadReply(m.tid, m.pgid, m.oid, m.shard,
                                self.osd_id, result, data, attrs))

    def _handle_sub_read_n(self, conn, m: MSubReadN) -> None:
        """Vectorized sub-read: serve every coalesced fetch and answer
        them all in ONE MSubReadReplyN.  Runs on m.pgid's scheduler
        shard (every item shares the pg), serialized against the pg's
        write applies like a plain MSubRead."""
        replies = []
        for fid, oid, shard, extents in m.items:
            self.perf.inc("subop_r")
            result, data, attrs = self._read_one_sub(m.pgid, oid, shard,
                                                     extents)
            replies.append((fid, shard, result, data, attrs))
        conn.send(MSubReadReplyN(self.osd_id, replies, m.pgid))

    def _handle_sub_read_reply(self, conn, m: MSubReadReply) -> None:
        self._on_shard_read(m.tid, m.shard, m.result, m.data, m.attrs)

    def _handle_sub_read_reply_n(self, conn, m: MSubReadReplyN) -> None:
        self._read_agg.on_reply(f"osd.{m.from_osd}", m.items)

    def _on_shard_read(self, tid, shard, result, data, attrs) -> None:
        with self._pending_lock:
            pr = self._pending_reads.get(tid)
            if pr is None:
                return
            pr.replies += 1
            if result == 0:
                pr.chunks[shard] = np.frombuffer(data, dtype=np.uint8)
                if attrs:
                    attrs = dict(attrs)
                    omap = attrs.pop("_omap", None)
                    if omap is not None:
                        pr.omaps[shard] = omap
                    pr.attrs.update(attrs)
                    pr.shard_attrs[shard] = dict(attrs)
                    if "v" in attrs:
                        pr.shard_vers[shard] = int(attrs["v"])
            k = self._pool_codec(pr.pool).k
            if pr.on_done is None and pr.shard_vers:
                # client-facing reads only decode a version-AGREED k-set
                # (the ECCommon read-consistency role, ECCommon.h:352-420):
                # a degraded read racing a partial write must not assemble
                # chunks from different versions
                vmax = max(pr.shard_vers.values())
                agreed = sum(1 for v in pr.shard_vers.values() if v == vmax)
                if agreed < k and pr.replies < pr.total_shards:
                    return
            elif (pr.want_all or len(pr.chunks) < k) \
                    and pr.replies < pr.total_shards:
                # finish as soon as enough chunks to decode are present —
                # no waiting for parity stragglers (ReadPipeline returns
                # at k); callback readers judge sufficiency themselves.
                # want_all readers (sub-chunk repairs: the MSR solve
                # consumes every helper) always wait the full fan-out.
                return
            self._pending_reads.pop(tid, None)
        self._finish_ec_read(pr)

    def _finish_ec_read(self, pr: _PendingRead) -> None:
        codec = self._pool_codec(pr.pool)
        done = pr.on_done
        if done:
            # callback readers (recovery, partial writes) judge chunk
            # sufficiency themselves — they may want fewer than k
            done(pr)
            return
        si = self._pool_stripe(pr.pool)
        epoch = self.osdmap.epoch if self.osdmap else 0
        chunks = pr.chunks
        total = self._ec_total_len(pr)
        if pr.shard_vers and chunks:
            vmax = max(pr.shard_vers.values())
            agreed = {s: c for s, c in chunks.items()
                      if pr.shard_vers.get(s) == vmax}
            if len(agreed) < codec.k and len(chunks) >= codec.k:
                # no complete version-agreed k-set: either a racing write
                # (transient — its commit completes the set) or a torn
                # stripe awaiting rollback/rebuild
                if pr.balanced:
                    # a balanced holder does not arbitrate torn state —
                    # the usual cause is simply a write in flight, and
                    # the primary serializes reads against its own
                    # pipeline.  Bounce the client there; no requery
                    # (a routine race must not trigger full peering).
                    self.perf.inc("balanced_read_bounce")
                    if pr.client:
                        if pr.pq_ctx is not None:
                            pr.pq_ctx.finish(0)
                        self.messenger.send_message(
                            pr.client,
                            MOSDOpReply(pr.client_tid, ESTALE,
                                        epoch=epoch, qphase=pr.qphase))
                    return
                # primary: kick a FULL reconciliation (lean peering
                # hides per-object versions) and have the client retry
                # rather than decode torn data
                if self.osdmap is not None:
                    seed = self.osdmap.object_to_pg(pr.pool, pr.oid)
                    self._requery_pg(PgId(pr.pool, seed), force_full=True)
                if pr.client:
                    if pr.pq_ctx is not None:
                        pr.pq_ctx.finish(0)
                    self.messenger.send_message(
                        pr.client, MOSDOpReply(pr.client_tid, EAGAIN,
                                               epoch=epoch,
                                               qphase=pr.qphase))
                return
            chunks = agreed
            # total length must come from an agreed shard, not the merged
            # last-reply-wins attrs (a stale straggler could clobber the
            # grown length and truncate the payload)
            for s in chunks:
                a = pr.shard_attrs.get(s, {})
                if "len" in a:
                    total = int(a["len"])
                    break
        if len(chunks) < codec.k:
            # no shard at all anywhere -> the object does not exist
            # (authoritative even on a balanced holder: the fan-out
            # covered the same acting set the primary would read);
            # some-but-too-few shards -> unrecoverable here — a
            # balanced holder bounces to the primary, which arbitrates
            # (recovery may be mid-flight), instead of minting EIO
            err = ENOENT if not pr.chunks else EIO
            if err == EIO and pr.balanced:
                self.perf.inc("balanced_read_bounce")
                err = ESTALE
            if pr.client:
                if pr.pq_ctx is not None:
                    pr.pq_ctx.finish(0)
                self.messenger.send_message(
                    pr.client, MOSDOpReply(pr.client_tid, err, epoch=epoch,
                                           qphase=pr.qphase))
            return
        if pr.stat_only:
            if pr.client:
                size = int(total or 0)
                if pr.pq_ctx is not None:
                    pr.pq_ctx.finish(8)
                self.messenger.send_message(
                    pr.client,
                    MOSDOpReply(pr.client_tid, 0,
                                data=size.to_bytes(8, "little"),
                                epoch=epoch, qphase=pr.qphase))
            return
        # equalize stream lengths (a straggling short shard pads; decode
        # is positional so padding is safe)
        stream_len = max(c.size for c in chunks.values())
        chunks = {s: (c if c.size == stream_len else np.concatenate(
            [c, np.zeros(stream_len - c.size, np.uint8)]))
            for s, c in chunks.items()}
        data_ids = list(range(codec.k))
        if all(i in chunks for i in data_ids):
            streams = [chunks[i] for i in data_ids]
        else:
            decoded = self._ec_decode(codec, data_ids, dict(chunks),
                                      span=pr.span)
            streams = [decoded[i] for i in data_ids]
        ro = si.ro_assemble(streams).tobytes()
        if pr.client and not pr.row_len and total and pr.shard_vers \
                and self.osdmap is not None:
            # hot-read tier: second hit on a whole-object client read
            # promotes the k data streams into the extent cache (and
            # lazily the device arena) at the agreed version
            seed = self.osdmap.object_to_pg(pr.pool, pr.oid)
            tpg = PgId(pr.pool, seed)
            if self._tier_admit_ok(tpg, pr.oid):
                self._tier_admit(pr, tpg, streams,
                                 max(pr.shard_vers.values()),
                                 int(total))
        if pr.row_len:
            # range read: ro covers [row_base, row_base + len(ro))
            limit = len(ro) if total is None \
                else max(0, min(len(ro), total - pr.row_base))
            avail = ro[:limit]
            start = pr.offset - pr.row_base
            payload = avail[start:start + pr.length] if pr.length \
                else avail[start:]
        else:
            payload = ro[:total] if total is not None else ro
            if pr.length:
                payload = payload[pr.offset:pr.offset + pr.length]
            elif pr.offset:
                payload = payload[pr.offset:]
        if pr.client:
            if pr.balanced:
                self.perf.inc("balanced_read_serve")
            lease = 0.0
            if self.osdmap is not None:
                seed = self.osdmap.object_to_pg(pr.pool, pr.oid)
                lease = self._lease_maybe_grant(
                    PgId(pr.pool, seed), pr.oid, pr.client,
                    whole=not pr.offset and not pr.length)
            if pr.pq_ctx is not None:
                pr.pq_ctx.finish(len(payload))
            self.messenger.send_message(
                pr.client,
                MOSDOpReply(pr.client_tid, 0, data=payload, epoch=epoch,
                            qphase=pr.qphase, lease=lease))

    def _ec_total_len(self, pr: _PendingRead) -> int | None:
        if "len" in pr.attrs:
            return int(pr.attrs["len"])
        if self.osdmap is None:
            return None
        seed = self.osdmap.object_to_pg(pr.pool, pr.oid)
        return self._ec_object_len(PgId(pr.pool, seed), pr.oid)

    def _ec_remove(self, conn, m: MOSDOp, pgid: PgId, up: list,
                   lock_key: tuple | None = None) -> None:
        # a head with clones (or a snapc staging one) must leave its
        # SnapSet behind: per-shard whiteout, not removal (snapdir role)
        _ign, rider = self._snap_prepare(pgid, m)
        ss = self._ec_load_ss(pgid, m.oid)
        whiteout = bool((ss or {}).get("clones")) or (
            rider is not None and rider.get("clone", -1) >= 0)
        version = self._next_version(pgid)
        if not whiteout:
            self._record_tombstone(pgid, m.oid, version)
        tid = next(self._tids)
        remote = sum(1 for o in up
                     if o is not None and o != self.osd_id)
        if remote:  # registered before any send (sharded dispatch);
            # +1 ack for the primary's own store commit
            pw = _PendingWrite(m.client, m.tid, remote + 1, version,
                               lock_key=lock_key)
            pw.span = getattr(m, '_span', None)
            pw.qphase = getattr(m, '_qos_phase', 0)
            pw.pq_ctx = getattr(m, '_pq_ctx', None)
            self._pending_writes[tid] = pw
        sub_attrs = {"_snap": rider} if rider is not None else {}
        for shard, osd in enumerate(up):
            if osd is None:
                continue
            if osd == self.osd_id:
                if whiteout:
                    pre = (self._snap_apply_rider(pgid, m.oid, rider,
                                                  shard=shard)
                           if rider is not None else None)
                    self._apply_whiteout(pgid, m.oid, version,
                                         pre_tx=pre, shard=shard)
                else:
                    self._apply_remove(pgid, m.oid, shard, version)
            else:
                self.messenger.send_message(
                    f"osd.{osd}",
                    MSubWrite(tid, pgid, m.oid, shard, version,
                              "whiteout" if whiteout else "remove",
                              attrs=dict(sub_attrs),
                              epoch=self._entry_epoch(),
                              trace=self._tctx(m), tenant=m.tenant))
        if remote == 0:
            def _finish_local() -> None:
                conn.send(MOSDOpReply(m.tid, 0, version=version,
                                      epoch=self.osdmap.epoch))
                self._obj_unlock(lock_key)
            self._on_store_commit(pgid, _finish_local)
        else:
            self._local_commit_ack(tid, pgid)

    # -- inline compression (osd/compression.py) ---------------------------
    def _compression_policy(self, pool: int):
        """The pool's resolved at-rest compression policy (None = store
        raw).  Cached per (pool, map epoch); a malformed profile on a
        live map degrades to raw rather than failing every write."""
        pm = getattr(self, "_comp_policies", None)
        if pm is None:
            pm = self._comp_policies = {}
        epoch = self.osdmap.epoch if self.osdmap is not None else 0
        hit = pm.get(pool)
        if hit is not None and hit[0] == epoch:
            return hit[1]
        pol = None
        try:
            spec = self.osdmap.pools.get(pool) if self.osdmap else None
            if spec is not None:
                pol = compression.CompressionPolicy.from_pool(
                    spec, self.cfg)
        except Exception as e:  # noqa: BLE001 - bad profile: store raw
            dout("osd", 1)("%s: pool %d compression profile invalid "
                           "(%r); storing raw", self.name, pool, e)
        pm[pool] = (epoch, pol)
        return pol

    def _inflate(self, data: bytes, attrs: dict) -> bytes:
        """Raw bytes of one stored blob (identity when uncompressed)."""
        if "cz" not in attrs:
            return data
        return compression.decompress(data, attrs["cz"],
                                      int(attrs["crl"]), perf=self.perf)

    def _read_obj_raw(self, cid, obj) -> tuple[bytes, dict]:
        """(raw bytes, stored attrs) of one object — the helper every
        read seam that needs RAW content goes through (wire payloads,
        extent arithmetic, cls/op contexts).  Attrs are returned as
        stored: callers shipping them strip cz/crl via _push_attrs."""
        attrs = dict(self.store.getattrs(cid, obj))
        data = self.store.read(cid, obj).to_bytes()
        return self._inflate(data, attrs), attrs

    def _obj_raw_size(self, cid, obj) -> int:
        """Logical (raw) size of a stored object: the recorded raw
        length when compressed, else the store's stat size."""
        try:
            attrs = self.store.getattrs(cid, obj)
        except NoSuchObject:
            attrs = {}
        if "cz" in attrs:
            return int(attrs["crl"])
        return self.store.stat(cid, obj)["size"]

    def _inflate_in_place(self, cid, obj, attrs: dict) -> dict:
        """Rewrite a compressed stored object RAW (same version) so the
        extent paths — partial writes, parity delta folds, rollback
        pre-images — operate in raw space.  Every replica/shard runs
        the same inflate on the same op, so stores stay byte-identical.
        Returns the refreshed attrs."""
        if "cz" not in attrs:
            return attrs
        raw = self._inflate(self.store.read(cid, obj).to_bytes(), attrs)
        attrs = dict(attrs)
        attrs.pop("cz")
        attrs.pop("crl", None)
        attrs["d"] = native_crc32c(raw)
        tx = Transaction()
        tx.truncate(cid, obj, 0)
        tx.write(cid, obj, 0, raw)
        tx.rmattr(cid, obj, "cz")
        tx.rmattr(cid, obj, "crl")
        tx.setattrs(cid, obj, {"d": attrs["d"]})
        self.store.queue_transaction(tx)
        return attrs

    # -- sub-op handling (shard/replica side) ------------------------------
    def _apply_write(self, pgid: PgId, oid: str, shard: int, data: bytes,
                     attrs: dict, omap: dict | None = None,
                     pre_tx: Transaction | None = None) -> None:
        cid = CollectionId(pgid.pool, pgid.seed)
        obj = to_oid(oid, shard)
        oid = vname_of(obj)  # canonical: log/tombstones use the vname
        # stored digest for deep scrub (per-blob csum, BlueStore role);
        # a device-computed csum from the fused encode pass arrives as
        # "dcsum" and skips the CPU re-sweep (scrub still re-verifies)
        dc = attrs.get("dcsum")
        # inline compression: whole-shard replace is the store's ingest
        # boundary, and the decision is a pure function of (pool
        # policy, raw bytes) — every holder of these bytes lands the
        # SAME stored form regardless of how they arrived (client op,
        # recovery push, scrub repair), which replica digest compare
        # relies on.  The wire always carries raw bytes.
        comp = None
        if data:
            pol = self._compression_policy(pgid.pool)
            if pol is not None and pol.mode == "aggressive":
                comp = pol.maybe_compress(data, perf=self.perf)
        if comp is not None:
            data, cattrs = comp
            # the stored digest covers the STORED bytes (scrub never
            # inflates); the fused-graph dcsum covered the raw bytes,
            # so it cannot stand in here
            attrs = dict(attrs, d=native_crc32c(data), **cattrs)
        else:
            attrs = dict(attrs, d=int(dc) if dc is not None
                         else native_crc32c(data))
        attrs.pop("dcsum", None)
        # entry epoch: a recovery push carries the authority's stamp in
        # "ev" (it must survive verbatim or the re-pushed entry forks
        # again); otherwise the minting/sub-op epoch
        ev = int(attrs.get("ev", 0)) or self._entry_epoch()
        attrs["ev"] = ev
        tx = Transaction()
        if cid not in self.store.list_collections():
            tx.create_collection(cid)
        if pre_tx is not None:
            tx.append(pre_tx)
        tx.touch(cid, obj)
        tx.truncate(cid, obj, 0)
        tx.write(cid, obj, 0, data)
        if "cz" not in attrs:
            # a raw overwrite of a previously-compressed object must
            # not leave stale extent metadata behind (setattrs merges)
            tx.rmattr(cid, obj, "cz")
            tx.rmattr(cid, obj, "crl")
        tx.setattrs(cid, obj, {k: v for k, v in attrs.items()})
        if omap is not None:
            # recovery pushes carry the object's omap: REPLACE ours
            try:
                old_keys = list(self.store.omap_get(cid, obj))
            except NoSuchObject:
                old_keys = []
            if old_keys:
                tx.omap_rmkeys(cid, obj, old_keys)
            if omap:
                tx.omap_setkeys(cid, obj, {str(k): bytes(v)
                                           for k, v in omap.items()})
        if "v" in attrs:
            try:
                old = self.store.getattrs(cid, obj)
            except NoSuchObject:
                old = {}
            # whole-object replace: no pre-image stash (rollback of a
            # full write = drop the shard object and rebuild from peers)
            self._log_apply(tx, pgid, LogEntry(
                int(attrs["v"]), "write", oid, shard,
                prev_version=int(old.get("v", -1)),
                old_len=int(old.get("len", -1)), epoch=ev))
        self.store.queue_transaction(tx)

    def _handle_sub_write(self, conn, m: MSubWrite) -> None:
        self.perf.inc("subop_w")
        if m.shard in self.inject.drop_shard_writes:
            # armed write-drop (ECInject write_error role): ack without
            # applying — a lost apply that scrub must later catch
            conn.send(MSubWriteReply(m.tid, m.pgid, m.shard, self.osd_id))
            return
        self._sub_epoch.v = m.epoch
        # omap mutations leave the object's DATA bytes unchanged: no
        # lease revoke, no extent-cache invalidation, no read fence
        mutates = not m.op.startswith("omap")
        if mutates:
            self._subw_begin(m.pgid, m.oid)
        try:
            if m.trace:
                # per-sub-op child span + the store-commit grandchild
                # (the ZTracer spans through EC sub-ops,
                # ECCommon.cc:1046-1051; the tree a collector merges:
                # client-op -> osd-op -> sub-write -> store-commit)
                with self.tracer.start(f"sub-write {m.op}",
                                       parent=m.trace,
                                       shard=m.shard,
                                       oid=m.oid) as sp:
                    with self.tracer.start("store-commit",
                                           parent=sp.ctx):
                        self._do_sub_write(conn, m)
            else:
                self._do_sub_write(conn, m)
        finally:
            self._sub_epoch.v = 0
            if mutates:
                self._subw_end(m.pgid, m.oid)

    def _do_sub_write(self, conn, m: MSubWrite) -> None:
        attrs = dict(m.attrs)
        rider = attrs.pop("_snap", None)
        pre_tx = (self._snap_apply_rider(m.pgid, m.oid, rider,
                                         shard=m.shard)
                  if rider is not None else None)
        if m.op == "write":
            self._apply_write(m.pgid, m.oid, m.shard, m.data,
                              dict(attrs, v=m.version), pre_tx=pre_tx)
        elif m.op == "write_partial":
            code = self._apply_partial(m.pgid, m.oid, m.shard,
                                       [(m.offset, m.data)], m.version,
                                       pre_tx=pre_tx, extra_attrs=attrs)
            if code != 0:
                # replica lacks the object (recovery lag): refuse rather
                # than fabricate a zero-prefixed copy at the new version
                conn.send(MSubWriteReply(m.tid, m.pgid, m.shard,
                                         self.osd_id, code))
                return
        elif m.op == "whiteout":
            self._apply_whiteout(m.pgid, m.oid, m.version, pre_tx=pre_tx,
                                 shard=m.shard)
        elif m.op == "snap_rollback":
            from ..msg.wire import unpack_value
            p = unpack_value(m.data)
            r = p.get("rider")
            rb_pre = (self._snap_apply_rider(m.pgid, m.oid, r,
                                             shard=m.shard)
                      if r else None)
            self._apply_snap_rollback(m.pgid, m.oid, int(p["cloneid"]),
                                      bytes(p["ss"]), m.version,
                                      pre_tx=rb_pre, shard=m.shard,
                                      total_len=int(p.get("total", -1)))
        elif m.op == "trim_clone":
            from ..msg.wire import unpack_value
            p = unpack_value(m.data)
            self._apply_trim(m.pgid, m.oid, int(p["snapid"]),
                             bytes(p["ss"]), bool(p["drop_head"]),
                             m.version, shard=m.shard)
        elif m.op == "remove":
            self._apply_remove(m.pgid, m.oid, m.shard, m.version)
        elif m.op in ("omap_set", "omap_rm"):
            from ..msg.wire import unpack_value
            self._apply_omap(m.pgid, m.oid, m.op, unpack_value(m.data),
                             m.version, create_ok=True, shard=m.shard)
        elif m.op == "cls_effects":
            from ..msg.wire import unpack_value
            self._apply_cls_effects(m.pgid, m.oid, unpack_value(m.data),
                                    m.version, shard=m.shard)
        elif m.op == "multi_effects":
            from ..msg.wire import unpack_value
            self._apply_multi_effects(m.pgid, m.oid,
                                      unpack_value(m.data), m.version,
                                      pre_tx=pre_tx, shard=m.shard)
        self._pg_versions[m.pgid] = max(
            self._pg_versions.get(m.pgid, 0), m.version)
        # the ack IS the durability promise: ride the commit pipeline's
        # finisher (in submission order) so it leaves only once the
        # apply's transactions are fsync'd — inline when sync-pinned
        self.store.commit_barrier(lambda: conn.send(
            MSubWriteReply(m.tid, m.pgid, m.shard, self.osd_id)))

    def _apply_remove(self, pgid: PgId, oid: str, shard: int,
                      version: int) -> None:
        cid = CollectionId(pgid.pool, pgid.seed)
        obj = to_oid(oid, shard)
        oid = vname_of(obj)
        tx = Transaction()
        if self.store.exists(cid, obj):
            tx.remove(cid, obj)
        self._log_apply(tx, pgid, LogEntry(version, "remove", oid, shard,
                                           prev_version=-1))
        self.store.queue_transaction(tx)
        self._ec_cache.invalidate(pgid, oid)
        self._record_tombstone(pgid, oid, version)

    def _on_store_commit(self, pgid: PgId, fn) -> None:
        """Run ``fn`` once everything queued in the store SO FAR is
        durable, ON pgid's scheduler shard — the finisher thread must
        never execute PG-state work itself (per-PG serialization is a
        shard-thread invariant).  Inline in sync mode: nothing is
        pending and the caller already holds the shard."""
        if not self._store_async:
            fn()
            return

        def fire() -> None:
            # force past the lossy QUEUE_CAP: a dropped completion has
            # no retry path — the reply would never leave and the
            # object lock would wedge forever
            self.scheduler.enqueue(
                "system", (lambda _c, _m: fn(), None, None),
                key=(pgid.pool, pgid.seed), force=True)
        self.store.commit_barrier(fire)

    def _local_commit_ack(self, tid: int, pgid: PgId) -> None:
        """Count the primary's OWN store commit as one ack on a pending
        write: registered as a commit barrier AFTER the local applies,
        so the finisher fires it once those transactions are durable
        (inline in sync mode — identical accounting to the pre-pipeline
        path).  The synthetic shard -2 rides the normal ack drain so
        result/fence/unlock/reply logic stays in one place."""
        self._on_store_commit(pgid, lambda: self._handle_sub_write_reply(
            None, MSubWriteReply(tid, pgid, -2, self.osd_id, 0)))

    def _handle_sub_write_reply(self, conn, m: MSubWriteReply) -> None:
        if m.result == EAGAIN:
            # a shard refused a conditional apply (it is stale): kick
            # recovery NOW — without this the shard only heals on the
            # next map epoch, and the client's retries spin meanwhile
            self._requery_pg(m.pgid)
        with self._pending_lock:
            pw = self._pending_writes.get(m.tid)
            if pw is None:
                return
            if m.result == EAGAIN:
                pw.retry += 1  # version conflict: transient, retryable
            elif m.result != 0:
                pw.failed += 1
            pw.acks_needed -= 1
            if pw.acks_needed > 0:
                return
            self._pending_writes.pop(m.tid, None)
        result = EIO if pw.failed else (EAGAIN if pw.retry else 0)
        # even a failed write may have mutated some shards (torn):
        # fence the aggregator's in-flight dup collapse either way,
        # BEFORE the client can observe the outcome
        self._note_obj_write(pw.lock_key)
        if result != 0 and pw.lock_key is not None:
            # a failed/torn write leaves cached extents untrustworthy
            self._ec_cache.invalidate(*pw.lock_key)
        if pw.span is not None:
            pw.span.tag("result", result)
            pw.span.finish()
        rdata = getattr(pw, "reply_data", b"") if result == 0 else b""
        if pw.pq_ctx is not None:
            # perf-query booking: this drain replies via the messenger,
            # so the dispatch-time conn wrapper never sees it
            pw.pq_ctx.finish(len(rdata))
        self.messenger.send_message(
            pw.client,
            MOSDOpReply(pw.client_tid, result, data=rdata,
                        version=pw.version,
                        epoch=self.osdmap.epoch if self.osdmap else 0,
                        qphase=pw.qphase))
        self._obj_unlock(pw.lock_key)

    # ----------------------------------------------------------- heartbeats
    def _heartbeat_loop(self) -> None:
        interval = self.cfg["osd_heartbeat_interval"]
        grace = self.cfg["osd_heartbeat_grace"]
        ticks = 0
        while not self._stop.wait(interval):
            now = time.time()
            self._sub_epoch.v = 0  # fresh epoch pin per hb-thread sweep
            # osd-beacon role (runs even before the FIRST map arrives):
            # map silence means the mon dropped our subscription (marked
            # us down / lost our boot during an election) or died —
            # rotate monitors, re-subscribe, and re-assert boot if the
            # map we hold doesn't show us up
            if now - self._last_map > 2 * grace:
                self._last_map = now  # debounce
                self._mon_idx += 1
                self.mon = self._mons[self._mon_idx % len(self._mons)]
                self.messenger.send_message(self.mon, MMonSubscribe())
                me = self.osdmap.osds.get(self.osd_id) \
                    if self.osdmap else None
                if me is None or not me.up:
                    net = self.messenger.network
                    self.messenger.send_message(
                        self.mon,
                        MOSDBoot(self.osd_id, self.host,
                                 net.addr_of(self.name),
                                 hb_addr=net.addr_of(
                                     self.hb_messenger.name)))
            if self.osdmap is None:
                continue
            self._sweep_pending(now)
            # flight recorder: an op that crossed the complaint time
            # while STILL IN FLIGHT journals its slow_op event (and
            # retains its trace) now — a wedged op may never finish
            try:
                self.op_tracker.note_inflight_slow()
            except Exception as e:  # noqa: BLE001 - never kill the thread
                dout("osd", 1)("%s: slow-op sweep failed: %r",
                               self.name, e)
            # metrics history: periodic snapshot of this daemon's perf
            # registries into the fixed-budget ring (shipped with the
            # stats reports, merged mon-side)
            m_int = self.cfg["metrics_history_interval_s"]
            if m_int > 0 and now - self._metrics_sampled_at >= m_int:
                self._metrics_sampled_at = now
                try:
                    self.metrics_history.sample(
                        self._metrics_registries(), ts=now)
                except Exception as e:  # noqa: BLE001
                    dout("osd", 1)("%s: metrics sample failed: %r",
                                   self.name, e)
            ticks += 1
            # active pg_temp overrides I lead: keep peering rounds
            # turning until the real primary verifies in sync and the
            # override clears (nothing else re-queries once the
            # recovery pushes have landed)
            for (pool, seed) in list(self.osdmap.pg_temp):
                spec = self.osdmap.pools.get(pool)
                if spec is None or spec.kind == "ec":
                    continue
                up_t = self.osdmap.pg_to_up_osds(pool, seed)
                if self._primary_of(up_t) == self.osd_id:
                    self._requery_pg(PgId(pool, seed))
            for peer in self.osdmap.up_osds():
                if peer == self.osd_id:
                    continue
                self.hb_messenger.send_message(
                    f"osd.{peer}.hb",
                    MOSDPing(self.osd_id, self.osdmap.epoch, now))
                # seed the clock at first observation so a peer that never
                # answers a single ping still gets reported
                last = self._hb_last.setdefault(peer, now)
                if now - last > grace:
                    self.perf.inc("failure_reports")
                    self.messenger.send_message(
                        self.mon,
                        MFailureReport(peer, self.osd_id,
                                       self.osdmap.epoch, now - last))
            # stats AFTER pings (the walk must never delay liveness), every
            # 5th tick, time-budgeted, and never allowed to kill the thread
            if ticks % 5 == 0:
                try:
                    self._report_stats(budget=max(grace / 4, 0.05))
                except Exception as e:  # noqa: BLE001
                    dout("osd", 1)("%s: stats report failed: %r",
                                   self.name, e)
            # background deep scrub: arm due PGs (chunks run on the
            # shard threads under the scrub mclock class, not here)
            try:
                self._scrub_tick(now)
            except Exception as e:  # noqa: BLE001
                dout("osd", 1)("%s: scrub tick failed: %r",
                               self.name, e)

    def _sweep_pending(self, now: float, max_age: float | None = None) -> None:
        """Fail ops whose sub-ops never completed (peer died mid-op) so
        clients get an error instead of a timeout and tables don't leak."""
        if max_age is None:
            max_age = self.cfg["osd_op_timeout"]
        epoch = self.osdmap.epoch if self.osdmap else 0
        expired_w, expired_r = [], []
        with self._pending_lock:
            for tid, pw in list(self._pending_writes.items()):
                if now - pw.stamp > max_age:
                    self._pending_writes.pop(tid, None)
                    expired_w.append(pw)
            for tid, pr in list(self._pending_reads.items()):
                if now - pr.stamp > max_age:
                    self._pending_reads.pop(tid, None)
                    expired_r.append(pr)
        for pw in expired_w:
            self._note_obj_write(pw.lock_key)  # possibly-torn write
            if pw.lock_key is not None:
                self._ec_cache.invalidate(*pw.lock_key)
            if pw.pq_ctx is not None:
                pw.pq_ctx.finish(0)
            self.messenger.send_message(
                pw.client, MOSDOpReply(pw.client_tid, EIO,
                                       version=pw.version, epoch=epoch))
            self._obj_unlock(pw.lock_key)
        for pr in expired_r:
            self._finish_ec_read(pr)  # decodes if >= k arrived, else err
        self._read_agg.sweep(now, max_age)
        self._sweep_notifies(now, max_age)
        self._sweep_leases(now)
        self._sweep_reservations(now)

    # --------------------------------------- flight recorder / telemetry
    def _note_slow_op(self, op) -> None:
        """OpTracker on_slow hook (fires once per op, off the tracker
        lock): journal the SLOW_OPS complaint as a slow_op cluster
        event.  The op's trace — head-sampled or retroactively
        promoted from the unsampled ring — is already retained by the
        tracker, so the event's trace_id resolves via
        dump_historic_slow_ops / dump_tracing."""
        dur = round(op.age(), 3)
        fields = {"desc": op.desc, "dur_s": dur, "done": bool(op.done)}
        if op.span is not None:
            fields["trace_id"] = op.span.trace_id
            fields["trace_sampled"] = bool(op.span.sampled)
        self.events.emit(
            "slow_op",
            f"slow op: {op.desc} blocked {dur:.3f}s (complaint time "
            f"{self.cfg['osd_op_complaint_time']}s)",
            severity="warn", **fields)

    def _collect_traces(self, trace_ids: set) -> dict:
        """Merged spans per trace id: this daemon's rings plus ONE
        full-ring fetch per peer admin socket in asok_dir (the PR-7
        shared resolver's directory) filtered against the whole id
        set — the round-trip count is O(peers), independent of how
        many slow ops are being resolved.  Deduped by span_id,
        start-ordered per trace."""
        by_tid: dict = {int(t): {} for t in trace_ids}

        def take(spans) -> None:
            for s in spans:
                if not isinstance(s, dict):
                    continue
                m = by_tid.get(s.get("trace_id"))
                if m is not None:
                    m.setdefault(s.get("span_id"), s)

        for tid in by_tid:
            take(self.tracer.spans_for(tid))
        if self.asok_dir and by_tid:
            import glob as _glob
            import os

            from ..utils.admin_socket import admin_request
            for path in sorted(_glob.glob(
                    os.path.join(self.asok_dir, "*.asok"))):
                if os.path.basename(path) == f"{self.name}.asok":
                    continue  # our rings were read directly above
                try:
                    spans = admin_request(path, "dump_tracing")
                except (OSError, RuntimeError):
                    continue  # mon sockets / dead daemons: keep going
                if isinstance(spans, list):
                    take(spans)
        return {tid: sorted(m.values(), key=lambda s: s["start"])
                for tid, m in by_tid.items()}

    def _metrics_registries(self) -> dict:
        """The registries this daemon's metrics history snapshots: its
        own perf counters (op/EC-batch/QoS/trace schema) and its data
        messenger's (dispatch latency, drops)."""
        return {self.name: self.perf,
                self.messenger.perf.name: self.messenger.perf}

    def _report_stats(self, budget: float = 0.5) -> None:
        """Usage/perf summary to the monitor (MMgrReport/PGStats role).
        The store walk is time-budgeted; a partial walk reports what it
        covered with partial=True rather than stalling heartbeats."""
        objects = nbytes = pgs = 0
        pool_objects: dict[int, int] = {}  # autoscaler input (per pool)
        partial = False
        t0 = time.monotonic()
        for cid in self.store.list_collections():
            pgs += 1
            for oid in self.store.list_objects(cid):
                try:
                    nbytes += self.store.stat(cid, oid)["size"]
                    objects += 1
                    if oid.shard > -2:  # user data, not PG meta
                        pool_objects[cid.pool] = \
                            pool_objects.get(cid.pool, 0) + 1
                except Exception:  # noqa: BLE001 - deleted under our feet
                    continue
            if time.monotonic() - t0 > budget:
                partial = True
                break
        # SLOW_OPS feed (the dump_historic_slow_ops -> health mux path):
        # currently-blocked slow ops drive the mon's HEALTH_WARN (they
        # clear when the ops finish); the cumulative count and the worst
        # offenders ride along for the per-daemon health detail
        slow = self.op_tracker.slow_summary()
        # messenger summary (monotonic counters only: queue depth moves
        # both ways and the mon's cluster_* aggregation types counters)
        mperf = self.messenger.perf
        # ship the pending journal WINDOW, not a drained batch: a
        # partition/lossy wire drops reports SILENTLY (deliver()=True),
        # so events re-ship with every report until they age out and
        # the mon dedupes by per-daemon lseq — at-least-once across
        # any outage shorter than osd_event_resend_s
        events = self.events.pending()
        self.messenger.send_message(
            self.mon,
            MStatsReport(self.osd_id,
                         self.osdmap.epoch if self.osdmap else 0,
                         {"pgs": pgs, "objects": objects, "bytes": nbytes,
                          "pool_objects": pool_objects,
                          "partial": partial,
                          # daemon wall clock at send: the mon's skew
                          # estimate (receive_time - sent_at, one-way)
                          # feeds the daemon_clock_skew_s gauge and
                          # trace_tool's waterfall normalization
                          "sent_at": time.time(),
                          "op_w": self.perf.get("op_w"),
                          "op_r": self.perf.get("op_r"),
                          "recovery_push": self.perf.get("recovery_push"),
                          "scrub_errors": self.perf.get("scrub_errors"),
                          "slow_ops": slow["inflight"],
                          "slow_ops_total": slow["total"],
                          "slow_ops_worst": slow["worst"],
                          "msg_dispatched": mperf.get("msg_dispatched"),
                          "msg_drop_wire": mperf.get("msg_drop_wire"),
                          "msg_drop_backpressure":
                              mperf.get("msg_drop_backpressure"),
                          # journal entries ride along (the LogClient
                          # piggyback); the mon merges + dedupes them
                          # into the cluster log
                          "events": events,
                          # metrics-history increments ride the same
                          # at-least-once window (seq-deduped mon-side)
                          "metrics": self.metrics_history.pending(
                              self.cfg["osd_event_resend_s"]),
                          # dynamic perf-query partials: cumulative
                          # seq-tagged snapshots, re-shipped whole
                          # every report (newest-seq-wins mon-side);
                          # key absent entirely when no query is active
                          **({"perf_queries": pq_snap}
                             if (pq_snap :=
                                 self.perf_queries.snapshot())
                             else {})}))
        self.events.prune(self.cfg["osd_event_resend_s"])

    def _handle_ping(self, conn, m: MOSDPing) -> None:
        conn.send(MOSDPingReply(self.osd_id, m.stamp))

    def _handle_ping_reply(self, conn, m: MOSDPingReply) -> None:
        self._hb_last[m.sender] = time.time()

    # ------------------------------------- recovery reservations/throttle
    # Bulk recovery data movement (pushes, shard rebuilds, migrations)
    # funnels through _recovery_op: the op waits for the PG's LOCAL
    # backfill reservation, then a REMOTE grant from its target OSD,
    # then an osd_recovery_max_active initiation slot (paced by
    # osd_recovery_sleep).  Peering/inventory traffic stays immediate —
    # client IO blocks on it (reference serves peering unthrottled).

    def _recovery_prio(self, pgid: PgId) -> int:
        # client IO blocked on missing objects = forced-recovery urgency
        return 255 if self._stale_objects.get(pgid) else 180

    def _rec_weight(self, pgid: PgId, name: str) -> int:
        """Byte weight of one recovery op on `name` (the ROADMAP
        backfill-vs-recovery split): progress items weight by object
        BYTES rather than op count, so ETAs stay accurate when object
        sizes are skewed (one 4 MiB object vs a thousand 4 KiB ones).
        Falls back to weight 1 when no local copy knows the length."""
        cid = CollectionId(pgid.pool, pgid.seed)
        try:  # replicated: the object IS the data
            return max(1, int(self.store.stat(cid,
                                              to_oid(name))["size"]))
        except (NoSuchObject, StoreError):
            pass
        try:  # EC: any local shard's whole-object len attr
            n = self._ec_object_len(pgid, name)
        except Exception:  # noqa: BLE001 - weighting must never block
            n = None
        return max(1, int(n)) if n else 1

    def _recovery_op(self, pgid: PgId, target: int | None, thunk,
                     nbytes: int = 0) -> None:
        prio = self._recovery_prio(pgid)
        nbytes = max(1, int(nbytes))  # byte weight; 1 = size unknown
        storm_opened = False
        with self._pending_lock:
            self._recovery_pg_ops[pgid] = \
                self._recovery_pg_ops.get(pgid, 0) + 1
            # recovery-storm journal accounting: ops scheduled vs done
            # since the storm opened (the progress module's feed),
            # byte-weighted alongside the raw op counts.  A storm
            # closes when the in-flight count drains to zero; a later
            # wave opens a NEW storm (its own progress item).
            rp = self._rec_progress.get(pgid)
            if rp is None:
                rp = self._rec_progress[pgid] = {
                    "total": 0, "done": 0, "total_b": 0, "done_b": 0,
                    "emitted": 0.0, "start_ts": time.time()}
                storm_opened = True
                # recovery storms are ROOT ops for the head sampler:
                # one draw per storm, finished at recovery_done.  The
                # draw + store happen INSIDE the lock that opened the
                # storm — storing after release races a storm that
                # drains to zero on another thread first, orphaning
                # the span (a sampled orphan would sit in the live
                # table until evicted with a FALSE leaked tag).  The
                # tracer lock is a leaf; holding _pending_lock over
                # it cannot deadlock.
                rspan = self.tracer.sample_root(
                    "recovery-storm", pg=self._pgstr(pgid))
                if rspan is not None:
                    self._rec_spans[pgid] = rspan
            rp["total"] += 1
            rp["total_b"] += nbytes
            self._local_waiting.setdefault(pgid, []).append(
                lambda: self._remote_gate(pgid, target, prio, thunk,
                                          nbytes))
        if storm_opened:
            self.events.emit(
                "recovery", f"pg {self._pgstr(pgid)} recovery start",
                event="recovery_start", pg=self._pgstr(pgid),
                done=0, total=rp["total_b"], done_ops=0,
                total_ops=rp["total"], start_ts=rp["start_ts"])
        self._local_reserver.request(
            pgid, prio, lambda: self._flush_local_waiting(pgid))
        if self._local_reserver.held(pgid):
            # request() was a no-op (already held): drain ourselves
            self._flush_local_waiting(pgid)

    def _flush_local_waiting(self, pgid: PgId) -> None:
        with self._pending_lock:
            thunks = self._local_waiting.pop(pgid, [])
        for t in thunks:
            t()

    def _remote_gate(self, pgid: PgId, target: int | None, prio: int,
                     thunk, nbytes: int = 0) -> None:
        if target is None or target == self.osd_id:
            self._recovery_enqueue(pgid, thunk, nbytes)
            return
        key = (pgid, target)
        with self._pending_lock:
            if key in self._remote_held:
                held, first = True, False
            else:
                held = False
                w = self._remote_waiting.setdefault(key, [])
                w.append((thunk, nbytes))
                first = len(w) == 1
                if first:
                    self._remote_pending_at[key] = time.time()
        if held:
            self._recovery_enqueue(pgid, thunk, nbytes)
        elif first:
            self.messenger.send_message(
                f"osd.{target}",
                MRecoveryReserve(pgid, self.osd_id, "request", prio))

    def _handle_recovery_reserve(self, conn, m: MRecoveryReserve) -> None:
        key = (m.pgid, m.from_osd)
        if m.action == "request":
            self._remote_reserver.request(
                key, m.priority,
                lambda: self.messenger.send_message(
                    f"osd.{m.from_osd}",
                    MRecoveryReserve(m.pgid, self.osd_id, "grant")))
        elif m.action == "grant":
            with self._pending_lock:
                self._remote_pending_at.pop(key, None)
                thunks = self._remote_waiting.pop(key, [])
                # a grant landing after a fail-open timeout drained this
                # PG's ops must hand the slot straight back, not leak it
                stale = (not thunks
                         and m.pgid not in self._recovery_pg_ops)
                if not stale:
                    self._remote_held.add(key)
            if stale:
                self.messenger.send_message(
                    f"osd.{m.from_osd}",
                    MRecoveryReserve(m.pgid, self.osd_id, "release"))
                return
            self.events.emit(
                "recovery",
                f"pg {self._pgstr(m.pgid)} remote reservation granted "
                f"by osd.{m.from_osd}",
                event="reservation_grant", pg=self._pgstr(m.pgid),
                target=m.from_osd, waiting_ops=len(thunks))
            for t, nb in thunks:
                self._recovery_enqueue(m.pgid, t, nb)
        elif m.action == "release":
            self._remote_reserver.release(key)

    def _recovery_enqueue(self, pgid: PgId, thunk,
                          nbytes: int = 0) -> None:
        with self._pending_lock:
            self._recovery_q.append((pgid, thunk, nbytes))
        self._pump_recovery()

    def _pump_recovery(self) -> None:
        sleep = self.cfg["osd_recovery_sleep"]
        while True:
            with self._pending_lock:
                if (self._recovery_inflight
                        >= self.cfg["osd_recovery_max_active"]
                        or not self._recovery_q):
                    return
                self._recovery_inflight += 1
                pgid, thunk, nbytes = self._recovery_q.popleft()
            self._sub_epoch.v = 0  # fresh epoch pin per recovery op
            try:
                thunk()
            except Exception:  # noqa: BLE001 - one op must not wedge the pump
                dout("osd", 0)("%s: recovery op failed for %s",
                               self.name, pgid)
            finally:
                with self._pending_lock:
                    self._recovery_inflight -= 1
                self._recovery_op_done(pgid, nbytes)
            if sleep > 0:
                t = threading.Timer(sleep, self._pump_recovery)
                t.daemon = True
                t.start()
                return

    def _recovery_op_done(self, pgid: PgId, nbytes: int = 0) -> None:
        release_local = False
        targets: list[tuple] = []
        ev = None
        rspan = None
        now = time.time()
        with self._pending_lock:
            n = self._recovery_pg_ops.get(pgid, 1) - 1
            rp = self._rec_progress.get(pgid)
            if rp is not None:
                rp["done"] += 1
                rp["done_b"] += max(1, int(nbytes))
            if n <= 0:
                rspan = self._rec_spans.pop(pgid, None)
                self._recovery_pg_ops.pop(pgid, None)
                release_local = True
                targets = [k for k in self._remote_held if k[0] == pgid]
                for k in targets:
                    self._remote_held.discard(k)
                if rp is not None:
                    self._rec_progress.pop(pgid, None)
                    ev = ("recovery_done", dict(rp))
            else:
                self._recovery_pg_ops[pgid] = n
                if rp is not None and now - rp["emitted"] >= \
                        self.cfg["osd_recovery_progress_interval"]:
                    rp["emitted"] = now
                    ev = ("recovery_progress", dict(rp))
        if ev is not None:
            kind, rp = ev
            # done/total ride BYTE-weighted (the progress tracker's
            # percent/ETA feed); the raw op counts stay alongside
            self.events.emit(
                "recovery",
                f"pg {self._pgstr(pgid)} "
                f"{'recovery done' if kind == 'recovery_done' else 'recovering'}"
                f" ({rp['done']}/{rp['total']} ops, "
                f"{rp['done_b']}/{rp['total_b']} weighted bytes)",
                event=kind, pg=self._pgstr(pgid), done=rp["done_b"],
                total=rp["total_b"],
                remaining=rp["total_b"] - rp["done_b"],
                done_ops=rp["done"], total_ops=rp["total"],
                start_ts=rp["start_ts"])
        if rspan is not None:
            if ev is not None and ev[0] == "recovery_done":
                rspan.tag("done_ops", ev[1]["done"])
                rspan.tag("done_bytes", ev[1]["done_b"])
            rspan.finish()
        if release_local:
            self._local_reserver.release(pgid)
            for pg, target in targets:
                self.messenger.send_message(
                    f"osd.{target}",
                    MRecoveryReserve(pg, self.osd_id, "release"))

    def _sweep_reservations(self, now: float) -> None:
        """Heartbeat-thread GC: fail open on remote grants that never
        came (target dead/partitioned — recovery must not wedge), and
        free remote slots whose requesting primary went down."""
        timeout = self.cfg["osd_recovery_reserve_timeout"]
        expired = []
        with self._pending_lock:
            for key, at in list(self._remote_pending_at.items()):
                if now - at > timeout:
                    del self._remote_pending_at[key]
                    self._remote_held.add(key)
                    expired.append((key, self._remote_waiting.pop(key, [])))
        for (pgid, _t), thunks in expired:
            for t, nb in thunks:
                self._recovery_enqueue(pgid, t, nb)
        if self.osdmap is not None:
            for key in self._remote_reserver.keys():
                _pg, requester = key
                o = self.osdmap.osds.get(requester)
                if o is None or not o.up:
                    self._remote_reserver.release(key)

    # ------------------------------------------------------ peering/recovery
    def _osd_alive(self, osd: int) -> bool:
        info = self.osdmap.osds.get(osd) if self.osdmap else None
        return info is not None and info.up

    @staticmethod
    def _pgstr(pgid: PgId) -> str:
        """Journal/operator-facing PG name (the pool.seed-hex form the
        mon's commit descriptions already use)."""
        return f"{pgid.pool}.{pgid.seed:x}"

    def _peer_query_set(self, pgid: PgId, up) -> set[int]:
        """Who a peering round must hear from: the up members PLUS
        every alive OSD that was a member of a maybe-active interval
        since the les fence (PastIntervals prior-set construction,
        PeeringState.h:1485).  An interval with NO surviving member
        contributes the -1 Down sentinel, wedging the PG until a
        revival/new map."""
        peers = {osd for osd in up
                 if osd is not None and osd != self.osd_id}
        pi, les = self._pi(pgid), self._les(pgid)
        prior = pi.prior_osds(since=les, exclude=self.osd_id)
        peers |= {o for o in prior if self._osd_alive(o)}
        for itv in pi.intervals:
            if itv.last < les or not itv.maybe_went_active():
                continue
            members = {o for o in itv.up if o is not None}
            if self.osd_id in members:
                continue  # I was there: I hold that history myself
            if members and not any(self._osd_alive(o)
                                   for o in members):
                dout("osd", 1)("%s: %s down — interval [%d,%d] has "
                               "no surviving member", self.name,
                               pgid, itv.first, itv.last)
                peers.add(-1)
        return peers

    def _start_recovery(self) -> None:
        """Primary-side: inventory peers for my PGs (recovery-lite).  PGs
        wait in 'peering' (IO blocked with EAGAIN) until every alive up
        member has answered, so a freshly-promoted primary cannot serve
        stale data (the GetInfo/GetMissing phase of the peering FSM).

        The query set is the up members PLUS every alive OSD that was a
        member of a maybe-active interval since the last peering fence
        (PastIntervals prior-set construction, PeeringState.h:1485): a
        re-promoted primary must hear from holders that took writes
        while it was away.  An interval with NO surviving member blocks
        the PG entirely (the reference's Down state) until one revives
        or the membership changes."""
        for pool_id, seed, up in self._pools_pgs_for_me():
            if self._primary_of(up) != self.osd_id:
                pg = PgId(pool_id, seed)
                self._peering.pop(pg, None)
                self._fence_round.pop(pg, None)
                self._peer_invs.pop(pg, None)
                self._peer_lcs.pop(pg, None)
                continue
            pgid = PgId(pool_id, seed)
            # fresh round: stale cached inventories/log-positions must
            # not feed rollback decisions (they could roll back writes
            # committed since they were collected) — and a stale shadow
            # fence round must not close against the NEW epoch's round
            # (its answers would fence an epoch whose prior-set queries
            # never completed)
            self._peer_invs.pop(pgid, None)
            self._peer_lcs.pop(pgid, None)
            self._fence_round.pop(pgid, None)
            self._peering_epoch[pgid] = self.osdmap.epoch
            peers = self._peer_query_set(pgid, up)
            if peers:
                self._peering[pgid] = set(peers)
                self.events.emit(
                    "pg", f"pg {self._pgstr(pgid)} peering start",
                    pg=self._pgstr(pgid), state="peering",
                    epoch=self.osdmap.epoch, peers=len(peers),
                    down=-1 in peers)
            else:
                self._peering.pop(pgid, None)
                # trivially peered (no peers to hear from): fence now
                self._set_les(pgid, self.osdmap.epoch)
            ents = self._pglog(pgid).entries()  # one decode
            last = ents[-1].version if ents else 0
            floor_v = ents[0].version if ents else 0
            for osd in peers:
                if osd < 0:
                    continue  # the Down sentinel, not a peer
                self.messenger.send_message(
                    f"osd.{osd}",
                    MPGQuery(pgid, self.osdmap.epoch,
                             primary_last=last,
                             primary_floor=floor_v,
                             force_full=pgid in self._split_fresh))
            # also reconcile my own shard inventory immediately
            self._handle_pg_info(None, self._my_pg_info(pgid))

    def _my_pg_info(self, pgid: PgId) -> MPGInfo:
        ents = self._pglog(pgid).entries()  # one decode for head + evs
        return MPGInfo(pgid, self.osd_id, -2, self._inventory(pgid),
                       dict(self._tombstones.get(pgid, {})),
                       last_complete=self._lc(pgid),
                       head_epoch=ents[-1].epoch if ents else 0,
                       log_evs={e.version: e.epoch for e in ents},
                       les=self._les(pgid))

    def _inventory(self, pgid: PgId) -> dict:
        cid = CollectionId(pgid.pool, pgid.seed)
        out = {}
        try:
            for oid in self.store.list_objects(cid):
                if oid.shard <= -2:
                    continue  # PG metadata (pglog/snapmapper), not user data
                attrs = self.store.getattrs(cid, oid)
                v = attrs.get("v", 0)
                # clones ride every (name, shard) subsystem as vnames
                out[(vname_of(oid), oid.shard)] = v
        except Exception:  # noqa: BLE001 - collection may not exist yet
            pass
        return out

    def _handle_pg_list(self, conn, m: MPGList) -> None:
        """List this PG's live object heads (librados pgls role).
        Primary-only, auth-gated like a read."""
        if self.osdmap is None or m.pgid.pool not in self.osdmap.pools:
            # the client's map may be AHEAD (pool just created): EAGAIN
            # retries; only a pool unknown at its own epoch is ENOENT
            my_epoch = self.osdmap.epoch if self.osdmap else 0
            err = EAGAIN if m.epoch > my_epoch else ENOENT
            conn.send(MPGListReply(m.tid, m.pgid, err, epoch=my_epoch))
            return
        up = self.osdmap.pg_to_up_osds(m.pgid.pool, m.pgid.seed)
        if self._primary_of(up) != self.osd_id:
            conn.send(MPGListReply(m.tid, m.pgid, ESTALE,
                                   epoch=self.osdmap.epoch))
            return
        if m.pgid in self._peering:
            # a freshly promoted primary's store may still be missing
            # not-yet-recovered heads: an authoritative listing must
            # wait for peering, exactly like client IO does
            conn.send(MPGListReply(m.tid, m.pgid, EAGAIN,
                                   epoch=self.osdmap.epoch))
            return
        if self.auth is not None:
            import hmac as _hmac

            from ..auth.cephx import op_proof
            vt = self.auth.verify(m.ticket)
            pool_name = self.osdmap.pools[m.pgid.pool].name
            want = (op_proof(vt.session_key, m.tid, m.pgid.pool,
                             m.pgid.seed, "pgls")
                    if vt is not None else b"")
            if vt is None or not _hmac.compare_digest(want, m.proof) \
                    or not vt.caps.allows("r", pool=pool_name):
                conn.send(MPGListReply(m.tid, m.pgid, EACCES,
                                       epoch=self.osdmap.epoch))
                return
        cid = CollectionId(m.pgid.pool, m.pgid.seed)
        dead = self._tombstones.get(m.pgid, {})
        is_ec = self._is_ec(m.pgid)
        names: set[str] = set()
        try:
            for oid in self.store.list_objects(cid):
                if oid.shard <= -2 or oid.generation >= 0:
                    continue  # PG metadata / snapshot clones
                if oid.name in names:
                    continue
                if is_ec and self._ec_whiteout(m.pgid, oid.name):
                    continue
                if oid.name in dead:
                    # deletes win unless the head was re-written SINCE
                    try:
                        v = int(self.store.getattrs(cid, oid).get("v", 0))
                    except Exception:  # noqa: BLE001
                        v = 0
                    if dead[oid.name] >= v:
                        continue
                names.add(oid.name)
        except Exception:  # noqa: BLE001 - collection vanished mid-walk
            # a partial walk must NOT masquerade as a complete listing
            conn.send(MPGListReply(m.tid, m.pgid, EAGAIN,
                                   epoch=self.osdmap.epoch))
            return
        conn.send(MPGListReply(m.tid, m.pgid, 0, sorted(names),
                               epoch=self.osdmap.epoch))

    def _handle_pg_query(self, conn, m: MPGQuery) -> None:
        if self.osdmap is not None and m.epoch > self._applied_epoch \
                and not self._stop.is_set() \
                and getattr(m, "_defers", 0) < 40:
            # The primary peers at an epoch I have not applied yet — my
            # inventory may be PRE-SPLIT (the child collection does not
            # exist until the map lands), and an empty answer would
            # close the primary's round as "peer holds nothing".  Ask
            # the mon for the map (once) and defer the answer until it
            # lands.  Bounded: after ~4s of deferral answer with what I
            # have — the primary's requery machinery reconciles later,
            # and an unreachable mon must not spin timers forever.
            if not getattr(m, "_defers", 0):
                self.messenger.send_message(
                    self.mon, MMonSubscribe("osdmap",
                                            have_epoch=self.osdmap.epoch))
            m._defers = getattr(m, "_defers", 0) + 1

            def retry(conn=conn, m=m):
                if not self._stop.is_set():
                    self._handle_pg_query(conn, m)
            t = threading.Timer(0.1, retry)
            t.daemon = True
            t.start()
            return
        # ONE log decode feeds head/floor/evs (the peering hot path —
        # every query/info otherwise re-reads the whole omap window)
        ents = self._pglog(m.pgid).entries()
        lc = self._lc(m.pgid)
        last = ents[-1].version if ents else 0
        head_epoch = ents[-1].epoch if ents else 0
        # LEAN fast path (log-based GetLog): my log is gapless through lc
        # and the primary can delta-replay from there — skip the
        # O(objects) inventory walk entirely.  head_epoch rides along so
        # the primary can detect a fork at my head (same version, other
        # interval) and demand the full log.
        inv = None
        if lc == 0:
            inv = self._inventory(m.pgid)  # walked once, reused below
        if (not m.force_full and m.primary_last >= 0
                and lc == last
                and lc <= m.primary_last
                and (lc + 1 >= m.primary_floor or lc == m.primary_last)
                and (lc > 0 or not inv)):
            # (lc == 0 with a NON-empty collection excluded: a freshly
            # merged/reset PG has an empty LOG but full data — a lean
            # "in sync at v0" answer would hide every object it holds.
            # A truly empty lc==0 PG stays lean: forcing inventories
            # there made the primary schedule spurious rebuilds that
            # raced scrub repair.)
            conn.send(MPGInfo(m.pgid, self.osd_id, -2, {},
                              dict(self._tombstones.get(m.pgid, {})),
                              last_complete=lc, lean=True,
                              head_epoch=head_epoch,
                              les=self._les(m.pgid)))
            return
        if inv is None:
            inv = self._inventory(m.pgid)
        conn.send(MPGInfo(m.pgid, self.osd_id, -2, inv,
                          dict(self._tombstones.get(m.pgid, {})),
                          last_complete=lc, head_epoch=head_epoch,
                          log_evs={e.version: e.epoch for e in ents},
                          les=self._les(m.pgid)))

    def _rearm_peering(self, pgid: PgId, block: bool = True) -> None:
        """Run another peering round.  block=True (a fork surfaced —
        possibly after the round closed): the PG re-peers with IO
        re-blocked until a round completes CLEAN.  block=False (routine
        recovery completion): the les fence still needs one clean round
        of answers, but client IO keeps flowing — the answers drain a
        shadow waiting set instead of the peering gate."""
        up = self.osdmap.pg_to_up_osds(pgid.pool, pgid.seed)
        if self._primary_of(up) != self.osd_id:
            return
        # the SAME prior-set construction as _start_recovery: a re-armed
        # round dropping the Down sentinel (or unanswered prior holders)
        # would close clean, fence, and trim the very interval evidence
        # that wedged the PG
        peers = self._peer_query_set(pgid, up)
        if not peers:
            return
        if not block and -1 in peers:
            # a Down interval outlaws the fence anyway: nothing to do
            return
        self._peering_epoch[pgid] = self.osdmap.epoch
        if block:
            self._fence_round.pop(pgid, None)
            self._peering[pgid] = set(peers)
            self.events.emit(
                "pg", f"pg {self._pgstr(pgid)} peering start (re-peer)",
                pg=self._pgstr(pgid), state="peering",
                epoch=self.osdmap.epoch, peers=len(peers), repeer=True)
        else:
            self._fence_round[pgid] = set(peers)
        # queries go out DIRECTLY: _requery_pg's debounce could swallow
        # a second rearm inside its window, leaving an armed wait set
        # nobody will ever drain (client IO wedged until the next epoch)
        ents = self._pglog(pgid).entries()
        last = ents[-1].version if ents else 0
        floor_v = ents[0].version if ents else 0
        for osd in peers:
            if osd < 0:
                continue  # the Down sentinel, not a peer
            self.messenger.send_message(
                f"osd.{osd}",
                MPGQuery(pgid, self.osdmap.epoch,
                         primary_last=last, primary_floor=floor_v,
                         force_full=block))

    def _merge_peer_log(self, pgid: PgId, m: MPGInfo) -> bool:
        """Divergent-entry merge (PGLog.h:1344 _merge_divergent_entries
        re-shaped): the same version logged under two different epochs
        is a fork — the entry from the NEWER interval is authoritative
        (the older interval's primary lost quorum before committing it,
        or the newer interval could never have re-minted the version).
        Discard the loser's tail from the fork point and let normal
        recovery re-push the authority's content.  Returns True when a
        fork was found and resolution scheduled (the caller must not
        schedule normal recovery off this info)."""
        pl = self._pglog(pgid)
        ents = pl.entries()  # one decode feeds every rule below
        my_evs = {e.version: e.epoch for e in ents}
        my_last = ents[-1].version if ents else 0
        my_les = self._les(pgid)
        if m.lean:
            # lean infos carry only the head; a fork at the peer's head
            # version is detectable, but the fork POINT needs its whole
            # log — demand a full answer and resolve on that
            if m.head_epoch <= 0 or m.last_complete <= 0:
                return False
            mine = my_evs.get(m.last_complete, 0)
            same_v_fork = mine > 0 and mine != m.head_epoch
            # a head BEYOND my log from an interval older than my fence
            # is a phantom tail (never committed) — also needs full log
            phantom_head = (m.last_complete > my_last
                            and 0 < m.head_epoch < my_les)
            # MY entries beyond the peer's head from intervals older
            # than the peer's fence are phantoms of my own — delta-
            # pushing them to the lean peer would resurrect a dead
            # interval's writes; discard them instead (resolvable
            # directly, no full log needed)
            mine_ph = sorted(v for v, e in my_evs.items()
                             if v > m.last_complete and 0 < e < m.les)
            if mine_ph:
                d = mine_ph[0]
                dout("osd", 1)("%s: %s MY phantom tail from v%d "
                               "(epoch %d < lean peer les %d): "
                               "discarding", self.name, pgid, d,
                               my_evs[d], m.les)
                self._rearm_peering(pgid)
                self._handle_pg_rollback(
                    None, MPGRollback(pgid, "", -3, d - 1,
                                      divergent=True, max_epoch=m.les))
                self._handle_pg_info(None, m)
                return True
            if not same_v_fork and not phantom_head:
                return False
            dout("osd", 1)("%s: %s head fork at v%d with osd.%d "
                           "(epoch %d vs %d, les %d): demanding full "
                           "log", self.name, pgid, m.last_complete,
                           m.from_osd, m.head_epoch, mine, my_les)
            self._rearm_peering(pgid)
            self.messenger.send_message(
                f"osd.{m.from_osd}",
                MPGQuery(pgid, self.osdmap.epoch,
                         primary_last=my_last,
                         primary_floor=ents[0].version if ents else 0,
                         force_full=True))
            return True
        if not m.log_evs:
            return False
        conflicts = sorted(
            v for v, pe in m.log_evs.items()
            if pe and my_evs.get(v, 0) and my_evs[v] != pe)
        if conflicts:
            d = conflicts[0]
            if my_evs[d] > m.log_evs[d]:
                # the peer's tail is the dead interval's: it must
                # discard from the fork point; its post-rollback info
                # re-enters here and normal recovery re-pushes mine
                dout("osd", 1)("%s: %s osd.%d divergent from v%d "
                               "(epoch %d < %d): discarding its tail",
                               self.name, pgid, m.from_osd, d,
                               m.log_evs[d], my_evs[d])
                self._rearm_peering(pgid)
                self.messenger.send_message(
                    f"osd.{m.from_osd}",
                    MPGRollback(pgid, "", -3, d - 1, divergent=True,
                                max_epoch=my_evs[d]))
                return True
            # I am the divergent one (re-promoted after my interval
            # died): discard my own tail, then re-process this info
            # with fresh state — peer objects I now miss get pulled
            dout("osd", 1)("%s: %s MY log divergent from v%d (epoch "
                           "%d < %d): discarding my tail", self.name,
                           pgid, d, my_evs[d], m.log_evs[d])
            self._rearm_peering(pgid)
            self._handle_pg_rollback(
                None, MPGRollback(pgid, "", -3, d - 1, divergent=True,
                                  max_epoch=m.log_evs[d]))
            self._handle_pg_info(None, m)
            return True
        # phantom tails (find_best_info's les-first rule): entries one
        # side holds BEYOND the other's head, stamped with an interval
        # older than the other's les fence, never committed — an
        # interval went active without them.  Adopting them would
        # resurrect writes whose absence was already served to readers.
        phantom_peer = sorted(v for v, pe in m.log_evs.items()
                              if v > my_last and 0 < pe < my_les)
        if phantom_peer and self._stale_objects.get(pgid):
            # my own log is known-incomplete (pulls outstanding): my
            # fence cannot judge anyone — wait for recovery to finish
            phantom_peer = []
        if phantom_peer:
            d = phantom_peer[0]
            dout("osd", 1)("%s: %s osd.%d phantom tail from v%d "
                           "(epoch %d < les %d): discarding", self.name,
                           pgid, m.from_osd, d, m.log_evs[d], my_les)
            self._rearm_peering(pgid)
            self.messenger.send_message(
                f"osd.{m.from_osd}",
                MPGRollback(pgid, "", -3, d - 1, divergent=True,
                            max_epoch=my_les))
            return True
        peer_last = max(m.log_evs) if m.log_evs else 0
        phantom_mine = sorted(v for v, e in my_evs.items()
                              if v > peer_last and 0 < e < m.les)
        if phantom_mine:
            d = phantom_mine[0]
            dout("osd", 1)("%s: %s MY phantom tail from v%d (epoch %d "
                           "< peer les %d): discarding", self.name,
                           pgid, d, my_evs[d], m.les)
            self._rearm_peering(pgid)
            self._handle_pg_rollback(
                None, MPGRollback(pgid, "", -3, d - 1, divergent=True,
                                  max_epoch=m.les))
            self._handle_pg_info(None, m)
            return True
        return False

    def _handle_pg_info(self, conn, m: MPGInfo) -> None:
        """Primary: compare a peer's state against authority and schedule
        recovery — by log replay (delta) when the peer's last-complete is
        inside our log window, by inventory compare otherwise."""
        if self.osdmap is None or m.pgid.pool not in self.osdmap.pools:
            return
        pool = self.osdmap.pools[m.pgid.pool]
        up = self.osdmap.pg_to_up_osds(m.pgid.pool, m.pgid.seed)
        if self._primary_of(up) != self.osd_id:
            return
        peer_inv = m.objects
        my_inv = self._inventory(m.pgid)
        # merge tombstone knowledge both ways (deletes must win races)
        for name, v in m.tombstones.items():
            self._record_tombstone(m.pgid, name, v)
        dead = self._tombstones.get(m.pgid, {})
        for (_name, _s), v in peer_inv.items():
            self._pg_versions[m.pgid] = max(
                self._pg_versions.get(m.pgid, 0), v)
        waiting = self._peering.get(m.pgid)
        done_peering = False
        if waiting is not None:
            waiting.discard(m.from_osd)
            if not waiting:
                del self._peering[m.pgid]
                done_peering = True
        fence = self._fence_round.get(m.pgid)
        fence_done = False
        if fence is not None:
            fence.discard(m.from_osd)
            if not fence:
                del self._fence_round[m.pgid]
                fence_done = True  # shadow round closed clean
        if m.last_complete >= 0:
            self._peer_lcs.setdefault(m.pgid, {})[m.from_osd] = \
                m.last_complete
        if m.from_osd != self.osd_id and \
                self._merge_peer_log(m.pgid, m):
            # a fork was found: resolution (divergent-head discard +
            # re-push from authority) is in flight; scheduling normal
            # recovery (or a lean checkpoint) off this info would bless
            # the divergent log.  The les fence deliberately does NOT
            # advance here: trimming the interval history before the
            # fork is resolved would lose the evidence that the prior
            # holder must be consulted again after a crash.
            return
        # peering bookkeeping: note objects I am behind on (they stay
        # blocked until the pull lands) — AFTER the fork check, so a
        # divergent peer's doomed versions never wedge the stale gate
        my_best: dict[str, int] = {}
        for (name, _s), v in my_inv.items():
            my_best[name] = max(my_best.get(name, -1), v)
        stale = self._stale_objects.setdefault(m.pgid, {})
        for (name, _s), v in peer_inv.items():
            if v > my_best.get(name, -1) and dead.get(name, -1) < v:
                stale[name] = max(stale.get(name, 0), v)
        if done_peering:
            # one full post-split round has closed: lean peering is
            # trustworthy again
            self._split_fresh.discard(m.pgid)
            self.events.emit(
                "pg", f"pg {self._pgstr(m.pgid)} peering done",
                pg=self._pgstr(m.pgid),
                state="degraded" if stale else "active",
                epoch=self._peering_epoch.get(m.pgid, 0),
                stale_objects=len(stale))
        if (done_peering or fence_done) and not stale:
            # every member (incl. prior-interval holders) answered a
            # round that closed with no fork and nothing known-missing:
            # the PG is peered — fence + trim the history.  The fence
            # advances to the epoch of the round that COMPLETED (not
            # the live map epoch: a straggler racing a map push must
            # not fence an epoch whose prior-set query never ran), and
            # ONLY via a closing round: fork resolution re-arms the
            # round (below), so a fence can never be taken off the
            # hollow mid-resolution state — round 4's first cut did,
            # and the bogus fence made the phantom rule discard
            # committed writes on a temp-primary.
            self._set_les(m.pgid,
                          self._peering_epoch.get(m.pgid, 0))
        if m.lean:
            self._delta_recover(m.pgid, pool, up, m.from_osd,
                                m.last_complete, dead)
            if m.last_complete >= self._pglog(m.pgid).last_version():
                self._maybe_clear_pg_temp(m.pgid, m.from_osd)
        else:
            self._peer_invs.setdefault(m.pgid, {})[m.from_osd] = peer_inv
            if pool.kind == "ec":
                scheduled = self._recover_ec(m.pgid, pool, up, m.from_osd,
                                             peer_inv, my_inv, dead)
            else:
                scheduled = self._recover_replicated(
                    m.pgid, up, m.from_osd, peer_inv, my_inv, dead)
            if scheduled == 0 and m.from_osd != self.osd_id and \
                    m.from_osd in [u for u in up if u is not None]:
                # verified in sync: checkpoint so future peering rounds
                # take the lean path
                self.messenger.send_message(
                    f"osd.{m.from_osd}",
                    MPGPush(m.pgid, -2, {}, {},
                            checkpoint=self._pglog(m.pgid).last_version()))
                self._maybe_clear_pg_temp(m.pgid, m.from_osd)
        if pool.kind == "ec" and (done_peering
                                  or m.pgid not in self._peering):
            # reconcile on completion AND on post-peering updates: a
            # pre-rollback inventory arriving late must not re-wedge the
            # stale gate on a version that was rolled back.  Debounced —
            # a recovery batch triggers one pass, not one per info.
            now = time.monotonic()
            if done_peering or \
                    now - self._reconcile_at.get(m.pgid, 0.0) > 0.25:
                self._reconcile_at[m.pgid] = now
                # the lc-based PG-level rollback only trusts a COMPLETE
                # fresh round (done_peering): partial or cached lc views
                # must never roll back writes committed since collection
                self._reconcile_ec(m.pgid, pool, up,
                                   lc_authority=done_peering)

    def _maybe_clear_pg_temp(self, pgid: PgId, peer: int) -> None:
        """If a pg_temp override is active and the REAL primary (the up
        set's head with no temp applied) just verified in sync, the
        override has served its purpose — ask the mon to clear it."""
        key = (pgid.pool, pgid.seed)
        if not self.osdmap.pg_temp.get(key):
            return
        real = self.osdmap.pg_to_up_osds(pgid.pool, pgid.seed,
                                         ignore_temp=True)
        if peer == self._primary_of(real):
            self.messenger.send_message(
                self.mon, MOSDPGTemp(self.osd_id, pgid, []))

    def _delta_recover(self, pgid: PgId, pool, up, peer: int,
                       peer_lc: int, dead: dict) -> None:
        """Log-based delta recovery: replay MY entries after the peer's
        last-complete and push exactly those objects (PGLog delta resync
        instead of whole-inventory backfill)."""
        pl = self._pglog(pgid)
        entries = pl.entries_after(peer_lc)
        if not entries:
            return
        self.perf.inc("recovery_delta")
        names: dict[str, int] = {}
        removes: dict[str, int] = {}
        for e in entries:
            if e.op == "remove":
                removes[e.oid] = max(removes.get(e.oid, 0), e.version)
                names.pop(e.oid, None)
            else:
                names[e.oid] = max(names.get(e.oid, -1), e.version)
        for name, v in list(names.items()):
            if dead.get(name, -1) >= v:
                removes[name] = dead[name]
                del names[name]
        if removes and peer != self.osd_id:
            self.messenger.send_message(
                f"osd.{peer}", MPGPush(pgid, -3, {}, removes,
                                       trace=self._rec_trace(pgid)))
        if pool.kind == "ec":
            for shard, osd in enumerate(up):
                if osd != peer:
                    continue
                for name, v in names.items():
                    self._recovery_op(
                        pgid, peer,
                        lambda name=name, shard=shard, v=v:
                        self._rebuild_shard(pgid, name, shard, peer, v),
                        nbytes=self._rec_weight(pgid, name))
        elif peer != self.osd_id:
            def push_delta(pgid=pgid, peer=peer, names=dict(names)):
                cid = CollectionId(pgid.pool, pgid.seed)
                push = {}
                for name, v in names.items():
                    obj = to_oid(name)
                    try:
                        data, attrs = self._read_obj_raw(cid, obj)
                        push[name] = (int(attrs.get("v", v)), data, None,
                                      self.store.omap_get(cid, obj),
                                      self._push_attrs(attrs))
                    except NoSuchObject:
                        continue
                if push:
                    self.perf.inc("recovery_push", len(push))
                    self.messenger.send_message(
                        f"osd.{peer}",
                        MPGPush(pgid, -1, push,
                                trace=self._rec_trace(pgid)))

            self._recovery_op(pgid, peer, push_delta,
                              nbytes=sum(self._rec_weight(pgid, n)
                                         for n in names))

    def _recover_replicated(self, pgid, up, peer, peer_inv, my_inv,
                            dead) -> int:
        if peer == self.osd_id:
            return 0
        peer_is_member = peer in [u for u in up if u is not None]
        cid = CollectionId(pgid.pool, pgid.seed)
        push, pull, deletes = [], [], {}
        for (name, shard), v in my_inv.items():
            if dead.get(name, -1) >= v:
                continue  # deleted; never resurrect
            if not peer_is_member:
                continue  # demoted holders only feed pulls, not pushes
            pv = peer_inv.get((name, shard), -1)
            if pv < v:
                push.append((name, shard))
        for (name, shard), pv in peer_inv.items():
            if dead.get(name, -1) >= pv:
                deletes[name] = dead[name]  # peer missed the remove
            elif my_inv.get((name, shard), -1) < pv:
                pull.append(name)
        # locally apply missed removes too
        for (name, shard), v in my_inv.items():
            if dead.get(name, -1) >= v:
                obj = to_oid(name, shard)
                if self.store.exists(cid, obj):
                    self.store.queue_transaction(
                        Transaction().remove(cid, obj))
        if push or deletes:
            self.perf.inc("recovery_push", len(push))

            def push_objs(pgid=pgid, peer=peer, push=list(push),
                          deletes=dict(deletes)):
                # read at EXECUTION time: the op may queue behind
                # reservations, and a stale closure would pin memory and
                # push bytes the receiver's version guard just discards
                out = {}
                for name, shard in push:
                    obj = to_oid(name, shard)
                    try:
                        data, attrs = self._read_obj_raw(cid, obj)
                        out[name] = (int(attrs.get("v", 0)), data, None,
                                     self.store.omap_get(cid, obj),
                                     self._push_attrs(attrs))
                    except NoSuchObject:
                        continue
                if out or deletes:
                    self.messenger.send_message(
                        f"osd.{peer}",
                        MPGPush(pgid, -1, out, deletes,
                                trace=self._rec_trace(pgid)))

            self._recovery_op(pgid, peer, push_objs,
                              nbytes=sum(self._rec_weight(pgid, n)
                                         for n, _s in push))
        if pull:
            # the primary itself is behind (e.g. revived empty): pull,
            # and ask the mon to keep the caught-up peer serving in the
            # meantime (pg_temp — clients follow the acting set).
            # Pulls unblock client IO, so they ride the reservation
            # queue at forced priority (stale objects exist by now).
            # pulled objects have no local copy to size: weight by name
            # count (1 each) rather than pretending to know their bytes
            self._recovery_op(
                pgid, peer,
                lambda pull=list(pull): self.messenger.send_message(
                    f"osd.{peer}",
                    MPGPull(pgid, pull, trace=self._rec_trace(pgid))),
                nbytes=len(pull))
            if peer_is_member:
                temp = [peer] + [u for u in up
                                 if u is not None and u != peer]
                self.messenger.send_message(
                    self.mon, MOSDPGTemp(self.osd_id, pgid, temp))
        return len(push) + len(deletes) + len(pull)

    def _handle_pg_pull(self, conn, m: MPGPull) -> None:
        # a sampled storm's pull serve becomes a child span of the
        # requesting primary's storm root (the carried wire ctx)
        span_ctx = (self.tracer.start("recovery-pull-serve",
                                      parent=tuple(m.trace),
                                      pg=self._pgstr(m.pgid),
                                      n_objects=len(m.names))
                    if m.trace else contextlib.nullcontext())
        with span_ctx:
            cid = CollectionId(m.pgid.pool, m.pgid.seed)
            push = {}
            for name in m.names:
                obj = to_oid(name)
                try:
                    data, attrs = self._read_obj_raw(cid, obj)
                    push[name] = (int(attrs.get("v", 0)), data, None,
                                  self.store.omap_get(cid, obj),
                                  self._push_attrs(attrs))
                except NoSuchObject:
                    continue
            if push:
                conn.send(MPGPush(m.pgid, -1, push, force=m.force,
                                  trace=tuple(m.trace)))

    def _recover_ec(self, pgid, pool, up, peer, peer_inv, my_inv,
                    dead) -> int:
        """Rebuild missing shards on `peer` from k survivors.  Returns
        how much recovery work was scheduled (0 = peer verified in
        sync)."""
        scheduled = 0
        # authority object set: union of all shard inventories we know of
        # (primary's own + this peer's); keyed by name -> version
        names: dict[str, int] = {}
        for (name, _s), v in list(my_inv.items()) + list(peer_inv.items()):
            names[name] = max(names.get(name, -1), v)
        # deletes win: drop dead names from recovery, purge stray shards
        deletes = {}
        for name in list(names):
            if dead.get(name, -1) >= names[name]:
                deletes[name] = dead[name]
                del names[name]
        if deletes:
            cid = CollectionId(pgid.pool, pgid.seed)
            for name in deletes:
                for (iname, shard), _v in list(my_inv.items()):
                    if iname == name:
                        obj = ObjectId(name, shard=shard)
                        if self.store.exists(cid, obj):
                            self.store.queue_transaction(
                                Transaction().remove(cid, obj))
            if peer != self.osd_id:
                self.messenger.send_message(
                    f"osd.{peer}", MPGPush(pgid, -3, {}, deletes,
                                           trace=self._rec_trace(pgid)))
        scheduled += len(deletes)
        if peer not in [u for u in up if u is not None]:
            # demoted holder (notify path): migrate its stranded shards to
            # the current position holders; the version gate on the push
            # side dedups if the holder already caught up
            for (name, shard), v in peer_inv.items():
                if dead.get(name, -1) >= v or shard >= len(up):
                    continue
                holder = up[shard]
                if holder is None or holder == peer:
                    continue
                self._recovery_op(
                    pgid, holder,
                    lambda name=name, shard=shard, v=v, holder=holder:
                    self._fetch_and_push(pgid, name, shard, peer,
                                         holder, v),
                    nbytes=self._rec_weight(pgid, name))
                scheduled += 1
            return scheduled
        for shard, osd in enumerate(up):
            if osd == peer:
                for name, version in names.items():
                    if peer_inv.get((name, shard), -1) >= version:
                        continue  # peer current for its shard
                    self._recovery_op(
                        pgid, peer,
                        lambda name=name, shard=shard, version=version:
                        self._rebuild_shard(pgid, name, shard, peer,
                                            version),
                        nbytes=self._rec_weight(pgid, name))
                    scheduled += 1
            elif osd == self.osd_id:
                # the peer's inventory may reveal objects where MY OWN
                # shard is missing/stale (e.g. primary revived empty)
                for name, version in names.items():
                    if my_inv.get((name, shard), -1) >= version:
                        continue
                    self._recovery_op(
                        pgid, None,
                        lambda name=name, shard=shard, version=version:
                        self._rebuild_shard(pgid, name, shard,
                                            self.osd_id, version),
                        nbytes=self._rec_weight(pgid, name))
                    scheduled += 1
        return scheduled

    def _reconcile_ec(self, pgid: PgId, pool, up,
                      lc_authority: bool = False) -> None:
        """After a peering round collected full inventories: find torn
        objects — ones whose newest version has FEWER than k shards (a
        partial write the stripe can never decode) — and roll the ahead
        shards back to the newest version k shards can serve (the EC
        rollback/rollforward decision of PGLog + rollback generations)."""
        codec = self._pool_codec(pgid.pool)
        invs = dict(self._peer_invs.get(pgid, {}))
        invs[self.osd_id] = self._inventory(pgid)
        holders = {shard: osd for shard, osd in enumerate(up)
                   if osd is not None}
        # PG-LEVEL torn detection from log positions (covers lean peers
        # whose inventories never traveled): the stripe can only decode
        # through the k-th highest last-complete; any member logged past
        # that point applied writes the stripe can never serve
        lcs = dict(self._peer_lcs.get(pgid, {}))
        lcs[self.osd_id] = self._lc(pgid)
        # only members with log EVIDENCE count: a freshly promoted spare
        # (lc 0, empty log) never saw the writes — its emptiness must not
        # drag the decode point down and roll back COMMITTED data on the
        # survivors (that would destroy the very shards recovery needs)
        member_lcs = {osd: lc for osd, lc in lcs.items()
                      if osd in holders.values() and lc > 0}
        if lc_authority and len(member_lcs) >= codec.k:
            decode_point = sorted(member_lcs.values(),
                                  reverse=True)[codec.k - 1]
            # versions past the decode point are being rolled back: stop
            # gating reads on pushes that will never come
            stale = self._stale_objects.get(pgid)
            if stale:
                for name, ver in list(stale.items()):
                    if ver > decode_point:
                        stale.pop(name)
            for osd, lc in member_lcs.items():
                if lc <= decode_point:
                    continue
                dout("osd", 1)("%s: %s member osd.%d logged to v%d past "
                               "decode point v%d: rolling back",
                               self.name, pgid, osd, lc, decode_point)
                msg = MPGRollback(pgid, "", -3, decode_point)
                if osd == self.osd_id:
                    self._handle_pg_rollback(None, msg)
                else:
                    self.messenger.send_message(f"osd.{osd}", msg)
        # per object: shard -> newest version any inventory reports
        per_obj: dict[str, dict[int, int]] = {}
        for _osd, inv in invs.items():
            for (name, shard), v in inv.items():
                if shard < 0:
                    continue
                cur = per_obj.setdefault(name, {})
                cur[shard] = max(cur.get(shard, -1), v)
        dead = self._tombstones.get(pgid, {})
        for name, vs in per_obj.items():
            if not vs or dead.get(name, -1) >= max(vs.values()):
                continue
            vmax = max(vs.values())
            if sum(1 for v in vs.values() if v == vmax) >= codec.k:
                continue  # newest version decodable: roll-forward path
            # newest version k shards hold EXACTLY (shards at a newer
            # version carry different bytes and only help if they roll
            # back, which is what we're about to ask of them)
            target = max((v for v in set(vs.values())
                          if sum(1 for x in vs.values() if x == v)
                          >= codec.k), default=None)
            if target is None or target == vmax:
                continue  # nothing decodable — scrub/EIO territory
            dout("osd", 1)("%s: torn EC object %s/%s: rolling %s back "
                           "to v%d", self.name, pgid, name,
                           [s for s, v in vs.items() if v > target],
                           target)
            for shard, v in vs.items():
                if v <= target or shard not in holders:
                    continue
                holder = holders[shard]
                msg = MPGRollback(pgid, name, shard, target)
                if holder == self.osd_id:
                    self._handle_pg_rollback(None, msg)
                else:
                    self.messenger.send_message(f"osd.{holder}", msg)

    def _handle_pg_rollback(self, conn, m: MPGRollback) -> None:
        """Shard holder: undo applies on `oid` past to_version using the
        pglog pre-images; without pre-images, drop the shard copy so
        recovery rebuilds it from the version k shards agree on."""
        self.perf.inc("rollbacks")
        cid = CollectionId(m.pgid.pool, m.pgid.seed)
        pl = self._pglog(m.pgid)
        if m.oid == "":
            # PG-level: undo EVERYTHING this shard logged past the
            # decode point (first entry past the point per object gives
            # the version to return to)
            span = sorted((e for e in pl.entries()
                           if e.version > m.to_version),
                          key=lambda e: e.version)
            groups: dict[tuple, list[LogEntry]] = {}
            for e in span:
                groups.setdefault((e.oid, e.shard), []).append(e)
            for (oid, shard), group in groups.items():
                if m.divergent and m.max_epoch > 0 and \
                        group[-1].epoch >= m.max_epoch:
                    # this object's NEWEST write belongs to an interval
                    # that survived the fork (e.g. committed after a
                    # rejoin): its content must be kept — only the
                    # phantom entries below it are scrubbed from the
                    # log (log hygiene without data loss)
                    from .pglog import _key
                    phantom = [e for e in group
                               if e.epoch < m.max_epoch]
                    if phantom:
                        self.store.queue_transaction(
                            Transaction().omap_rmkeys(
                                cid, PGLOG_OID,
                                [_key(e.version) for e in phantom]))
                    continue
                # PG-level undo is PRE-IMAGE ONLY: dropping a full-write
                # shard here could destroy the only copy of its position
                # without verifying the target version is decodable —
                # that call belongs to the per-object reconcile, which
                # checks k-support first.  EXCEPT divergent discard: the
                # tail being dropped belongs to a dead interval and
                # never committed — the authority re-pushes its own
                # content, so dropping is the point (PGLog.h:1344)
                self._rollback_one(m.pgid, pl, cid, oid, shard,
                                   group[0].prev_version,
                                   allow_drop=m.divergent)
        else:
            self._rollback_one(m.pgid, pl, cid, m.oid, m.shard,
                               m.to_version)
        if self._lc(m.pgid) > m.to_version:
            self._set_lc(m.pgid, m.to_version)
        # surface the new state to the primary so it can verify/rebuild
        if self.osdmap is None or m.pgid.pool not in self.osdmap.pools:
            return
        up = self.osdmap.pg_to_up_osds(m.pgid.pool, m.pgid.seed)
        primary = self._primary_of(up)
        if primary is None:
            return
        info = self._my_pg_info(m.pgid)
        if primary == self.osd_id:
            self._handle_pg_info(None, info)
        else:
            self.messenger.send_message(f"osd.{primary}", info)

    def _rollback_one(self, pgid: PgId, pl: PGLog, cid, oid: str,
                      shard: int, to_version: int,
                      allow_drop: bool = True) -> None:
        """Undo one object's applies past to_version: pre-images when
        stashed, else (allow_drop) drop the shard copy for rebuild;
        to_version < 0 means the object was CREATED past the point —
        drop it."""
        obj = ObjectId(oid, shard=shard)
        ok = False
        if to_version >= 0:
            try:
                ok = pl.rollback_object(oid, shard, to_version)
            except Exception as e:  # noqa: BLE001
                dout("osd", 1)("%s: rollback %s/%s failed: %r", self.name,
                               pgid, oid, e)
        if not ok and not allow_drop:
            return
        if not ok:
            span = [e for e in pl.entries_for(oid)
                    if e.shard == shard and e.version > max(to_version, -1)]
            tx = Transaction()
            if self.store.exists(cid, obj):
                tx.remove(cid, obj)
            if span:
                from .pglog import _key
                tx.omap_rmkeys(cid, PGLOG_OID,
                               [_key(e.version) for e in span])
            if tx.ops:
                self.store.queue_transaction(tx)
        self._ec_cache.invalidate(pgid, oid)
        dout("osd", 2)("%s: rolled %s/%s shard %d back to v%d (%s)",
                       self.name, pgid, oid, shard, to_version,
                       "pre-images" if ok else "dropped for rebuild")

    def _fetch_and_push(self, pgid, name, shard, src: int, dst: int,
                        version: int) -> None:
        """Copy one shard from a demoted holder to its current position
        holder (direct migration — no decode needed)."""
        tid = next(self._tids)
        # storm ctx captured NOW: the storm accounting can drain (the
        # scheduling thunk returns before the async reads do) and pop
        # the root span before on_done fires
        tctx = self._rec_trace(pgid)

        def on_done(pr) -> None:
            if pr is None or shard not in pr.chunks:
                return
            total = self._ec_total_len(pr)
            self.perf.inc("recovery_push")
            omap = pr.omaps.get(shard)
            extra = (self._push_attrs(pr.shard_attrs[shard])
                     if shard in pr.shard_attrs else {})
            self.messenger.send_message(
                f"osd.{dst}",
                MPGPush(pgid, shard,
                        {name: (version, pr.chunks[shard].tobytes(),
                                total, omap, extra)},
                        trace=tctx))

        pr = _PendingRead(None, 0, pgid.pool, name, total_shards=1,
                          on_done=on_done)
        self._pending_reads[tid] = pr
        self.messenger.send_message(
            f"osd.{src}",
            MSubRead(tid, pgid, name, shard, klass="recovery"))

    def _ec_narrow_on(self) -> bool:
        return str(self.cfg["osd_ec_repair_narrow"]).lower() != "off"

    def _rebuild_fetch_set(self, codec, shard: int,
                           fan: list) -> set[int] | None:
        """Positions a single-shard rebuild actually needs to read —
        the codec's minimum_to_decode set when it is NARROWER than a
        k-wide read (LRC: the lost chunk's locality group; SHEC: one
        shingle window).  None = read every holder (plain RS reads k+
        survivors anyway, and multi-failure/scrub paths want the full
        inventory)."""
        avail = [s for s, src in enumerate(fan)
                 if src is not None and s != shard
                 and s < codec.chunk_count]
        try:
            need = codec.minimum_to_decode([shard], avail)
        except Exception:  # noqa: BLE001 - undecodable now: wide fan
            return None
        need = [s for s in need if s != shard]
        if len(need) >= codec.k:
            return None
        return set(need)

    def _rebuild_shard(self, pgid, name, shard, peer, version,
                       force: bool = False, wide: bool = False) -> None:
        """Reconstruct one shard from survivors, then push it.

        Repair-bandwidth-optimal fetch (osd_ec_repair_narrow): a plain
        single-failure rebuild asks the codec what it MINIMALLY needs —
        a sub-chunk codec at the MSR point (CLAY, d=k+m-1) reads only
        the alpha/q repair-plane byte ranges from each helper
        (_rebuild_shard_subchunk), a locality code reads one narrow
        group (LRC: |group| < k shards; SHEC: one shingle) — and only
        falls back to the k-wide whole-shard fan-out (``wide=True``,
        today's behavior) when the narrow read cannot produce a
        version-agreed decodable set."""
        up = self.osdmap.pg_to_up_osds(pgid.pool, pgid.seed)
        codec = self._pool_codec(pgid.pool)
        narrow = self._ec_narrow_on() and not force and not wide
        # shard -> source OSD: the position holder when it (plausibly)
        # has the shard, else ANY holder the collected inventories
        # revealed — after a PG split the shards sit on strays and
        # wrong positions until recovery completes, and a purely
        # positional fan-out would never gather k survivors.
        invs = dict(self._peer_invs.get(pgid, {}))
        invs[self.osd_id] = self._inventory(pgid)
        fan = []
        for s, u in enumerate(up):
            src = None
            if u is not None and u != peer and \
                    (u not in invs or (name, s) in invs[u]):
                src = u
            if src is None:
                src = next((osd_id for osd_id, inv in invs.items()
                            if osd_id != peer and (name, s) in inv),
                           None)
            fan.append(src)
        if narrow and self._rebuild_shard_subchunk(pgid, name, shard,
                                                   peer, version, fan,
                                                   up, codec):
            return
        fetch = self._rebuild_fetch_set(codec, shard, fan) \
            if narrow else None
        if fetch is not None:
            # the lost position itself stays in the fan: a stray still
            # holding the shard supplies it directly (no decode at all)
            fan = [src if (s in fetch or s == shard) else None
                   for s, src in enumerate(fan)]
        tid = next(self._tids)
        # storm ctx captured NOW, not at push time: the storm's op
        # accounting drains when the scheduling thunks return (the
        # shard reads are async), which can pop the root span before
        # on_done runs
        tctx = self._rec_trace(pgid)

        def retry_wide() -> None:
            # narrow read insufficient (stale/missing group member):
            # one wide retry — the full fan-out sees every holder
            self.perf.inc("recovery_wide_retries")
            self._rebuild_shard(pgid, name, shard, peer, version,
                                force=force, wide=True)

        def enough(cand: dict) -> bool:
            if shard in cand and not force:
                return True
            return (fetch <= set(cand) if fetch is not None
                    else len(cand) >= codec.k)

        def on_done(pr) -> None:
            if pr is None or not enough(pr.chunks):
                # not enough survivors NOW; a narrow read retries wide,
                # else a later peering/requery round retries (never
                # leave a hole with no retry scheduled)
                if fetch is not None:
                    retry_wide()
                else:
                    self._requery_pg(pgid)
                return
            chunks = pr.chunks
            push_version = version
            if pr.shard_vers:
                # rebuild only from a version-AGREED survivor set: mixing
                # a stale shard into the decode would fabricate garbage
                # stamped with the new version
                vmax = max(pr.shard_vers.values())
                cand = {s: c for s, c in chunks.items()
                        if pr.shard_vers.get(s) == vmax}
                if enough(cand):
                    chunks = cand
                    # stamp what the agreed set actually decodes — NOT
                    # the requested version: a rebuild scheduled from a
                    # pre-rollback inventory would otherwise fabricate
                    # old bytes labelled with the rolled-back version,
                    # re-tearing the stripe it was meant to heal
                    push_version = vmax
                elif fetch is not None:
                    retry_wide()
                    return
                else:
                    self._requery_pg(pgid, force_full=True)
                    return  # no consistent set yet; the requery retries
            if shard in chunks and not force:
                rebuilt = chunks[shard]
            else:
                # scrub repair must NOT trust the (possibly corrupt)
                # existing shard copy: always re-derive it
                chunks = {i: c for i, c in chunks.items() if i != shard} \
                    if force else chunks
                if not enough(chunks):
                    self._requery_pg(pgid)
                    return
                try:
                    out = self._ec_decode(codec, [shard], dict(chunks))
                except Exception:  # noqa: BLE001 - narrow set fell short
                    if fetch is not None:
                        retry_wide()
                        return
                    raise
                rebuilt = out[shard]
                self.perf.inc("recovery_fetch_bytes",
                              sum(c.nbytes for c in chunks.values()))
                self.perf.inc("recovery_rebuilt_bytes", rebuilt.nbytes)
                if fetch is not None:
                    self.perf.inc("recovery_narrow_rebuilds")
            total = self._ec_total_len(pr)
            self.perf.inc("recovery_push")
            # metadata travels with the rebuild — from a SURVIVING
            # shard's reply when available (the pushing primary's own
            # copy may itself be the one missing)
            omap, extra = self._ec_meta_for(pgid, name)
            for s in chunks:
                if s in pr.omaps:
                    omap = pr.omaps[s]
                    break
            src = next((s for s in chunks if s in pr.shard_attrs), None)
            if src is not None:
                extra = self._push_attrs(pr.shard_attrs[src])
            self.messenger.send_message(
                f"osd.{peer}",
                MPGPush(pgid, shard,
                        {name: (push_version, rebuilt.tobytes(), total,
                                omap, extra)},
                        force=force, trace=tctx))

        pr = _PendingRead(None, 0, pgid.pool, name,
                          total_shards=sum(1 for u in fan
                                           if u is not None),
                          on_done=on_done)
        self._pending_reads[tid] = pr
        self._fan_shard_reads(tid, pgid, name, fan, klass="recovery")

    def _subchunk_repair_plan(self, pgid: PgId, name: str, shard: int,
                              fan: list, up: list, peer: int,
                              codec) -> dict | None:
        """Fetch plan for a sub-chunk (CLAY MSR) rebuild of `shard`, or
        None when the bandwidth-optimal repair does not apply: needs
        the REQUIRE_SUB_CHUNKS repair surface at the MSR point (m == q,
        i.e. d = k+m-1), a live source for EVERY other position (the
        column solve consumes all n-1 helpers), a known object length,
        and a chunk size the plane grid divides.  A position the
        inventory scan left sourceless still tries its live map holder
        — a lean peering round simply hasn't shipped that inventory
        yet, and a holder genuinely missing the object answers ENOENT,
        which retries wide (the designed fallback)."""
        from ..ec.interface import Flags as ECFlags
        if not (codec.get_flags() & ECFlags.REQUIRE_SUB_CHUNKS):
            return None
        if not hasattr(codec, "repair_chunk") \
                or getattr(codec, "q", None) != codec.m:
            return None
        n = codec.chunk_count
        if shard >= n or len(up) < n:
            return None
        sources: dict[int, int] = {}
        for s in range(n):
            if s == shard:
                continue
            src = fan[s] if s < len(fan) else None
            if src is None and up[s] is not None and up[s] != peer:
                src = up[s]
            if src is None:
                return None
            sources[s] = src
        helpers = sorted(sources)
        if len(helpers) != n - 1:
            return None
        total = self._ec_object_len(pgid, name)
        if not total:
            return None
        si = self._pool_stripe(pgid.pool)
        # sub-chunk layout: the write path encodes the WHOLE write's
        # shard stream as ONE codec chunk (streams are (k, rows*cs)),
        # and the degraded-read decode splits whole streams the same
        # way — so the repair plan's plane grid spans the whole shard
        # stream too (sub-chunk = stream/alpha), NOT per stripe row.
        # Both are exact for full-stream writes, the only write shape
        # REQUIRE_SUB_CHUNKS pools take through this daemon.
        shard_len = si.object_chunk_size(total)
        alpha = codec.alpha
        if shard_len <= 0 or shard_len % alpha:
            return None
        sub = shard_len // alpha
        planes = codec.repair_planes(shard)
        # contiguous plane indices merge into few ranged extents
        runs: list[tuple[int, int]] = []
        start = prev = planes[0]
        for z in planes[1:]:
            if z == prev + 1:
                prev = z
                continue
            runs.append((start, prev - start + 1))
            start = prev = z
        runs.append((start, prev - start + 1))
        extents = [(z0 * sub, cnt * sub) for z0, cnt in runs]
        return {"helpers": helpers, "sources": sources,
                "extents": extents, "planes": planes,
                "shard_len": shard_len, "sub": sub}

    def _rebuild_shard_subchunk(self, pgid, name, shard, peer, version,
                                fan: list, up: list, codec) -> bool:
        """Bandwidth-optimal single-shard rebuild for sub-chunk codecs
        (CLAY at d = k+m-1): fetch only the alpha/q repair-plane byte
        ranges from each of the n-1 helpers — (n-1)/q of the bytes a
        k-wide whole-shard read moves — and solve the lost chunk with
        the codec's repair path (folded across the storm by the
        batcher).  Returns False when the plan does not apply (caller
        falls through to the plain fan-out); any mid-flight
        insufficiency retries wide."""
        plan = self._subchunk_repair_plan(pgid, name, shard, fan, up,
                                          peer, codec)
        if plan is None:
            return False
        # the rebuilt shard must land WITH its replicated metadata, and
        # ranged replies ship only the verification attrs — so the
        # metadata must come from a local shard copy; without one the
        # wide whole-shard read (which carries omap+attrs) is the
        # correct path
        omap, extra = self._ec_meta_for(pgid, name)
        if not extra and omap is None:
            return False
        helpers, extents = plan["helpers"], plan["extents"]
        sub, P = plan["sub"], len(plan["planes"])
        per_helper = P * sub
        tid = next(self._tids)
        # storm ctx captured now (see _rebuild_shard)
        tctx = self._rec_trace(pgid)

        def retry_wide() -> None:
            self.perf.inc("recovery_wide_retries")
            self._rebuild_shard(pgid, name, shard, peer, version,
                                wide=True)

        def on_done(pr) -> None:
            if pr is None or not all(
                    h in pr.chunks and pr.chunks[h].size == per_helper
                    for h in helpers):
                retry_wide()
                return
            push_version = version
            if pr.shard_vers:
                # the MSR solve mixes every helper's symbols: ALL n-1
                # must agree on one version (cf. the agreed-k rule)
                vers = {pr.shard_vers.get(h) for h in helpers}
                if len(vers) != 1 or None in vers:
                    retry_wide()
                    return
                push_version = vers.pop()
            sub_arrs = {h: np.asarray(pr.chunks[h],
                                      dtype=np.uint8).reshape(P, sub)
                        for h in helpers}
            try:
                rebuilt = self._ec_repair(codec, shard, sub_arrs,
                                          plan["shard_len"])
            except Exception:  # noqa: BLE001 - solve failed: go wide
                retry_wide()
                return
            self.perf.inc("recovery_fetch_bytes",
                          per_helper * len(helpers))
            self.perf.inc("recovery_rebuilt_bytes", rebuilt.nbytes)
            self.perf.inc("recovery_subchunk_rebuilds")
            self.perf.inc("recovery_push")
            total = self._ec_total_len(pr)
            self.messenger.send_message(
                f"osd.{peer}",
                MPGPush(pgid, shard,
                        {name: (push_version, rebuilt.tobytes(), total,
                                omap, extra)},
                        trace=tctx))

        pr = _PendingRead(None, 0, pgid.pool, name,
                          total_shards=len(helpers), on_done=on_done,
                          want_all=True)
        self._pending_reads[tid] = pr
        # repair-plane extents ride the per-(peer, pg) aggregator when
        # read coalescing is on (ROADMAP wide-codes follow-on (c)): a
        # storm rebuilding many objects sends ONE MSubReadN per helper
        # per window — recovery-class lanes, so the peer still queues
        # the batch under its recovery reservation — instead of one
        # MSubRead per (object, helper)
        coalesce = self._ec_read_coalesce_on(pgid.pool)
        for s in helpers:
            osd = plan["sources"][s]
            if osd == self.osd_id:
                self._deliver_local_shard_read(tid, pgid, name, s,
                                               extents)
            elif coalesce:
                self._read_agg.submit(f"osd.{osd}", tid, pgid, name, s,
                                      list(extents), klass="recovery")
            else:
                self.messenger.send_message(
                    f"osd.{osd}",
                    MSubRead(tid, pgid, name, s, list(extents),
                             klass="recovery"))
        return True

    def _ec_meta_for(self, pgid: PgId, name: str):
        """(omap, user attrs) from MY shard copy of an EC object —
        rides recovery pushes so rebuilt shards carry the replicated
        metadata (the ECOmapJournal recovery contract)."""
        up = self.osdmap.pg_to_up_osds(pgid.pool, pgid.seed)
        myshard = up.index(self.osd_id) if self.osd_id in up else 0
        cid = CollectionId(pgid.pool, pgid.seed)
        obj = to_oid(name, myshard)
        try:
            omap = self.store.omap_get(cid, obj)
            extra = self._push_attrs(self.store.getattrs(cid, obj))
        except NoSuchObject:
            return None, {}
        return (omap or None), extra

    def _push_attrs(self, attrs: dict) -> dict:
        """Attrs worth carrying on a recovery push: everything the apply
        side does not recompute (v/len/d, and the compression extent
        metadata cz/crl — pushes ship raw bytes and the receiver's
        _apply_write re-decides compression for its own store) —
        SnapSets, whiteouts, user attrs survive recovery this way."""
        return {k: v for k, v in attrs.items()
                if k not in ("v", "len", "d", "cz", "crl")}

    def _handle_pg_push(self, conn, m: MPGPush) -> None:
        # per-push child span of the sender's storm root (the carried
        # wire ctx): a sampled recovery storm's merged waterfall shows
        # every push apply cross-daemon (ROADMAP telemetry (b))
        if m.trace:
            with self.tracer.start("recovery-push-apply",
                                   parent=tuple(m.trace),
                                   pg=self._pgstr(m.pgid),
                                   n_objects=len(m.objects),
                                   n_deletes=len(m.deletes),
                                   nbytes=sum(len(p[1]) for p
                                              in m.objects.values())):
                self._apply_pg_push(conn, m)
            return
        self._apply_pg_push(conn, m)

    def _apply_pg_push(self, conn, m: MPGPush) -> None:
        cid = CollectionId(m.pgid.pool, m.pgid.seed)
        for name, version in m.deletes.items():
            self._record_tombstone(m.pgid, name, version)
            base, gen = split_vname(name)
            for oid in (list(self.store.list_objects(cid))
                        if cid in self.store.list_collections() else []):
                # a head tombstone must not nuke clones (they die only by
                # snap trim, under their own vname tombstones)
                if oid.name == base and (oid.generation == gen
                                         or (gen < 0
                                             and oid.generation < 0)):
                    self.store.queue_transaction(
                        Transaction().remove(cid, oid))
        dead = self._tombstones.get(m.pgid, {})
        for name in m.objects:
            self._ec_cache.invalidate(m.pgid, name)
        for name, payload in m.objects.items():
            if dead.get(name, -1) >= payload[0]:
                continue  # delete raced ahead of this push
            # never clobber a NEWER local copy with a stale recovery push
            # (a rebuild computed from a pre-overwrite inventory snapshot);
            # scrub repairs force through (same-version corrupt copies)
            shard_id = m.shard if m.shard >= 0 else -1
            if not m.force:
                try:
                    cur = self.store.getattrs(cid, to_oid(name, shard_id))
                    if int(cur.get("v", -1)) >= payload[0]:
                        continue
                except NoSuchObject:
                    pass
            if m.shard >= 0:
                version, data, total = payload[0], payload[1], payload[2]
                attrs = {"v": version}
                if total is not None:
                    attrs["len"] = total
                if len(payload) > 4 and payload[4]:
                    attrs.update(payload[4])  # user attrs ride along
                self._apply_write(m.pgid, name, m.shard, data, attrs,
                                  omap=payload[3]
                                  if len(payload) > 3 else None)
            else:
                version, data = payload[0], payload[1]
                omap = payload[3] if len(payload) > 3 else None
                attrs = {"v": version, "len": len(data)}
                if len(payload) > 4 and payload[4]:
                    attrs.update(payload[4])  # ss/wh/user attrs
                self._apply_write(m.pgid, name, -1, data, attrs,
                                  omap=omap)
        self._pg_versions[m.pgid] = max(
            self._pg_versions.get(m.pgid, 0),
            max((p[0] for p in m.objects.values()), default=0))
        # pushed objects are no longer stale-blocked
        stale = self._stale_objects.get(m.pgid)
        if stale:
            for name in list(m.objects) + list(m.deletes):
                stale.pop(name, None)
            if not stale and m.pgid not in self._peering:
                # recovery just drained the last known-missing object:
                # run one clean (non-blocking) round so the les fence
                # (which only advances via a closing round) catches up
                self._rearm_peering(m.pgid, block=False)
        if m.checkpoint >= 0 and m.checkpoint > self._lc(m.pgid):
            # the primary verified we need nothing through this version:
            # future peering rounds can take the lean (log) path
            self._set_lc(m.pgid, m.checkpoint)
        # if I am this PG's primary, newly-landed data may need forwarding
        # to members whose inventories were processed earlier: re-query,
        # debounced so a recovery batch triggers one round, not O(objects)
        self._requery_pg(m.pgid)

    def _requery_pg(self, pgid: PgId, force_full: bool = False) -> None:
        """Primary: re-run the inventory exchange for one PG (debounced)
        so recovery reconciles stale/missing shards without waiting for
        the next map epoch.  force_full demands inventories even from
        in-sync (lean) peers — needed when a version-split read shows
        the PG is torn and reconciliation requires the full picture."""
        if self.osdmap is None or pgid.pool not in self.osdmap.pools:
            return
        now = time.monotonic()
        # force_full has its own debounce lane so a routine requery just
        # before it cannot swallow the full-inventory demand
        key = (pgid, force_full)
        if now - self._requery_at.get(key, 0.0) < 0.2:
            # DEFER, never drop: a swallowed kick that happens to be the
            # last event in a recovery chain leaves a permanent fixed
            # point (the thrash missing_shard hole) — re-fire once the
            # window passes instead
            def fire(key=key, pgid=pgid, force_full=force_full):
                with self._pending_lock:
                    self._requery_timers.pop(key, None)
                if not self._stop.is_set():
                    self._requery_pg(pgid, force_full)
            with self._pending_lock:
                if key not in self._requery_timers:
                    t = threading.Timer(0.25, fire)
                    t.daemon = True
                    self._requery_timers[key] = t
                    t.start()
            return
        with self._pending_lock:
            stale = self._requery_timers.pop(key, None)
        if stale is not None:
            stale.cancel()  # superseded: must not re-fire redundantly
        up = self.osdmap.pg_to_up_osds(pgid.pool, pgid.seed)
        if self._primary_of(up) != self.osd_id:
            return
        self._requery_at[key] = now
        ents = self._pglog(pgid).entries()  # one decode
        last = ents[-1].version if ents else 0
        floor_v = ents[0].version if ents else 0
        for osd in up:
            if osd is not None and osd != self.osd_id:
                self.messenger.send_message(
                    f"osd.{osd}",
                    MPGQuery(pgid, self.osdmap.epoch,
                             primary_last=last,
                             primary_floor=floor_v,
                             force_full=force_full))
