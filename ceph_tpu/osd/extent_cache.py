"""ECExtentCache: hot shard extents for the partial-write pipeline.

The capability of the reference's ECExtentCache
(src/osd/ECExtentCache.{h,cc}: an LRU of shard extents backing RMW
reads so overlapping partial writes don't re-read what the pipeline
just touched).  The primary consults it before fanning old-byte reads
for a parity-delta overwrite and refills it with the bytes it reads
and writes; anything that mutates shard state outside the primary's
write pipeline (recovery pushes, rollbacks, removes, map changes)
invalidates.

Device plane (the "device-resident stripe plane" promotion): when the
cache is constructed with a DeviceArena (ec/arena.py), each host run
can carry an HBM mirror keyed ``(pgid, oid, shard, run_off, gen)``
(``gen`` = the shard extent's write generation, so a racing re-stage
of pre-overwrite bytes can never land under a serveable key) — built
LAZILY on the first ``read_device`` (non-jax pools never stage a
byte), then served as zero-copy device slices so RMW old-byte reads
and hot-object degraded reads feed the ECBatcher's folded launches
without a host->device hop per op.  The host bytes remain the source
of truth: any mutation (a ``write`` merging runs, every invalidation
path above, host-LRU eviction) DROPS the device mirror, and an
arena-budget eviction (``ec_arena_max_bytes``) merely degrades the
next device read back to a one-time re-stage — the invalidation
contract is unchanged, the device copy can only ever lag into a miss,
never into stale bytes.
"""

from __future__ import annotations

import collections
import threading

#: the read scale-out counter schema (hot-tier admission telemetry,
#: lease grant/revoke flow, balanced non-primary serving) — registered
#: zeroed at OSD boot so the exporter and the prom recording rules see
#: a standing series per daemon before any read lands
READ_SCALEOUT_COUNTERS = (
    "ec_read_tier_hit", "ec_read_tier_miss",
    "ec_read_tier_admit", "ec_read_tier_evict",
    "read_lease_grant", "read_lease_ride", "read_lease_revoke",
    "balanced_read_serve", "balanced_read_bounce")


def register_read_scaleout_counters(perf) -> None:
    """Register the read scale-out counters on ``perf`` (idempotent:
    re-adding an existing counter would RESET it)."""
    for name in READ_SCALEOUT_COUNTERS:
        if not perf.has(name):
            perf.add(name)


class _Extents:
    """Non-overlapping sorted (off, bytearray, gen) runs for one shard.

    Each run carries the WRITE GENERATION that produced its current
    bytes (a per-shard monotonic counter stamped on the merged run):
    the device plane folds it into the arena key so a reader that
    snapshotted run bytes before a concurrent overwrite can only ever
    re-stage them under the OLD generation's key — never serveable
    again, aged out by the arena LRU — instead of resurrecting stale
    bytes under the live key.  Per-RUN (not per-shard) stamping keeps
    a write to one run from orphaning every other run's arena mirror."""

    __slots__ = ("runs", "gen")

    def __init__(self):
        self.runs: list[tuple[int, bytearray, int]] = []
        self.gen = 0

    def nbytes(self) -> int:
        return sum(len(b) for _o, b, _g in self.runs)

    def write(self, off: int, data: bytes) -> tuple[list[int], int]:
        """Insert/overwrite [off, off+len) and merge adjacent runs.
        Returns (offsets of runs absorbed/replaced by the merge, the
        merged run's offset) so the caller can drop exactly the device
        mirrors whose host bytes changed."""
        self.gen += 1
        end = off + len(data)
        merged_off = off
        buf = bytearray(data)
        keep: list[tuple[int, bytearray, int]] = []
        dirty: list[int] = []
        for roff, rbuf, rgen in self.runs:
            rend = roff + len(rbuf)
            if rend < off or roff > end:
                keep.append((roff, rbuf, rgen))  # untouched: gen kept
                continue
            # overlap/adjacency: fold the old run around the new bytes
            dirty.append(roff)
            if roff < merged_off:
                buf = rbuf[: merged_off - roff] + buf
                merged_off = roff
            if rend > end:
                buf = buf + rbuf[len(rbuf) - (rend - end):]
                end = rend
        keep.append((merged_off, buf, self.gen))
        keep.sort(key=lambda t: t[0])
        self.runs = keep
        return dirty, merged_off

    def read(self, off: int, length: int) -> bytes | None:
        """The exact bytes if FULLY covered, else None."""
        end = off + length
        for roff, rbuf, _g in self.runs:
            if roff <= off and off + length <= roff + len(rbuf):
                return bytes(rbuf[off - roff: end - roff])
        return None

    def covering(self, off: int,
                 length: int) -> tuple[int, bytearray, int] | None:
        """(run offset, run buffer, run gen) of the run fully covering
        the range, else None — the device plane stages WHOLE runs so
        every later slice of the run is a free device view.  The buffer
        is returned WITHOUT copying (the hit path must stay O(1)):
        writes never mutate a run buffer in place — they build fresh
        ones and replace the list — so a reference snapshotted under
        the cache lock stays content-stable outside it."""
        for roff, rbuf, rgen in self.runs:
            if roff <= off and off + length <= roff + len(rbuf):
                return roff, rbuf, rgen
        return None


class ECExtentCache:
    def __init__(self, max_bytes: int = 8 << 20, arena=None,
                 on_evict=None):
        self._max = max_bytes
        self._bytes = 0
        # eviction telemetry hook: called once per whole-object LRU
        # eviction (capacity pressure only — invalidations are not
        # evictions).  Must be cheap and lock-free; fired OUTSIDE the
        # cache lock.
        self._on_evict = on_evict
        self._lock = threading.Lock()
        # key: (pgid, oid) -> shard -> _Extents; LRU by key
        self._lru: collections.OrderedDict = collections.OrderedDict()
        # object version the cached bytes correspond to (the pipeline
        # updates it with every write it caches; external mutation
        # paths invalidate instead)
        self._ver: dict = {}
        # whole-object logical length at that version, when the
        # pipeline knows it (write paths carry total_len) — a
        # cache-served client read needs it to trim stripe padding
        self._len: dict = {}
        # device plane: HBM mirrors of host runs, keyed
        # (pgid, oid, shard, run_off); None = host-only cache
        self._arena = arena

    def pgids(self) -> set:
        """PGs with cached entries (map-change invalidation scans only
        these, not the whole cluster's placement)."""
        with self._lock:
            return {k[0] for k in self._lru}

    def version(self, pgid, oid: str) -> int | None:
        with self._lock:
            return self._ver.get((pgid, oid))

    def object_len(self, pgid, oid: str) -> int | None:
        """Whole-object length at the cached version (None when no
        write-through recorded it)."""
        with self._lock:
            return self._len.get((pgid, oid))

    def read(self, pgid, oid: str, shard: int, off: int,
             length: int) -> bytes | None:
        with self._lock:
            shards = self._lru.get((pgid, oid))
            if shards is None:
                return None
            ext = shards.get(shard)
            data = ext.read(off, length) if ext is not None else None
            if data is None:
                return None
            self._lru.move_to_end((pgid, oid))
            return data

    def read_device(self, pgid, oid: str, shard: int, off: int,
                    length: int):
        """The covered range as a DEVICE array slice, staging the whole
        covering run into the arena on first touch (one h2d per run
        mutation, then every hit is a zero-copy device view) — or None
        when no arena is attached or the range isn't covered.  Callers
        must treat the result as immutable and never donate it (the
        arena owns the buffer)."""
        if self._arena is None:
            return None
        with self._lock:
            shards = self._lru.get((pgid, oid))
            ext = shards.get(shard) if shards is not None else None
            cov = ext.covering(off, length) if ext is not None else None
            if cov is None:
                return None
            roff, rbuf, gen = cov
            self._lru.move_to_end((pgid, oid))
        # stage OUTSIDE the cache lock (a device_put under it would
        # serialize every reader behind the transfer).  The key carries
        # the shard extent's write GENERATION: a concurrent write bumps
        # it, so if it races this put, the stale bytes land under the
        # old-gen key — unreachable (every later read asks for the new
        # gen and re-stages) and aged out by the arena LRU.  A
        # same-length overwrite without the gen would pass a shape
        # check and serve stale bytes forever.  rbuf is content-stable
        # outside the lock (covering's no-mutation contract).
        key = (pgid, oid, shard, roff, gen)
        dev = self._arena.get(key)
        if dev is None:
            dev = self._arena.put(key, rbuf)
        start = off - roff
        return dev[start: start + length]

    def write(self, pgid, oid: str, shard: int, off: int,
              data: bytes, version: int | None = None,
              length: int | None = None) -> None:
        if not data:
            return
        drop_prefixes: set = set()
        drop_objs: set = set()
        with self._lock:
            key = (pgid, oid)
            shards = self._lru.get(key)
            if shards is None:
                shards = {}
                self._lru[key] = shards
            ext = shards.setdefault(shard, _Extents())
            self._bytes -= ext.nbytes()
            dirty, merged_off = ext.write(off, data)
            self._bytes += ext.nbytes()
            # host bytes changed: the absorbed runs' mirrors AND the
            # merged run's (its off may equal an absorbed one's) are
            # stale device copies now — matched by (pg, oid, shard,
            # run_off) PREFIX, gen-agnostic, so mirrors staged under
            # any older generation drop too
            drop_prefixes = {(pgid, oid, shard, o)
                             for o in set(dirty) | {merged_off}}
            if version is not None:
                self._ver[key] = version
            if length is not None:
                self._len[key] = length
            self._lru.move_to_end(key)
            evictions = 0
            while self._bytes > self._max and self._lru:
                k, dropped = self._lru.popitem(last=False)
                self._ver.pop(k, None)
                self._len.pop(k, None)
                self._bytes -= sum(e.nbytes() for e in dropped.values())
                # host LRU evicted the whole object: every arena mirror
                # of it (any shard/run/gen) goes with it
                drop_objs.add((k[0], k[1]))
                evictions += 1
        if self._arena is not None and (drop_prefixes or drop_objs):
            self._arena.drop_where(
                lambda k: k[:4] in drop_prefixes or k[:2] in drop_objs)
        if self._on_evict is not None:
            for _ in range(evictions):
                self._on_evict()

    def drop_shards(self, pgid, oid: str, shards) -> None:
        """Drop specific shards' cached runs (host AND device mirrors),
        leaving the object's other shards cached.  The parity-delta
        write path needs this: deltas are applied shard-locally by the
        parity holders, so the primary never learns the resulting
        parity bytes — cached parity runs from an earlier full/row
        write would claim stale bytes at the advanced version."""
        shards = set(shards)
        with self._lock:
            ent = self._lru.get((pgid, oid))
            if ent is not None:
                for s in [s for s in ent if s in shards]:
                    self._bytes -= ent.pop(s).nbytes()
                if not ent:
                    self._lru.pop((pgid, oid), None)
                    self._ver.pop((pgid, oid), None)
                    self._len.pop((pgid, oid), None)
        if self._arena is not None:
            self._arena.drop_where(
                lambda k: k[0] == pgid and k[1] == oid
                and k[2] in shards)

    def invalidate(self, pgid, oid: str | None = None) -> None:
        with self._lock:
            if oid is not None:
                key = (pgid, oid)
                dropped = self._lru.pop(key, None)
                self._ver.pop(key, None)
                self._len.pop(key, None)
                if dropped:
                    self._bytes -= sum(e.nbytes()
                                       for e in dropped.values())
            else:
                for key in [k for k in self._lru if k[0] == pgid]:
                    dropped = self._lru.pop(key)
                    self._ver.pop(key, None)
                    self._len.pop(key, None)
                    self._bytes -= sum(e.nbytes()
                                       for e in dropped.values())
        if self._arena is not None:
            # the invalidation CONTRACT extends to the device plane:
            # a recovery push / rollback / remove / map change must
            # evict the HBM copy with the host one
            if oid is not None:
                self._arena.drop_where(
                    lambda k: k[0] == pgid and k[1] == oid)
            else:
                self._arena.drop_where(lambda k: k[0] == pgid)

    def clear(self) -> None:
        with self._lock:
            self._lru.clear()
            self._ver.clear()
            self._len.clear()
            self._bytes = 0
        if self._arena is not None:
            self._arena.clear()
