"""ECExtentCache: hot shard extents for the partial-write pipeline.

The capability of the reference's ECExtentCache
(src/osd/ECExtentCache.{h,cc}: an LRU of shard extents backing RMW
reads so overlapping partial writes don't re-read what the pipeline
just touched).  The primary consults it before fanning old-byte reads
for a parity-delta overwrite and refills it with the bytes it reads
and writes; anything that mutates shard state outside the primary's
write pipeline (recovery pushes, rollbacks, removes, map changes)
invalidates.
"""

from __future__ import annotations

import collections
import threading


class _Extents:
    """Non-overlapping sorted (off -> bytearray) runs for one shard."""

    __slots__ = ("runs",)

    def __init__(self):
        self.runs: list[tuple[int, bytearray]] = []

    def nbytes(self) -> int:
        return sum(len(b) for _o, b in self.runs)

    def write(self, off: int, data: bytes) -> None:
        """Insert/overwrite [off, off+len) and merge adjacent runs."""
        end = off + len(data)
        merged_off = off
        buf = bytearray(data)
        keep: list[tuple[int, bytearray]] = []
        for roff, rbuf in self.runs:
            rend = roff + len(rbuf)
            if rend < off or roff > end:
                keep.append((roff, rbuf))
                continue
            # overlap/adjacency: fold the old run around the new bytes
            if roff < merged_off:
                buf = rbuf[: merged_off - roff] + buf
                merged_off = roff
            if rend > end:
                buf = buf + rbuf[len(rbuf) - (rend - end):]
                end = rend
        keep.append((merged_off, buf))
        keep.sort(key=lambda t: t[0])
        self.runs = keep

    def read(self, off: int, length: int) -> bytes | None:
        """The exact bytes if FULLY covered, else None."""
        end = off + length
        for roff, rbuf in self.runs:
            if roff <= off and off + length <= roff + len(rbuf):
                return bytes(rbuf[off - roff: end - roff])
        return None


class ECExtentCache:
    def __init__(self, max_bytes: int = 8 << 20):
        self._max = max_bytes
        self._bytes = 0
        self._lock = threading.Lock()
        # key: (pgid, oid) -> shard -> _Extents; LRU by key
        self._lru: collections.OrderedDict = collections.OrderedDict()
        # object version the cached bytes correspond to (the pipeline
        # updates it with every write it caches; external mutation
        # paths invalidate instead)
        self._ver: dict = {}

    def pgids(self) -> set:
        """PGs with cached entries (map-change invalidation scans only
        these, not the whole cluster's placement)."""
        with self._lock:
            return {k[0] for k in self._lru}

    def version(self, pgid, oid: str) -> int | None:
        with self._lock:
            return self._ver.get((pgid, oid))

    def read(self, pgid, oid: str, shard: int, off: int,
             length: int) -> bytes | None:
        with self._lock:
            shards = self._lru.get((pgid, oid))
            if shards is None:
                return None
            ext = shards.get(shard)
            data = ext.read(off, length) if ext is not None else None
            if data is None:
                return None
            self._lru.move_to_end((pgid, oid))
            return data

    def write(self, pgid, oid: str, shard: int, off: int,
              data: bytes, version: int | None = None) -> None:
        if not data:
            return
        with self._lock:
            key = (pgid, oid)
            shards = self._lru.get(key)
            if shards is None:
                shards = {}
                self._lru[key] = shards
            ext = shards.setdefault(shard, _Extents())
            self._bytes -= ext.nbytes()
            ext.write(off, data)
            self._bytes += ext.nbytes()
            if version is not None:
                self._ver[key] = version
            self._lru.move_to_end(key)
            while self._bytes > self._max and self._lru:
                k, dropped = self._lru.popitem(last=False)
                self._ver.pop(k, None)
                self._bytes -= sum(e.nbytes() for e in dropped.values())

    def invalidate(self, pgid, oid: str | None = None) -> None:
        with self._lock:
            if oid is not None:
                key = (pgid, oid)
                dropped = self._lru.pop(key, None)
                self._ver.pop(key, None)
                if dropped:
                    self._bytes -= sum(e.nbytes()
                                       for e in dropped.values())
                return
            for key in [k for k in self._lru if k[0] == pgid]:
                dropped = self._lru.pop(key)
                self._ver.pop(key, None)
                self._bytes -= sum(e.nbytes() for e in dropped.values())

    def clear(self) -> None:
        with self._lock:
            self._lru.clear()
            self._ver.clear()
            self._bytes = 0
