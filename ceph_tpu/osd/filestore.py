"""FileStore: a durable, crash-consistent ObjectStore backend.

The capability slot of the reference's BlueStore (SURVEY.md §2.6: atomic
transactions via a write-ahead journal, crash-resume replay, per-blob
checksums verified on read — ref src/os/bluestore/BlueStore.cc deferred
WAL, csum :6080, _verify_csum BlueStore.h:3757), scoped for this round to
a file-per-object layout instead of a raw-block allocator stack:

- every Transaction is encoded (versioned codec) into a WAL record framed
  [u32 len][u32 crc32c][payload], fsync'd, THEN applied to the backing
  files; a torn tail is discarded on replay (crc gate), so mount after a
  crash replays exactly the committed prefix;
- object data lives in one file per object; attrs/omap in a sidecar meta
  file written atomically (tmp+rename); data checksums are crc32c per 4K
  page, stored in the meta and verified on every read;
- the WAL is compacted (truncated) once applied records exceed a
  threshold, with a checkpoint marker (the journal-trim role).

The raw-block allocator + KV metadata design (BlueStore proper) is the
next widening step behind this same factory.
"""

from __future__ import annotations

import os
import struct
import threading
from typing import Callable

from ..ops import native
from ..utils.buffer import BufferList
from ..utils.codec import Decoder, Encoder
from .objectstore import (CollectionId, NoSuchCollection, NoSuchObject,
                          ObjectId, ObjectStore, StoreError, Transaction,
                          TxOp, MemStore)

CSUM_BLOCK = 4096
WAL_COMPACT_BYTES = 8 * 1024 * 1024


# ------------------------- transaction (de)serialisation -------------------

def _enc_value(e: Encoder, v) -> None:
    if isinstance(v, bool):
        e.u8(3); e.boolean(v)
    elif isinstance(v, int):
        e.u8(0); e.i64(v)
    elif isinstance(v, (bytes, bytearray)):
        e.u8(1); e.blob(bytes(v))
    elif isinstance(v, str):
        e.u8(2); e.string(v)
    else:
        raise StoreError(f"unencodable attr value {type(v)}")


def _dec_value(d: Decoder):
    tag = d.u8()
    if tag == 0:
        return d.i64()
    if tag == 1:
        return d.blob()
    if tag == 2:
        return d.string()
    if tag == 3:
        return d.boolean()
    raise StoreError(f"bad value tag {tag}")


def _enc_cid(e: Encoder, cid: CollectionId) -> None:
    e.u64(cid.pool); e.u64(cid.pg_seed)


def _dec_cid(d: Decoder) -> CollectionId:
    return CollectionId(d.u64(), d.u64())


def _enc_oid(e: Encoder, oid: ObjectId) -> None:
    e.string(oid.name); e.i64(oid.shard); e.i64(oid.generation)


def _dec_oid(d: Decoder) -> ObjectId:
    return ObjectId(d.string(), d.i64(), d.i64())


def encode_transaction_enc(tx: Transaction) -> Encoder:
    """Segmented WAL-record encoder: large WRITE payloads ride BY
    REFERENCE (``BufferList.contiguous()`` + the segmented
    ``Encoder.blob`` contract) so the group-commit WAL append is a
    vectored write straight out of the rx-carved frame buffer — no
    eager detach copy.  Safe because (a) the transport never reuses a
    carved frame buffer (msg/README.md ownership contract) and (b) the
    pipeline holds the Transaction — and thus the payload — alive
    until the batch commits."""
    e = Encoder()

    def body(se: Encoder):
        se.u32(len(tx.ops))
        for op in tx.ops:
            kind = op[0]
            se.string(kind.value)
            if kind in (TxOp.CREATE_COLLECTION, TxOp.REMOVE_COLLECTION):
                _enc_cid(se, op[1])
                continue
            _enc_cid(se, op[1])
            _enc_oid(se, op[2])
            if kind == TxOp.WRITE:
                se.u64(op[3]); se.blob(op[4].contiguous())
            elif kind == TxOp.ZERO:
                se.u64(op[3]); se.u64(op[4])
            elif kind == TxOp.TRUNCATE:
                se.u64(op[3])
            elif kind in (TxOp.SETATTRS, TxOp.OMAP_SETKEYS):
                se.u32(len(op[3]))
                for k, v in sorted(op[3].items()):
                    se.string(str(k)); _enc_value(se, v)
            elif kind == TxOp.RMATTR:
                se.string(op[3])
            elif kind == TxOp.OMAP_RMKEYS:
                se.seq(op[3], Encoder.string)
            elif kind == TxOp.CLONE:
                _enc_oid(se, op[3])
    e.versioned(1, 1, body)
    return e


def encode_transaction(tx: Transaction) -> bytes:
    return encode_transaction_enc(tx).tobytes()


def decode_transaction(data: bytes) -> Transaction:
    d = Decoder(data)

    def body(sd: Decoder, version: int) -> Transaction:
        tx = Transaction()
        for _ in range(sd.u32()):
            kind = TxOp(sd.string())
            if kind in (TxOp.CREATE_COLLECTION, TxOp.REMOVE_COLLECTION):
                tx.ops.append((kind, _dec_cid(sd)))
                continue
            cid, oid = _dec_cid(sd), _dec_oid(sd)
            if kind in (TxOp.TOUCH, TxOp.REMOVE):
                tx.ops.append((kind, cid, oid))
            elif kind == TxOp.WRITE:
                off = sd.u64()
                tx.ops.append((kind, cid, oid, off, BufferList(sd.blob())))
            elif kind == TxOp.ZERO:
                tx.ops.append((kind, cid, oid, sd.u64(), sd.u64()))
            elif kind == TxOp.TRUNCATE:
                tx.ops.append((kind, cid, oid, sd.u64()))
            elif kind in (TxOp.SETATTRS, TxOp.OMAP_SETKEYS):
                kv = {}
                for _i in range(sd.u32()):
                    k = sd.string(); kv[k] = _dec_value(sd)
                tx.ops.append((kind, cid, oid, kv))
            elif kind == TxOp.RMATTR:
                tx.ops.append((kind, cid, oid, sd.string()))
            elif kind == TxOp.OMAP_RMKEYS:
                tx.ops.append((kind, cid, oid, sd.seq(Decoder.string)))
            elif kind == TxOp.CLONE:
                tx.ops.append((kind, cid, oid, _dec_oid(sd)))
            else:  # pragma: no cover
                raise StoreError(f"bad wal op {kind}")
        return tx
    return d.versioned(1, body)


# --------------------------------- store -----------------------------------

def _esc(name: str) -> str:
    return "".join(c if c.isalnum() or c in "._-" else f"%{ord(c):02x}"
                   for c in name)


class FileStore(ObjectStore):
    """Durable ObjectStore: WAL + file-per-object + checksummed reads.

    Internally the live state is a MemStore replica kept in sync with the
    files (fast reads; the files are the durable truth); mount() rebuilds
    the replica from disk then replays any committed WAL tail.
    """

    def __init__(self, path: str):
        self.path = path
        self._wal_path = os.path.join(path, "wal.bin")
        self._ckpt_path = os.path.join(path, "wal.ckpt")
        self._mem = MemStore()
        self._lock = threading.RLock()
        self._wal_file = None
        self._mounted = False
        self._corrupt: set[tuple[CollectionId, ObjectId]] = set()

    # ------------------------------------------------------------- mount
    def mount(self) -> None:
        with self._lock:
            if self._mounted:
                return
            os.makedirs(self.path, exist_ok=True)
            self._mem = MemStore()
            self._mem.mount()
            self._load_from_files()
            self._replay_wal()
            self._wal_file = open(self._wal_path, "ab")
            self._mounted = True

    def umount(self) -> None:
        self.flush()  # drain the commit pipeline before the WAL closes
        with self._lock:
            if self._wal_file:
                self._wal_file.close()
                self._wal_file = None
            self._mounted = False

    # -------------------------------------------------------- durability
    def _tx_cost(self, tx: Transaction) -> int:
        """Throttle accounting: a queued FileStore item pins a FULL
        per-object snapshot (data+attrs+omap, see _tx_snaps), so the
        touched objects' current sizes count toward the admission
        bound — a stream of tiny appends to a huge object must hit
        backpressure on the snapshots it pins, not on its payload."""
        n = super()._tx_cost(tx)
        seen: set[tuple] = set()
        for op in tx.ops:
            kind = op[0]
            if kind in (TxOp.CREATE_COLLECTION, TxOp.REMOVE_COLLECTION,
                        TxOp.REMOVE):
                continue
            keys = [(op[1], op[2])]
            if kind == TxOp.CLONE:
                keys.append((op[1], op[3]))
            for key in keys:
                if key in seen:
                    continue
                seen.add(key)
                try:
                    n += len(self._mem._obj(*key).data)
                except (NoSuchObject, NoSuchCollection):
                    pass  # created by this tx: payload already counted
        return n

    def _snapshot(self, cid: CollectionId, oid: ObjectId):
        """Post-tx image of one object (data, attrs, omap) — or None
        when absent."""
        try:
            obj = self._mem._obj(cid, oid)
        except (NoSuchObject, NoSuchCollection):
            return None
        return (bytes(obj.data), dict(obj.attrs), dict(obj.omap))

    def _tx_snaps(self, tx: Transaction) -> dict:
        """Per-object snapshots AS OF this transaction (taken right
        after its replica apply, under the store lock): the batch
        mirror writes THESE, never the live replica — live state may
        already include later queued transactions whose WAL records
        are not yet fsync'd, and persisting their fragments would
        break the committed-prefix crash contract."""
        snaps: dict = {}
        for op in tx.ops:
            kind = op[0]
            if kind in (TxOp.CREATE_COLLECTION, TxOp.REMOVE_COLLECTION,
                        TxOp.REMOVE):
                continue
            snaps[(op[1], op[2])] = self._snapshot(op[1], op[2])
            if kind == TxOp.CLONE:
                snaps[(op[1], op[3])] = self._snapshot(op[1], op[3])
        return snaps

    def _prepare(self, tx: Transaction):
        """Validate + apply to the memory replica (read-your-writes
        holds on return), encode the WAL record and snapshot the
        touched objects (see _tx_snaps).  Durability — the append,
        the ONE batch fsync, the file mirror and the applied
        checkpoint — happens in ``_commit_batch``."""
        segments = encode_transaction_enc(tx).segments()
        plen = crc = 0
        ref_b = 0
        for s in segments:
            plen += len(s)
            crc = native.crc32c(s, crc)
            if isinstance(s, memoryview):
                ref_b += len(s)
        self._book("store_ingest_ref_bytes", ref_b)
        self._book("store_ingest_copy_bytes", plen - ref_b)
        header = struct.pack("<II", plen, crc)
        with self._lock:
            if not self._mounted:
                raise StoreError("not mounted")
            # validate-before-anything: a rejected tx must never reach
            # the WAL (a durable-but-invalid record would replay later).
            # MemStore.queue_transaction validates then applies
            # atomically under its own lock.
            self._mem.queue_transaction(tx)
            return (header, segments, tx, self._tx_snaps(tx))

    def _commit_batch(self, items: list) -> int:
        """The group commit: every record in one vectored append, ONE
        WAL fsync (the commit point for the whole batch), then each
        dirty object mirrored ONCE and the checkpoint advanced once.

        The mirror writes each dirty object's LAST per-tx snapshot
        from THIS batch (captured at prepare, see _tx_snaps) — never
        the live replica, which may already hold effects of later
        queued transactions whose records are not yet journaled; a
        crash must only ever surface fsync'd-WAL-prefix state."""
        fsyncs = 0
        with self._lock:
            wal = self._wal_file
            if wal is None:
                raise StoreError("not mounted")
            for header, segments, _tx, _snaps in items:
                wal.write(header)
                for s in segments:
                    wal.write(s)
            wal.flush()
            os.fsync(wal.fileno())
            fsyncs += 1
            fsyncs += self._apply_files_batch(items)
            self._write_ckpt(wal.tell())
            fsyncs += 1  # the checkpoint's tmp-file fsync
            self._maybe_compact()
        return fsyncs

    def _write_ckpt(self, offset: int) -> None:
        tmp = self._ckpt_path + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(offset))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._ckpt_path)

    def _read_ckpt(self) -> int:
        try:
            with open(self._ckpt_path) as f:
                return int(f.read().strip() or 0)
        except (FileNotFoundError, ValueError):
            return 0

    def _replay_wal(self) -> None:
        """Re-apply committed WAL records PAST the applied checkpoint
        (records before it already reached the files); discard a torn
        tail.  Replaying only the unapplied suffix keeps non-idempotent
        ops (clone) correct across clean remounts."""
        if not os.path.exists(self._wal_path):
            return
        with open(self._wal_path, "rb") as f:
            raw = f.read()
        pos = min(self._read_ckpt(), len(raw))
        while pos + 8 <= len(raw):
            ln, crc = struct.unpack_from("<II", raw, pos)
            if pos + 8 + ln > len(raw):
                break  # torn write at the tail
            payload = raw[pos + 8: pos + 8 + ln]
            if native.crc32c(payload) != crc:
                break  # corrupt tail record: stop (crash gate)
            tx = decode_transaction(payload)
            try:
                self._mem.queue_transaction(tx)
                self._apply_files(tx)
            except StoreError:
                pass  # partially applied before the crash; files are truth
            pos += 8 + ln
            self._write_ckpt(pos)
        # truncate any torn tail so future appends are clean
        if pos < len(raw):
            with open(self._wal_path, "r+b") as f:
                f.truncate(pos)
            self._write_ckpt(min(self._read_ckpt(), pos))

    def _maybe_compact(self) -> None:
        if os.path.getsize(self._wal_path) < WAL_COMPACT_BYTES:
            return
        # files fully reflect the WAL; safe to start a fresh journal
        self._wal_file.close()
        with open(self._wal_path, "wb") as f:
            f.flush()
            os.fsync(f.fileno())
        self._wal_file = open(self._wal_path, "ab")
        self._write_ckpt(0)

    # ----------------------------------------------------- file layout
    def _coll_dir(self, cid: CollectionId) -> str:
        return os.path.join(self.path, f"coll_{cid.pool}_{cid.pg_seed:x}")

    def _obj_base(self, cid: CollectionId, oid: ObjectId) -> str:
        return os.path.join(self._coll_dir(cid),
                            f"{_esc(oid.name)}_{oid.shard}_{oid.generation}")

    def _apply_files(self, tx: Transaction) -> None:
        # replay/mount path (single-threaded): the live replica IS the
        # post-tx state, so snapshots taken now are exact
        self._apply_files_batch([(None, None, tx, self._tx_snaps(tx))])

    def _apply_files_batch(self, items: list) -> int:
        """Mirror a whole batch: collection/remove ops in order, then
        each dirty object written ONCE from its LAST per-tx snapshot —
        N same-object writes in a batch cost one mirror, not N, and
        the persisted state is exactly the batch's WAL prefix.
        Returns the fsync count spent."""
        fsyncs = 0
        dirty: dict[tuple[CollectionId, ObjectId], tuple | None] = {}
        for _h, _s, tx, snaps in items:
            for op in tx.ops:
                kind = op[0]
                if kind == TxOp.CREATE_COLLECTION:
                    os.makedirs(self._coll_dir(op[1]), exist_ok=True)
                elif kind == TxOp.REMOVE_COLLECTION:
                    d = self._coll_dir(op[1])
                    if os.path.isdir(d):
                        for f in os.listdir(d):
                            os.unlink(os.path.join(d, f))
                        os.rmdir(d)
                    dirty = {k: v for k, v in dirty.items()
                             if k[0] != op[1]}
                elif kind == TxOp.REMOVE:
                    base = self._obj_base(op[1], op[2])
                    for suffix in (".data", ".meta"):
                        if os.path.exists(base + suffix):
                            os.unlink(base + suffix)
                    dirty.pop((op[1], op[2]), None)
                    self._corrupt.discard((op[1], op[2]))
                else:
                    dirty[(op[1], op[2])] = snaps.get((op[1], op[2]))
                    if kind == TxOp.CLONE:
                        dirty[(op[1], op[3])] = snaps.get(
                            (op[1], op[3]))
        for (cid, oid), snap in dirty.items():
            fsyncs += self._write_object_files(cid, oid, snap)
        return fsyncs

    def _write_object_files(self, cid: CollectionId, oid: ObjectId,
                            snap: tuple | None) -> int:
        """Mirror one object's snapshotted state (data, attrs, omap)
        to disk, with per-page checksums in the meta sidecar.  Returns
        the fsync count spent."""
        if snap is None:  # absent at snapshot time (e.g. removed)
            return 0
        data, attrs, omap = snap
        base = self._obj_base(cid, oid)
        os.makedirs(os.path.dirname(base), exist_ok=True)
        self._corrupt.discard((cid, oid))  # fresh write supersedes rot
        # one native round-trip for the whole page sweep
        csums = native.crc32c_blocks(data, CSUM_BLOCK) if data else []
        tmp = base + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, base + ".data")
        e = Encoder()

        def body(se: Encoder):
            se.u32(len(attrs))
            for k, v in sorted(attrs.items()):
                se.string(str(k)); _enc_value(se, v)
            se.u32(len(omap))
            for k, v in sorted(omap.items()):
                se.string(str(k)); _enc_value(se, v)
            se.u64(len(data))
            se.u32(CSUM_BLOCK)
            se.seq(csums, Encoder.u32)
        e.versioned(1, 1, body)
        with open(tmp, "wb") as f:
            f.write(e.tobytes())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, base + ".meta")
        return 2  # data tmp + meta tmp

    def _load_from_files(self) -> None:
        if not os.path.isdir(self.path):
            return
        for entry in sorted(os.listdir(self.path)):
            if not entry.startswith("coll_"):
                continue
            _, pool, seed = entry.split("_")
            cid = CollectionId(int(pool), int(seed, 16))
            self._mem.queue_transaction(
                Transaction().create_collection(cid))
            cdir = os.path.join(self.path, entry)
            for fname in sorted(os.listdir(cdir)):
                if not fname.endswith(".meta"):
                    continue
                base = os.path.join(cdir, fname[:-5])
                name_esc, shard, gen = fname[:-5].rsplit("_", 2)
                oid = ObjectId(_unesc(name_esc), int(shard), int(gen))
                attrs, omap, _size, csums = self._read_meta(base)
                data = b""
                if os.path.exists(base + ".data"):
                    with open(base + ".data", "rb") as f:
                        data = f.read()
                # verify page checksums ONCE, at load (reads then serve
                # from the in-RAM replica, which cannot rot)
                got = [native.crc32c(data[i:i + CSUM_BLOCK])
                       for i in range(0, len(data), CSUM_BLOCK)]
                if got != csums:
                    self._corrupt.add((cid, oid))
                tx = Transaction().touch(cid, oid)
                if data:
                    tx.write(cid, oid, 0, data)
                if attrs:
                    tx.setattrs(cid, oid, attrs)
                if omap:
                    tx.omap_setkeys(cid, oid, omap)
                self._mem.queue_transaction(tx)

    @staticmethod
    def _read_meta(base: str):
        with open(base + ".meta", "rb") as f:
            d = Decoder(f.read())

        def body(sd: Decoder, version: int):
            attrs = {sd.string(): _dec_value(sd) for _ in range(sd.u32())}
            omap = {sd.string(): _dec_value(sd) for _ in range(sd.u32())}
            size = sd.u64()
            sd.u32()  # csum block size
            csums = sd.seq(Decoder.u32)
            return attrs, omap, size, csums
        return d.versioned(1, body)

    # ------------------------------------------------------------ reads
    def read(self, cid, oid, offset: int = 0,
             length: int | None = None) -> BufferList:
        if (cid, oid) in self._corrupt:
            # bitrot found at load (BlueStore _verify_csum -> EIO role)
            raise StoreError(f"checksum mismatch on {cid}/{oid}")
        return self._mem.read(cid, oid, offset, length)

    def deep_verify(self, cid, oid) -> bool:
        """Re-read the on-disk object and check every page checksum (the
        deep-scrub primitive).  Returns False (and poisons reads) on
        mismatch."""
        base = self._obj_base(cid, oid)
        if not os.path.exists(base + ".meta"):
            return not self._mem.exists(cid, oid)
        _a, _o, _s, want = self._read_meta(base)
        data = b""
        if os.path.exists(base + ".data"):
            with open(base + ".data", "rb") as f:
                data = f.read()
        got = [native.crc32c(data[i:i + CSUM_BLOCK])
               for i in range(0, len(data), CSUM_BLOCK)]
        if got != want:
            self._corrupt.add((cid, oid))
            return False
        return True

    def stat(self, cid, oid):
        return self._mem.stat(cid, oid)

    def exists(self, cid, oid) -> bool:
        return self._mem.exists(cid, oid)

    def getattrs(self, cid, oid):
        return self._mem.getattrs(cid, oid)

    def omap_get(self, cid, oid):
        return self._mem.omap_get(cid, oid)

    def list_objects(self, cid):
        return self._mem.list_objects(cid)

    def list_collections(self):
        return self._mem.list_collections()


def _unesc(s: str) -> str:
    out, i = [], 0
    while i < len(s):
        if s[i] == "%" and i + 2 < len(s) + 1:
            out.append(chr(int(s[i + 1:i + 3], 16)))
            i += 3
        else:
            out.append(s[i])
            i += 1
    return "".join(out)
