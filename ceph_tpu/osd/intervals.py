"""Past intervals: the membership history a PG peers against.

The capability of the reference's interval machinery
(src/osd/PeeringState.h:460+ statechart prior-set construction,
src/osd/osd_types.h PastIntervals): every time a PG's up set or
primary changes across map epochs, the closed interval is recorded
durably with the PG's metadata.  A freshly-(re)promoted primary then
knows WHO might have served writes while it was away and must be
queried (or waited for) before the PG serves IO — current up members
alone are not enough, because an OSD that held the PG in a prior
interval may carry committed writes every current member missed.

Shape differences from the reference are deliberate: intervals live in
the same meta-object omap as the PGLog (one durability domain per PG),
and `maybe_went_active` is approximated by "had a primary" — the
min_size refinement rides on the primary's own last-epoch-started
fence, checked at query time.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..utils.codec import Decoder, Encoder

# omap keys on the PG meta object (shared with PGLog's entries + "_lc")
INTERVALS_KEY = "_intervals"
LES_KEY = "_les"  # last epoch started: peering-complete fence


@dataclass
class Interval:
    first: int          # first map epoch of the interval
    last: int           # last map epoch (inclusive)
    up: list            # up set (may contain None holes)
    primary: int | None

    def maybe_went_active(self) -> bool:
        """Could writes have been served in this interval?  Without a
        primary nothing was served (PastIntervals::check_new_interval's
        acting-nonempty test, simplified)."""
        return self.primary is not None


@dataclass
class PastIntervals:
    """Closed intervals (oldest first) + the currently-open one."""

    intervals: list[Interval] = field(default_factory=list)
    cur_first: int = 0           # first epoch of the open interval
    cur_up: list = field(default_factory=list)
    cur_primary: int | None = None

    KEEP = 64  # bounded history (pruned by last-epoch-started anyway)

    # -- maintenance -------------------------------------------------------
    def note(self, epoch: int, up: list, primary: int | None) -> bool:
        """Observe the PG's membership at `epoch`.  Returns True when a
        new interval opened (the caller persists)."""
        up = list(up)
        if not self.cur_up and self.cur_primary is None \
                and self.cur_first == 0:
            self.cur_first, self.cur_up, self.cur_primary = \
                epoch, up, primary
            return True
        if up == self.cur_up and primary == self.cur_primary:
            return False
        self.intervals.append(Interval(self.cur_first, epoch - 1,
                                       self.cur_up, self.cur_primary))
        if len(self.intervals) > self.KEEP:
            self.intervals = self.intervals[-self.KEEP:]
        self.cur_first, self.cur_up, self.cur_primary = epoch, up, primary
        return True

    def trim_to(self, epoch: int) -> None:
        """Drop intervals fully before `epoch` (the PG peered and went
        active at `epoch`: older history can no longer matter)."""
        self.intervals = [i for i in self.intervals if i.last >= epoch]

    # -- queries -----------------------------------------------------------
    def prior_osds(self, since: int, exclude: int) -> set[int]:
        """OSDs that were members of a maybe-active interval whose span
        reaches back to `since` (the last epoch this PG completed
        peering) — the prior set the primary must hear from."""
        out: set[int] = set()
        for i in self.intervals:
            if i.last < since or not i.maybe_went_active():
                continue
            out.update(o for o in i.up if o is not None)
        out.discard(exclude)
        return out

    # -- codec -------------------------------------------------------------
    def encode_bytes(self) -> bytes:
        e = Encoder()

        def body(se: Encoder):
            se.u32(len(self.intervals))
            for i in self.intervals:
                se.u64(i.first)
                se.u64(i.last)
                se.u32(len(i.up))
                for o in i.up:
                    se.i64(-1 if o is None else o)
                se.i64(-1 if i.primary is None else i.primary)
            se.u64(self.cur_first)
            se.u32(len(self.cur_up))
            for o in self.cur_up:
                se.i64(-1 if o is None else o)
            se.i64(-1 if self.cur_primary is None else self.cur_primary)
        e.versioned(1, 1, body)
        return e.tobytes()

    @classmethod
    def decode_bytes(cls, raw: bytes) -> "PastIntervals":
        d = Decoder(raw)

        def body(sd: Decoder, _v: int):
            pi = cls()
            for _ in range(sd.u32()):
                first, last = sd.u64(), sd.u64()
                up = [None if (o := sd.i64()) < 0 else o
                      for _ in range(sd.u32())]
                prim = sd.i64()
                pi.intervals.append(
                    Interval(first, last, up,
                             None if prim < 0 else prim))
            pi.cur_first = sd.u64()
            pi.cur_up = [None if (o := sd.i64()) < 0 else o
                         for _ in range(sd.u32())]
            prim = sd.i64()
            pi.cur_primary = None if prim < 0 else prim
            return pi
        return d.versioned(1, body)
