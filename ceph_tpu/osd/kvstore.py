"""KeyValueDB: the KV abstraction + a durable WAL/snapshot store.

The capability of the reference's src/kv/ (KeyValueDB.h interface over
RocksDBStore, consumed by BlueStore metadata and MonitorDBStore):

- `KeyValueDB`: get/put/rm per (prefix, key), atomic transactions,
  ordered iteration within a prefix — the interface BlueStore-shaped
  stores program against;
- `WalKV`: a durable implementation with the store family's WAL
  contract — every transaction appends a crc-framed fsync'd record
  ([u32 len][u32 crc32c][payload]); a torn tail is discarded on open;
  the log compacts to a snapshot when it outgrows the live data (so
  neither the file nor open-replay grows with history).  The snapshot
  rewrite is an inline stall in miniature (the whole live set encodes
  + fsyncs inside submit) — it is COUNTED (``kv_wal_compact_us``) and
  can move behind a background thread (``bg_compact=True``: writers
  keep appending to the live file while the snapshot writes to a tmp;
  frames landed since the snapshot replay into the tmp under a short
  critical section before the rename).
- `SstKV` (sstkv.py): the leveled LSM stack (RocksDB-tier) with
  background memtable flush / compaction threads, a shared block
  cache and counted write stalls.

Every durable backend books maintenance onto one shared counter
schema (``register_kv_counters`` → registry ``kv.<store>``) so the
exporter/metrics-history see a stable shape across backends.
"""

from __future__ import annotations

import os
import struct
import threading
import time
from abc import ABC, abstractmethod

from ..ops.native import crc32c
from ..utils.codec import Decoder, Encoder
from ..utils.perf import CounterType, PerfCounters, global_perf

# --------------------------------------------------------------- kv perf
#: the KV-tier maintenance schema (registry ``kv.<store>``), registered
#: zeroed so scrapes see one stable shape whether or not maintenance has
#: run yet.  ``*_inline`` counters book maintenance performed in the
#: SUBMIT path (the caller's — for the async store pipeline, the
#: kv-sync thread's) — the quantity the background seam drives to zero.
KV_COUNTERS = ("kv_flush", "kv_flush_inline",
               "kv_compact", "kv_compact_inline",
               "kv_stall_memtable", "kv_stall_l0", "kv_slowdown",
               "kv_cache_hit", "kv_cache_miss", "kv_cache_evict",
               "kv_wal_compact", "kv_wal_compact_inline")
KV_HISTOGRAMS = ("kv_flush_us", "kv_compact_us", "kv_stall_us",
                 "kv_wal_compact_us")
KV_GAUGES = ("kv_cache_bytes", "kv_imm_memtables", "kv_l0_files")


def register_kv_counters(perf: PerfCounters) -> None:
    """Idempotently register the KV maintenance counter schema."""
    for n in KV_COUNTERS:
        if not perf.has(n):
            perf.add(n)
    for n in KV_HISTOGRAMS:
        if not perf.has(n):
            perf.add(n, CounterType.HISTOGRAM)
    for n in KV_GAUGES:
        if not perf.has(n):
            perf.add(n, CounterType.U64)


def resolve_kv_perf(name: str | None,
                    perf: PerfCounters | None) -> tuple[PerfCounters, bool]:
    """The booking registry for one KV store: an explicit ``perf``, the
    process-global ``kv.<name>`` when named (owned: the store removes
    it on close), else an anonymous local registry (unit-test stores
    must not grow the exporter's scrape)."""
    if perf is not None:
        pc, owned = perf, False
    elif name is not None:
        pc, owned = global_perf().create(f"kv.{name}"), True
    else:
        pc, owned = PerfCounters("kv.anon"), False
    register_kv_counters(pc)
    return pc, owned


class KVTransaction:
    """Atomic batch (KeyValueDB::Transaction role)."""

    def __init__(self):
        self.ops: list[tuple] = []  # ("put", prefix, key, val)|("rm",..)

    def put(self, prefix: str, key: str, value: bytes) -> "KVTransaction":
        self.ops.append(("put", prefix, key, bytes(value)))
        return self

    def rm(self, prefix: str, key: str) -> "KVTransaction":
        self.ops.append(("rm", prefix, key, b""))
        return self

    def rm_prefix(self, prefix: str) -> "KVTransaction":
        self.ops.append(("rmp", prefix, "", b""))
        return self


class KeyValueDB(ABC):
    @abstractmethod
    def submit(self, tx: KVTransaction, sync: bool = True) -> None:
        """Apply one atomic batch.  ``sync=False`` defers durability:
        the record is written (appended) but not fsync'd — the group-
        commit path submits a whole batch unsynced then calls
        ``sync()`` ONCE (the kv-sync-thread contract)."""

    @abstractmethod
    def get(self, prefix: str, key: str) -> bytes | None: ...

    @abstractmethod
    def iterate(self, prefix: str, start: str = ""): ...

    def sync(self) -> None:
        """Make every prior unsynced submit durable (one fsync)."""

    def put(self, prefix: str, key: str, value: bytes) -> None:
        self.submit(KVTransaction().put(prefix, key, value))

    def rm(self, prefix: str, key: str) -> None:
        self.submit(KVTransaction().rm(prefix, key))

    def stats(self) -> dict:
        """Backend maintenance/occupancy stats (the `kv stats` admin
        surface; durable backends override)."""
        return {}

    def close(self) -> None: ...


_REC_TX, _REC_SNAP = 1, 2


class MemKV(KeyValueDB):
    """In-memory KeyValueDB (tests / volatile stores)."""

    def __init__(self):
        self._data: dict[str, dict[str, bytes]] = {}
        self._lock = threading.RLock()

    def submit(self, tx: KVTransaction, sync: bool = True) -> None:
        with self._lock:
            for op, prefix, key, val in tx.ops:
                if op == "put":
                    self._data.setdefault(prefix, {})[key] = val
                elif op == "rm":
                    self._data.get(prefix, {}).pop(key, None)
                else:
                    self._data.pop(prefix, None)

    def get(self, prefix: str, key: str) -> bytes | None:
        with self._lock:
            return self._data.get(prefix, {}).get(key)

    def iterate(self, prefix: str, start: str = ""):
        with self._lock:
            items = sorted(self._data.get(prefix, {}).items())
        for k, v in items:
            if k >= start:
                yield k, v


class WalKV(MemKV):
    """Durable KV: MemKV state + crc-framed WAL + snapshot compaction
    (the FileStore/DurableMonStore WAL contract over KV semantics).

    Snapshot compaction is counted (``kv_wal_compact_us`` +
    ``kv_wal_compact[_inline]`` on the ``kv.<name>`` registry) and,
    with ``bg_compact=True``, moves off the submit path: a dedicated
    thread snapshots the live data under the lock, encodes + writes
    the tmp file UNLOCKED while writers keep appending to the live
    file (each new frame also lands in a pending buffer), then under a
    short critical section replays the pending frames into the tmp,
    fsyncs and renames.  A crash mid-compaction loses nothing: the
    live file keeps every synced frame until the rename, and the tmp
    is complete (snapshot + pending tail) before it replaces anything.
    ``store_sync_commit=on`` remains the orthogonal escape hatch (no
    group commit ⇒ every submit pays its own fsync, compaction
    included, with the pre-pipeline interleaving)."""

    COMPACT_RATIO = 4  # compact when log bytes > ratio * live bytes

    def __init__(self, path: str, *, name: str | None = None,
                 perf: PerfCounters | None = None,
                 bg_compact: bool = False):
        super().__init__()
        os.makedirs(path, exist_ok=True)
        self._path = os.path.join(path, "kv.wal")
        self._file = None
        self._log_bytes = 0
        self._live_bytes = 0
        self.perf, self._owns_perf = resolve_kv_perf(name, perf)
        self._perf_name = f"kv.{name}" if name is not None else None
        self._bg = bool(bg_compact)
        self._cv = threading.Condition(self._lock)
        self._compacting = False     # bg snapshot in flight
        self._compact_kick = False
        self._pending_frames: list[bytes] = []  # frames since snapshot
        self._stopping = False
        self._compact_thread: threading.Thread | None = None
        # a crash mid-compaction leaves a snapshot-sized tmp the live
        # file supersedes (the rename never happened) — drop it
        try:
            os.remove(self._path + ".tmp")
        except OSError:
            pass
        self._load()
        self._file = open(self._path, "ab")
        if self._bg:
            self._compact_thread = threading.Thread(
                target=self._compact_loop, daemon=True,
                name=f"kv-wal-compact-{name or 'anon'}")
            self._compact_thread.start()

    # -- framing -----------------------------------------------------------
    @staticmethod
    def _frame(payload: bytes) -> bytes:
        return struct.pack("<II", len(payload), crc32c(payload)) + payload

    def _load(self) -> None:
        if not os.path.exists(self._path):
            return
        with open(self._path, "rb") as f:
            raw = f.read()
        pos = 0
        while pos + 8 <= len(raw):
            length, crc = struct.unpack_from("<II", raw, pos)
            payload = raw[pos + 8: pos + 8 + length]
            if len(payload) < length or crc32c(payload) != crc:
                break  # torn tail
            self._apply_payload(payload)
            pos += 8 + length
        if pos < len(raw):
            with open(self._path, "r+b") as f:
                f.truncate(pos)
        self._log_bytes = pos
        self._live_bytes = self._live_size()

    def _apply_payload(self, payload: bytes) -> None:
        d = Decoder(payload)
        kind = d.u8()
        if kind == _REC_TX:
            tx = KVTransaction()
            for _ in range(d.u32()):
                op, prefix, key, val = d.string(), d.string(), \
                    d.string(), d.blob()
                tx.ops.append((op, prefix, key, val))
            MemKV.submit(self, tx)
        elif kind == _REC_SNAP:
            self._data = {}
            for _ in range(d.u32()):
                prefix = d.string()
                self._data[prefix] = d.mapping(Decoder.string,
                                               Decoder.blob)

    def _live_size(self) -> int:
        return sum(len(k) + len(v)
                   for kv in self._data.values()
                   for k, v in kv.items()) or 1

    # -- api ---------------------------------------------------------------
    def submit(self, tx: KVTransaction, sync: bool = True) -> None:
        """Append one crc-framed record (+apply).  ``sync=False`` skips
        the fsync — the group-commit caller batches N submits behind
        ONE ``sync()``; a crash before it loses only unacked records
        (each frame is individually crc-gated, so replay applies the
        committed prefix and discards the torn tail)."""
        e = Encoder()
        e.u8(_REC_TX)
        e.u32(len(tx.ops))
        for op, prefix, key, val in tx.ops:
            e.string(op)
            e.string(prefix)
            e.string(key)
            e.blob(val)
        payload = e.tobytes()
        with self._lock:
            super().submit(tx)
            frame = self._frame(payload)
            self._file.write(frame)
            if self._compacting:
                # a bg snapshot is in flight: this frame postdates it,
                # so it must replay into the tmp before the rename
                self._pending_frames.append(frame)
            if sync:
                self._file.flush()
                os.fsync(self._file.fileno())
            self._log_bytes += len(payload) + 8
            if self._log_bytes > self.COMPACT_RATIO * \
                    max(self._live_bytes, 4096):
                if self._bg:
                    if not self._compacting and not self._compact_kick:
                        self._compact_kick = True
                        self._cv.notify_all()
                else:
                    t0 = time.monotonic()
                    self._compact()
                    self.perf.inc("kv_wal_compact")
                    self.perf.inc("kv_wal_compact_inline")
                    self.perf.hinc("kv_wal_compact_us",
                                   (time.monotonic() - t0) * 1e6)

    def sync(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.flush()
                os.fsync(self._file.fileno())

    def _snapshot_frame(self, data: dict) -> bytes:
        e = Encoder()
        e.u8(_REC_SNAP)
        e.u32(len(data))
        for prefix in sorted(data):
            e.string(prefix)
            e.mapping(data[prefix], Encoder.string, Encoder.blob)
        return self._frame(e.tobytes())

    def _compact(self) -> None:
        """Rewrite the file as one snapshot record (tmp+rename) —
        the inline path, caller holds the lock."""
        frame = self._snapshot_frame(self._data)
        tmp = self._path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(frame)
            f.flush()
            os.fsync(f.fileno())
        if self._file:
            self._file.close()
        os.replace(tmp, self._path)
        self._file = open(self._path, "ab")
        self._log_bytes = len(frame)
        self._live_bytes = self._live_size()

    def _compact_loop(self) -> None:
        """Background snapshot compaction (see class docstring for the
        crash contract)."""
        while True:
            with self._cv:
                while not self._compact_kick and not self._stopping:
                    self._cv.wait()
                if self._stopping:
                    return
                self._compact_kick = False
                # snapshot under the lock: a consistent image of the
                # live data; frames landing after this go to _pending
                self._compacting = True
                self._pending_frames = []
                snap = {p: dict(kv) for p, kv in self._data.items()}
            t0 = time.monotonic()
            f = None
            try:
                frame = self._snapshot_frame(snap)  # UNLOCKED encode
                tmp = self._path + ".tmp"
                f = open(tmp, "wb")
                f.write(frame)
                with self._cv:
                    if self._file is None:  # closed underneath us
                        f.close()
                        os.remove(tmp)
                        self._compacting = False
                        continue
                    # short critical section: tail of frames since the
                    # snapshot, fsync, swap
                    for pf in self._pending_frames:
                        f.write(pf)
                    f.flush()
                    os.fsync(f.fileno())
                    size = f.tell()
                    f.close()
                    self._file.close()
                    os.replace(tmp, self._path)
                    self._file = open(self._path, "ab")
                    self._log_bytes = size
                    self._live_bytes = self._live_size()
                    self._pending_frames = []
                    self._compacting = False
                self.perf.inc("kv_wal_compact")
                self.perf.hinc("kv_wal_compact_us",
                               (time.monotonic() - t0) * 1e6)
            except Exception as e:  # noqa: BLE001 - keep the live file
                # authoritative; a failed compaction only wastes bytes
                # (and must not leak the tmp handle/file)
                if f is not None:
                    try:
                        f.close()
                        os.remove(self._path + ".tmp")
                    except OSError:
                        pass
                with self._cv:
                    self._compacting = False
                    self._pending_frames = []
                from ..utils.log import dout
                dout("kv", 1)("wal bg compaction failed: %r", e)

    def stats(self) -> dict:
        with self._lock:
            return {"log_bytes": self._log_bytes,
                    "live_bytes": self._live_bytes,
                    "compactions": self.perf.get("kv_wal_compact"),
                    "bg_compact": self._bg}

    def close(self) -> None:
        with self._cv:
            self._stopping = True
            self._cv.notify_all()
        if self._compact_thread is not None:
            self._compact_thread.join(timeout=10)
        with self._lock:
            if self._file:
                self._file.close()
                self._file = None
        if self._owns_perf and self._perf_name:
            global_perf().remove(self._perf_name)


def create_kv(kind: str, path: str | None = None, **kw) -> KeyValueDB:
    """Factory (KeyValueDB::create role): 'mem', 'wal', or 'sst'
    (leveled LSM, the RocksDB-tier backend).  ``kw`` passes backend
    tuning through (name/perf, WalKV ``bg_compact``, SstKV
    ``memtable_bytes``/``cache_bytes``/``background``)."""
    if kind == "mem":
        return MemKV()
    if kind == "wal":
        if not path:
            raise ValueError("wal kv needs a path")
        return WalKV(path, **kw)
    if kind == "sst":
        if not path:
            raise ValueError("sst kv needs a path")
        from .sstkv import SstKV
        return SstKV(path, **kw)
    raise ValueError(f"unknown kv backend {kind!r}")
