"""KeyValueDB: the KV abstraction + a durable WAL/snapshot store.

The capability of the reference's src/kv/ (KeyValueDB.h interface over
RocksDBStore, consumed by BlueStore metadata and MonitorDBStore):

- `KeyValueDB`: get/put/rm per (prefix, key), atomic transactions,
  ordered iteration within a prefix — the interface BlueStore-shaped
  stores program against;
- `WalKV`: a durable implementation with the store family's WAL
  contract — every transaction appends a crc-framed fsync'd record
  ([u32 len][u32 crc32c][payload]); a torn tail is discarded on open;
  the log compacts to a snapshot when it outgrows the live data (so
  neither the file nor open-replay grows with history).

A leveled SSTable stack (RocksDB-grade) is the next widening; the
interface is the stable seam.
"""

from __future__ import annotations

import os
import struct
import threading
from abc import ABC, abstractmethod

from ..ops.native import crc32c
from ..utils.codec import Decoder, Encoder


class KVTransaction:
    """Atomic batch (KeyValueDB::Transaction role)."""

    def __init__(self):
        self.ops: list[tuple] = []  # ("put", prefix, key, val)|("rm",..)

    def put(self, prefix: str, key: str, value: bytes) -> "KVTransaction":
        self.ops.append(("put", prefix, key, bytes(value)))
        return self

    def rm(self, prefix: str, key: str) -> "KVTransaction":
        self.ops.append(("rm", prefix, key, b""))
        return self

    def rm_prefix(self, prefix: str) -> "KVTransaction":
        self.ops.append(("rmp", prefix, "", b""))
        return self


class KeyValueDB(ABC):
    @abstractmethod
    def submit(self, tx: KVTransaction, sync: bool = True) -> None:
        """Apply one atomic batch.  ``sync=False`` defers durability:
        the record is written (appended) but not fsync'd — the group-
        commit path submits a whole batch unsynced then calls
        ``sync()`` ONCE (the kv-sync-thread contract)."""

    @abstractmethod
    def get(self, prefix: str, key: str) -> bytes | None: ...

    @abstractmethod
    def iterate(self, prefix: str, start: str = ""): ...

    def sync(self) -> None:
        """Make every prior unsynced submit durable (one fsync)."""

    def put(self, prefix: str, key: str, value: bytes) -> None:
        self.submit(KVTransaction().put(prefix, key, value))

    def rm(self, prefix: str, key: str) -> None:
        self.submit(KVTransaction().rm(prefix, key))

    def close(self) -> None: ...


_REC_TX, _REC_SNAP = 1, 2


class MemKV(KeyValueDB):
    """In-memory KeyValueDB (tests / volatile stores)."""

    def __init__(self):
        self._data: dict[str, dict[str, bytes]] = {}
        self._lock = threading.RLock()

    def submit(self, tx: KVTransaction, sync: bool = True) -> None:
        with self._lock:
            for op, prefix, key, val in tx.ops:
                if op == "put":
                    self._data.setdefault(prefix, {})[key] = val
                elif op == "rm":
                    self._data.get(prefix, {}).pop(key, None)
                else:
                    self._data.pop(prefix, None)

    def get(self, prefix: str, key: str) -> bytes | None:
        with self._lock:
            return self._data.get(prefix, {}).get(key)

    def iterate(self, prefix: str, start: str = ""):
        with self._lock:
            items = sorted(self._data.get(prefix, {}).items())
        for k, v in items:
            if k >= start:
                yield k, v


class WalKV(MemKV):
    """Durable KV: MemKV state + crc-framed WAL + snapshot compaction
    (the FileStore/DurableMonStore WAL contract over KV semantics)."""

    COMPACT_RATIO = 4  # compact when log bytes > ratio * live bytes

    def __init__(self, path: str):
        super().__init__()
        os.makedirs(path, exist_ok=True)
        self._path = os.path.join(path, "kv.wal")
        self._file = None
        self._log_bytes = 0
        self._live_bytes = 0
        self._load()
        self._file = open(self._path, "ab")

    # -- framing -----------------------------------------------------------
    @staticmethod
    def _frame(payload: bytes) -> bytes:
        return struct.pack("<II", len(payload), crc32c(payload)) + payload

    def _load(self) -> None:
        if not os.path.exists(self._path):
            return
        with open(self._path, "rb") as f:
            raw = f.read()
        pos = 0
        while pos + 8 <= len(raw):
            length, crc = struct.unpack_from("<II", raw, pos)
            payload = raw[pos + 8: pos + 8 + length]
            if len(payload) < length or crc32c(payload) != crc:
                break  # torn tail
            self._apply_payload(payload)
            pos += 8 + length
        if pos < len(raw):
            with open(self._path, "r+b") as f:
                f.truncate(pos)
        self._log_bytes = pos
        self._live_bytes = self._live_size()

    def _apply_payload(self, payload: bytes) -> None:
        d = Decoder(payload)
        kind = d.u8()
        if kind == _REC_TX:
            tx = KVTransaction()
            for _ in range(d.u32()):
                op, prefix, key, val = d.string(), d.string(), \
                    d.string(), d.blob()
                tx.ops.append((op, prefix, key, val))
            MemKV.submit(self, tx)
        elif kind == _REC_SNAP:
            self._data = {}
            for _ in range(d.u32()):
                prefix = d.string()
                self._data[prefix] = d.mapping(Decoder.string,
                                               Decoder.blob)

    def _live_size(self) -> int:
        return sum(len(k) + len(v)
                   for kv in self._data.values()
                   for k, v in kv.items()) or 1

    # -- api ---------------------------------------------------------------
    def submit(self, tx: KVTransaction, sync: bool = True) -> None:
        """Append one crc-framed record (+apply).  ``sync=False`` skips
        the fsync — the group-commit caller batches N submits behind
        ONE ``sync()``; a crash before it loses only unacked records
        (each frame is individually crc-gated, so replay applies the
        committed prefix and discards the torn tail)."""
        e = Encoder()
        e.u8(_REC_TX)
        e.u32(len(tx.ops))
        for op, prefix, key, val in tx.ops:
            e.string(op)
            e.string(prefix)
            e.string(key)
            e.blob(val)
        payload = e.tobytes()
        with self._lock:
            super().submit(tx)
            self._file.write(self._frame(payload))
            if sync:
                self._file.flush()
                os.fsync(self._file.fileno())
            self._log_bytes += len(payload) + 8
            if self._log_bytes > self.COMPACT_RATIO * \
                    max(self._live_bytes, 4096):
                self._compact()

    def sync(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.flush()
                os.fsync(self._file.fileno())

    def _compact(self) -> None:
        """Rewrite the file as one snapshot record (tmp+rename)."""
        e = Encoder()
        e.u8(_REC_SNAP)
        e.u32(len(self._data))
        for prefix in sorted(self._data):
            e.string(prefix)
            e.mapping(self._data[prefix], Encoder.string, Encoder.blob)
        tmp = self._path + ".tmp"
        with open(tmp, "wb") as f:
            frame = self._frame(e.tobytes())
            f.write(frame)
            f.flush()
            os.fsync(f.fileno())
        if self._file:
            self._file.close()
        os.replace(tmp, self._path)
        self._file = open(self._path, "ab")
        self._log_bytes = len(frame)
        self._live_bytes = self._live_size()

    def close(self) -> None:
        with self._lock:
            if self._file:
                self._file.close()
                self._file = None


def create_kv(kind: str, path: str | None = None) -> KeyValueDB:
    """Factory (KeyValueDB::create role): 'mem', 'wal', or 'sst'
    (leveled LSM, the RocksDB-tier backend)."""
    if kind == "mem":
        return MemKV()
    if kind == "wal":
        if not path:
            raise ValueError("wal kv needs a path")
        return WalKV(path)
    if kind == "sst":
        if not path:
            raise ValueError("sst kv needs a path")
        from .sstkv import SstKV
        return SstKV(path)
    raise ValueError(f"unknown kv backend {kind!r}")
