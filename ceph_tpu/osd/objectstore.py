"""ObjectStore: local object storage API + in-memory implementation.

The capability of the reference's ObjectStore layer (src/os/ObjectStore.h —
collections of objects, atomic Transactions with ordered op-codes,
queue_transactions with commit callbacks :241, factory create
src/os/ObjectStore.cc:28) with MemStore (src/os/memstore/MemStore.cc) as
the first backend — the reference's own test/fake backend and the minimal
slice target (SURVEY.md §7.3).  A BlueStore-shaped durable backend slots in
behind the same factory later.

Objects are keyed by (pool, shard, name) — the ghobject role: shard id
distinguishes EC shard copies, generation supports EC rollback (deferred).
"""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass, field
from typing import Callable

import numpy as np

from ..utils.buffer import BufferList


class StoreError(Exception):
    pass


class NoSuchObject(StoreError):
    pass


class NoSuchCollection(StoreError):
    pass


@dataclass(frozen=True, order=True)
class ObjectId:
    """ghobject-shaped key: name + shard (EC) + snapshot generation."""

    name: str
    shard: int = -1  # -1 = whole object / replicated (NO_SHARD)
    generation: int = -1

    def __str__(self) -> str:
        s = self.name
        if self.shard >= 0:
            s += f"(s{self.shard})"
        if self.generation >= 0:
            s += f"(g{self.generation})"
        return s


@dataclass(frozen=True, order=True)
class CollectionId:
    """One PG's object namespace (coll_t)."""

    pool: int
    pg_seed: int

    def __str__(self) -> str:
        return f"{self.pool}.{self.pg_seed:x}"


class TxOp(enum.Enum):
    TOUCH = "touch"
    WRITE = "write"
    ZERO = "zero"
    TRUNCATE = "truncate"
    REMOVE = "remove"
    SETATTRS = "setattrs"
    RMATTR = "rmattr"
    OMAP_SETKEYS = "omap_setkeys"
    OMAP_RMKEYS = "omap_rmkeys"
    CLONE = "clone"
    CREATE_COLLECTION = "create_collection"
    REMOVE_COLLECTION = "remove_collection"


@dataclass
class Transaction:
    """Ordered list of mutations applied atomically (Transaction.h)."""

    ops: list[tuple] = field(default_factory=list)

    def touch(self, cid, oid):
        self.ops.append((TxOp.TOUCH, cid, oid))
        return self

    def write(self, cid, oid, offset: int, data):
        if not isinstance(data, BufferList):
            data = BufferList(data)
        self.ops.append((TxOp.WRITE, cid, oid, offset, data))
        return self

    def zero(self, cid, oid, offset: int, length: int):
        self.ops.append((TxOp.ZERO, cid, oid, offset, length))
        return self

    def truncate(self, cid, oid, size: int):
        self.ops.append((TxOp.TRUNCATE, cid, oid, size))
        return self

    def remove(self, cid, oid):
        self.ops.append((TxOp.REMOVE, cid, oid))
        return self

    def setattrs(self, cid, oid, attrs: dict[str, bytes]):
        self.ops.append((TxOp.SETATTRS, cid, oid, dict(attrs)))
        return self

    def rmattr(self, cid, oid, name: str):
        self.ops.append((TxOp.RMATTR, cid, oid, name))
        return self

    def omap_setkeys(self, cid, oid, kv: dict[str, bytes]):
        self.ops.append((TxOp.OMAP_SETKEYS, cid, oid, dict(kv)))
        return self

    def omap_rmkeys(self, cid, oid, keys):
        self.ops.append((TxOp.OMAP_RMKEYS, cid, oid, list(keys)))
        return self

    def clone(self, cid, src, dst):
        self.ops.append((TxOp.CLONE, cid, src, dst))
        return self

    def create_collection(self, cid):
        self.ops.append((TxOp.CREATE_COLLECTION, cid))
        return self

    def remove_collection(self, cid):
        self.ops.append((TxOp.REMOVE_COLLECTION, cid))
        return self

    def append(self, other: "Transaction"):
        self.ops.extend(other.ops)
        return self

    def empty(self) -> bool:
        return not self.ops


class ObjectStore:
    """Abstract store; see MemStore below."""

    @staticmethod
    def create(kind: str, **kw) -> "ObjectStore":
        """Factory (ObjectStore::create src/os/ObjectStore.cc:28):
        'memstore' (in-RAM, tests) or 'filestore' (durable, WAL-backed)."""
        if kind == "memstore":
            return MemStore(**kw)
        if kind == "filestore":
            from .filestore import FileStore
            return FileStore(**kw)
        if kind == "bluestore":
            from .bluestore import BlueStore
            return BlueStore(**kw)
        raise StoreError(f"unknown objectstore backend {kind!r}")

    # -- lifecycle ---------------------------------------------------------
    def mount(self) -> None: ...
    def umount(self) -> None: ...

    # -- mutation ----------------------------------------------------------
    def queue_transaction(self, tx: Transaction,
                          on_commit: Callable[[], None] | None = None) -> None:
        raise NotImplementedError

    # -- queries -----------------------------------------------------------
    def read(self, cid, oid, offset: int = 0,
             length: int | None = None) -> BufferList:
        raise NotImplementedError

    def stat(self, cid, oid) -> dict:
        raise NotImplementedError

    def exists(self, cid, oid) -> bool:
        raise NotImplementedError

    def getattrs(self, cid, oid) -> dict[str, bytes]:
        raise NotImplementedError

    def omap_get(self, cid, oid) -> dict[str, bytes]:
        raise NotImplementedError

    def list_objects(self, cid) -> list[ObjectId]:
        raise NotImplementedError

    def list_collections(self) -> list[CollectionId]:
        raise NotImplementedError


class _Obj:
    __slots__ = ("data", "attrs", "omap")

    def __init__(self):
        self.data = bytearray()
        self.attrs: dict[str, bytes] = {}
        self.omap: dict[str, bytes] = {}


class MemStore(ObjectStore):
    """In-RAM ObjectStore with atomic transactions (MemStore.cc role)."""

    def __init__(self):
        self._colls: dict[CollectionId, dict[ObjectId, _Obj]] = {}
        self._lock = threading.RLock()
        self._mounted = False

    def mount(self) -> None:
        self._mounted = True

    def umount(self) -> None:
        self._mounted = False

    # -- transaction application (atomic under the store lock) -------------
    def queue_transaction(self, tx: Transaction,
                          on_commit: Callable[[], None] | None = None) -> None:
        with self._lock:
            self.validate(tx)
            for op in tx.ops:
                self._apply(op)
        if on_commit:
            on_commit()

    def validate(self, tx: Transaction) -> None:
        """Raise if the transaction cannot apply; no effects.  Tracks
        objects/collections materialised earlier in the SAME tx so e.g.
        touch-then-truncate sequences validate (all-or-nothing)."""
        with self._lock:
            created: set[tuple] = set()
            for op in tx.ops:
                self._check(op, created)

    def _coll(self, cid) -> dict[ObjectId, _Obj]:
        c = self._colls.get(cid)
        if c is None:
            raise NoSuchCollection(str(cid))
        return c

    _CREATES = (TxOp.TOUCH, TxOp.WRITE, TxOp.ZERO, TxOp.SETATTRS,
                TxOp.OMAP_SETKEYS, TxOp.TRUNCATE)

    def _check(self, op, created: set) -> None:
        kind = op[0]
        if kind == TxOp.CREATE_COLLECTION:
            created.add(("coll", op[1]))
            return
        if kind == TxOp.REMOVE_COLLECTION:
            if ("coll", op[1]) not in created:
                self._coll(op[1])
            return
        cid = op[1]
        if ("coll", cid) in created:
            coll = self._colls.get(cid, {})
        else:
            coll = self._coll(cid)

        def have(oid) -> bool:
            return oid in coll or ("obj", cid, oid) in created

        if kind == TxOp.CLONE:
            if not have(op[2]):
                raise NoSuchObject(str(op[2]))
            created.add(("obj", cid, op[3]))
            return
        if kind in (TxOp.REMOVE, TxOp.RMATTR,
                    TxOp.OMAP_RMKEYS) and not have(op[2]):
            raise NoSuchObject(str(op[2]))
        if kind in self._CREATES:
            created.add(("obj", cid, op[2]))

    def _apply(self, op) -> None:
        kind = op[0]
        if kind == TxOp.CREATE_COLLECTION:
            self._colls.setdefault(op[1], {})
            return
        if kind == TxOp.REMOVE_COLLECTION:
            self._colls.pop(op[1], None)
            return
        cid, oid = op[1], op[2]
        coll = self._coll(cid)
        if kind == TxOp.TOUCH:
            coll.setdefault(oid, _Obj())
        elif kind == TxOp.WRITE:
            _, _, _, offset, data = op
            o = coll.setdefault(oid, _Obj())
            raw = data.to_bytes()
            end = offset + len(raw)
            if len(o.data) < end:
                o.data.extend(b"\0" * (end - len(o.data)))
            o.data[offset:end] = raw
        elif kind == TxOp.ZERO:
            _, _, _, offset, length = op
            o = coll.setdefault(oid, _Obj())
            end = offset + length
            if len(o.data) < end:
                o.data.extend(b"\0" * (end - len(o.data)))
            o.data[offset:end] = b"\0" * length
        elif kind == TxOp.TRUNCATE:
            o = coll.setdefault(oid, _Obj())
            size = op[3]
            if len(o.data) > size:
                del o.data[size:]
            else:
                o.data.extend(b"\0" * (size - len(o.data)))
        elif kind == TxOp.REMOVE:
            coll.pop(oid, None)
        elif kind == TxOp.SETATTRS:
            coll.setdefault(oid, _Obj()).attrs.update(op[3])
        elif kind == TxOp.RMATTR:
            coll[oid].attrs.pop(op[3], None)
        elif kind == TxOp.OMAP_SETKEYS:
            coll.setdefault(oid, _Obj()).omap.update(op[3])
        elif kind == TxOp.OMAP_RMKEYS:
            o = coll[oid]
            for k in op[3]:
                o.omap.pop(k, None)
        elif kind == TxOp.CLONE:
            src = coll[op[2]]
            dst = coll.setdefault(op[3], _Obj())
            dst.data = bytearray(src.data)
            dst.attrs = dict(src.attrs)
            dst.omap = dict(src.omap)
        else:  # pragma: no cover
            raise StoreError(f"unknown tx op {kind}")

    # -- reads -------------------------------------------------------------
    def _obj(self, cid, oid) -> _Obj:
        with self._lock:
            coll = self._coll(cid)
            o = coll.get(oid)
            if o is None:
                raise NoSuchObject(f"{cid}/{oid}")
            return o

    def read(self, cid, oid, offset: int = 0,
             length: int | None = None) -> BufferList:
        o = self._obj(cid, oid)
        with self._lock:
            data = bytes(o.data[offset:None if length is None
                                else offset + length])
        return BufferList(data)

    def stat(self, cid, oid) -> dict:
        o = self._obj(cid, oid)
        return {"size": len(o.data), "attrs": len(o.attrs),
                "omap": len(o.omap)}

    def exists(self, cid, oid) -> bool:
        with self._lock:
            try:
                return oid in self._coll(cid)
            except NoSuchCollection:
                return False

    def getattrs(self, cid, oid) -> dict[str, bytes]:
        return dict(self._obj(cid, oid).attrs)

    def omap_get(self, cid, oid) -> dict[str, bytes]:
        return dict(self._obj(cid, oid).omap)

    def list_objects(self, cid) -> list[ObjectId]:
        with self._lock:
            return sorted(self._coll(cid))

    def list_collections(self) -> list[CollectionId]:
        with self._lock:
            return sorted(self._colls)
