"""ObjectStore: local object storage API + the async commit pipeline.

The capability of the reference's ObjectStore layer (src/os/ObjectStore.h —
collections of objects, atomic Transactions with ordered op-codes,
queue_transactions with commit callbacks :241, factory create
src/os/ObjectStore.cc:28) with MemStore (src/os/memstore/MemStore.cc) as
the first backend.  FileStore / BlueStore slot in behind the same factory.

Objects are keyed by (pool, shard, name) — the ghobject role: shard id
distinguishes EC shard copies, generation supports EC rollback (deferred).

Commit pipeline (the BlueStore queue_transactions + _kv_sync_thread group
commit, src/os/bluestore/BlueStore.cc):

    callers --queue_transaction--> [throttle] --_prepare--> queue
                                                              |
                      kv-sync thread:  drain -> _commit_batch (ONE fsync
                      per batch: all WAL records in one vectored write)
                                                              |
                      finisher thread: on_commit callbacks, in submission
                      order (global FIFO, so per-collection order holds)

Every backend splits ``queue_transaction`` into two primitives:

- ``_prepare(tx)``: synchronous staging + in-RAM apply in the CALLER's
  thread under the store lock.  After it returns, reads observe the
  transaction (read-your-writes holds before durability — the staged /
  shadow-onode state IS the read state) and per-collection ordering is
  fixed by queue position.  Raises exactly like the old inline path
  (validation failures never reach the WAL).
- ``_commit_batch(items)``: makes a whole batch durable with the minimum
  number of fsyncs (device sync + one vectored WAL/KV append + one KV
  fsync for BlueStore; one WAL write + fsync + one file mirror per dirty
  object for FileStore; nothing for MemStore) and returns the fsync
  count.  Runs on the kv-sync thread (or inline in sync mode).

Ordering & durability contract:

- ``on_commit`` fires only after the transaction's WAL record is fsync'd,
  in submission order (the finisher drains a FIFO).
- a crash loses only un-acked transactions: replay applies exactly the
  committed WAL prefix (records are individually crc-framed; a torn tail
  is discarded).  BlueStore additionally defers freed-page reuse and
  deferred-write device IO to AFTER the batch's KV fsync so a committed
  onode can never point at clobbered bytes.
- sync mode (``store_sync_commit=on`` / no ``enable_async``) runs
  prepare+commit inline per transaction — byte-identical on-disk
  behavior to the pre-pipeline stores, for scrub interleaving and tests.

Throttle knobs: ``store_throttle_bytes`` / ``store_throttle_ops`` bound
the queue (admission blocks BEFORE the store lock — BlueStore-style
backpressure instead of unbounded growth); the adaptive batch window
(``store_batch_window_us``, EWMA toward ``store_batch_target_txns``,
clamped to ``store_batch_window_max_us``) adds coalescing delay only
when concurrency exists, decaying to 0 for sequential writers so an
idle store commits promptly.

The KV tier below BlueStore composes with this chain: its background
flush/compaction threads keep ``_commit_batch`` off the merge path,
and when LSM maintenance falls behind, the counted KV write stall
lands on the kv-sync thread → the commit queue stays full → this
admission throttle blocks submitters.  Backpressure stays honest end
to end instead of an unbounded inline merge (osd/sstkv.py).
"""

from __future__ import annotations

import collections
import enum
import threading
import time
from dataclasses import dataclass, field
from typing import Callable

import numpy as np

from ..utils.buffer import BufferList
from ..utils.perf import CounterType, PerfCounters, global_perf


class StoreError(Exception):
    pass


class NoSuchObject(StoreError):
    pass


class NoSuchCollection(StoreError):
    pass


@dataclass(frozen=True, order=True)
class ObjectId:
    """ghobject-shaped key: name + shard (EC) + snapshot generation."""

    name: str
    shard: int = -1  # -1 = whole object / replicated (NO_SHARD)
    generation: int = -1

    def __str__(self) -> str:
        s = self.name
        if self.shard >= 0:
            s += f"(s{self.shard})"
        if self.generation >= 0:
            s += f"(g{self.generation})"
        return s


@dataclass(frozen=True, order=True)
class CollectionId:
    """One PG's object namespace (coll_t)."""

    pool: int
    pg_seed: int

    def __str__(self) -> str:
        return f"{self.pool}.{self.pg_seed:x}"


class TxOp(enum.Enum):
    TOUCH = "touch"
    WRITE = "write"
    ZERO = "zero"
    TRUNCATE = "truncate"
    REMOVE = "remove"
    SETATTRS = "setattrs"
    RMATTR = "rmattr"
    OMAP_SETKEYS = "omap_setkeys"
    OMAP_RMKEYS = "omap_rmkeys"
    CLONE = "clone"
    CREATE_COLLECTION = "create_collection"
    REMOVE_COLLECTION = "remove_collection"


@dataclass
class Transaction:
    """Ordered list of mutations applied atomically (Transaction.h)."""

    ops: list[tuple] = field(default_factory=list)

    def touch(self, cid, oid):
        self.ops.append((TxOp.TOUCH, cid, oid))
        return self

    def write(self, cid, oid, offset: int, data):
        if not isinstance(data, BufferList):
            data = BufferList(data)
        self.ops.append((TxOp.WRITE, cid, oid, offset, data))
        return self

    def zero(self, cid, oid, offset: int, length: int):
        self.ops.append((TxOp.ZERO, cid, oid, offset, length))
        return self

    def truncate(self, cid, oid, size: int):
        self.ops.append((TxOp.TRUNCATE, cid, oid, size))
        return self

    def remove(self, cid, oid):
        self.ops.append((TxOp.REMOVE, cid, oid))
        return self

    def setattrs(self, cid, oid, attrs: dict[str, bytes]):
        self.ops.append((TxOp.SETATTRS, cid, oid, dict(attrs)))
        return self

    def rmattr(self, cid, oid, name: str):
        self.ops.append((TxOp.RMATTR, cid, oid, name))
        return self

    def omap_setkeys(self, cid, oid, kv: dict[str, bytes]):
        self.ops.append((TxOp.OMAP_SETKEYS, cid, oid, dict(kv)))
        return self

    def omap_rmkeys(self, cid, oid, keys):
        self.ops.append((TxOp.OMAP_RMKEYS, cid, oid, list(keys)))
        return self

    def clone(self, cid, src, dst):
        self.ops.append((TxOp.CLONE, cid, src, dst))
        return self

    def create_collection(self, cid):
        self.ops.append((TxOp.CREATE_COLLECTION, cid))
        return self

    def remove_collection(self, cid):
        self.ops.append((TxOp.REMOVE_COLLECTION, cid))
        return self

    def append(self, other: "Transaction"):
        self.ops.extend(other.ops)
        return self

    def empty(self) -> bool:
        return not self.ops


# --------------------------------------------------------------- pipeline

#: the per-store perf schema (registry ``store.<name>``): registered
#: zeroed at pipeline creation so the exporter/metrics-history see one
#: stable shape whether or not traffic has flowed yet.
STORE_COUNTERS = ("store_txns", "store_fsyncs", "store_batches",
                  "store_throttle_stalls",
                  "store_ingest_ref_bytes", "store_ingest_copy_bytes")
STORE_HISTOGRAMS = ("store_commit_us", "store_queue_us",
                    "store_txns_per_fsync", "store_throttle_wait_us")
STORE_GAUGES = ("store_queue_depth",)


def register_store_counters(perf: PerfCounters) -> None:
    """Idempotently register the commit-pipeline counter schema."""
    for n in STORE_COUNTERS:
        if not perf.has(n):
            perf.add(n)
    for n in STORE_HISTOGRAMS:
        if not perf.has(n):
            perf.add(n, CounterType.HISTOGRAM)
    for n in STORE_GAUGES:
        if not perf.has(n):
            perf.add(n, CounterType.U64)


class _QueuedTx:
    __slots__ = ("item", "on_commit", "nbytes", "t_enq", "admitted",
                 "on_error")

    def __init__(self, item, on_commit, nbytes, admitted,
                 on_error=False):
        self.item = item            # backend-opaque prepared txn; None
        #                             = pure completion barrier
        self.on_commit = on_commit
        self.nbytes = nbytes
        self.t_enq = time.monotonic()
        self.admitted = admitted    # counted against the throttle
        # fire even when the batch FAILS: flush events ride this (the
        # waiter re-checks _failed) — durability acks never do (a
        # failed commit must not ack)
        self.on_error = on_error


class CommitPipeline:
    """Per-store kv-sync + finisher threads (see module docstring for
    the full contract).  One instance per async-enabled store; owns the
    ``store.<name>`` perf registry unless handed an external one."""

    #: hard batch-size cut: past this many queued txns the kv thread
    #: commits immediately regardless of window (bounds commit latency
    #: and the single vectored write's size)
    MAX_BATCH = 256

    def __init__(self, store: "ObjectStore", *, name: str = "store",
                 throttle_bytes: int = 64 << 20,
                 throttle_ops: int = 1024,
                 window_us: float = 0.0,
                 window_min_us: float = 50.0,
                 window_max_us: float = 4000.0,
                 target_txns: float = 8.0,
                 adaptive: bool = True,
                 perf: PerfCounters | None = None):
        self._store = store
        self.throttle_bytes = int(throttle_bytes)
        self.throttle_ops = int(throttle_ops)
        self.window_us = float(window_us)
        self.window_min_us = float(window_min_us)
        self.window_max_us = float(window_max_us)
        self.target_txns = float(target_txns)
        self.adaptive = bool(adaptive)
        self._owns_perf = perf is None
        self._perf_name = f"store.{name}"
        self.perf = perf if perf is not None \
            else global_perf().create(self._perf_name)
        register_store_counters(self.perf)
        self._lock = threading.Lock()
        self._cv_work = threading.Condition(self._lock)   # kv thread
        self._cv_space = threading.Condition(self._lock)  # throttled
        self._queue: list[_QueuedTx] = []
        self._bytes = 0
        self._ops = 0
        self._kick = False
        self._stopping = False
        self._failed: BaseException | None = None
        # adaptive-window state (EWMA of observed batch size + commit
        # cost; see _steer_window)
        self._ewma_n = 1.0
        self._ewma_commit_s = 0.0
        self._fin_cv = threading.Condition(threading.Lock())
        self._fin_q: collections.deque = collections.deque()
        self._fin_open = True
        self._kv_thread = threading.Thread(
            target=self._kv_sync_loop, daemon=True,
            name=f"kv-sync-{name}")
        self._fin_thread = threading.Thread(
            target=self._finisher_loop, daemon=True,
            name=f"store-fin-{name}")
        self._kv_thread.start()
        self._fin_thread.start()

    # ------------------------------------------------------------- admit
    def admit(self, nbytes: int) -> None:
        """Admission throttle: block the SUBMITTING thread (never the
        store lock holder — callers admit before preparing) while the
        queue is over either bound."""
        t0 = None
        with self._cv_space:
            while not self._stopping and (
                    self._bytes >= self.throttle_bytes
                    or self._ops >= self.throttle_ops):
                if t0 is None:
                    t0 = time.monotonic()
                    self.perf.inc("store_throttle_stalls")
                self._cv_space.wait(0.5)
            self._bytes += nbytes
            self._ops += 1
            self.perf.set("store_queue_depth", self._ops)
        if t0 is not None:
            self.perf.hinc("store_throttle_wait_us",
                           (time.monotonic() - t0) * 1e6)

    def unadmit(self, nbytes: int) -> None:
        with self._cv_space:
            self._bytes -= nbytes
            self._ops -= 1
            self.perf.set("store_queue_depth", self._ops)
            self._cv_space.notify_all()

    # ------------------------------------------------------------ submit
    def submit(self, item, on_commit, nbytes: int,
               admitted: bool = True) -> None:
        # deliberately NO _failed check here: the caller already
        # applied the transaction in RAM (_prepare), so raising now
        # would error-return a write that stays visible to reads.
        # queue_transaction gates on _failed BEFORE preparing; a
        # failure landing in between means this item enqueues, its
        # batch is skipped, and its ack never fires (op-timeout
        # surfaces it) — the same fate as any tx whose commit fails.
        q = _QueuedTx(item, on_commit, nbytes, admitted)
        with self._cv_work:
            if self._stopping:
                # unreachable through queue_transaction (the order
                # mutex serializes against disable_async); a backstop
                # for direct misuse
                raise StoreError("commit pipeline stopped")
            self._queue.append(q)
            self._cv_work.notify_all()

    def barrier(self, cb: Callable[[], None], kick: bool = False,
                on_error: bool = False) -> None:
        """Queue a completion AFTER everything currently queued: the
        finisher fires ``cb`` once every prior transaction is durable
        (the on_flush role — reply continuations ride this).  Plain
        barriers do NOT cut the batch window — an ack continuation is
        exactly the latency the window is allowed to trade; ``kick``
        (flush) forces an immediate cut.  ``on_error`` barriers fire
        even when the batch fails (flush events; the waiter re-checks
        the failure) — ack barriers never do."""
        with self._cv_work:
            # _stopping (not thread aliveness) is the safe gate: the kv
            # thread decides to exit under this lock, so a cb appended
            # after _stopping could land in a queue nobody drains
            if not self._stopping and self._kv_thread.is_alive():
                self._queue.append(_QueuedTx(None, cb, 0, False,
                                             on_error=on_error))
                if kick:
                    self._kick = True
                self._cv_work.notify_all()
                return
        # pipeline stopping/dismantled (shutdown race): stop()'s flush
        # already drained everything queued before it, so the barrier's
        # contract is satisfied inline
        cb()

    def flush(self, timeout: float = 60.0) -> None:
        """Block until everything queued so far is committed AND its
        callbacks have fired.  Raises StoreError when the pipeline has
        failed or the drain never completes — umount must not close a
        device the kv thread might still be writing."""
        if self._failed is not None:
            raise StoreError(f"commit pipeline failed: {self._failed}")
        if not self._kv_thread.is_alive():
            return
        ev = threading.Event()
        self.barrier(ev.set, kick=True, on_error=True)
        if not ev.wait(timeout):
            raise StoreError(
                f"store flush did not drain in {timeout}s"
                f" ({self._failed or 'commit still in flight'})")
        if self._failed is not None:
            raise StoreError(f"commit pipeline failed: {self._failed}")

    def stop(self) -> None:
        try:
            self.flush()
        except StoreError:
            pass  # failure already surfaced; dismantle regardless
        with self._cv_work:
            self._stopping = True
            self._cv_work.notify_all()
            self._cv_space.notify_all()
        self._kv_thread.join(timeout=10)
        with self._fin_cv:
            self._fin_open = False
            self._fin_cv.notify_all()
        self._fin_thread.join(timeout=10)
        if self._owns_perf:
            global_perf().remove(self._perf_name)

    @property
    def depth(self) -> int:
        with self._lock:
            return self._ops

    # ------------------------------------------------------------ kv sync
    def _kv_sync_loop(self) -> None:
        while True:
            with self._cv_work:
                while not self._queue and not self._stopping:
                    self._cv_work.wait()
                if not self._queue:
                    return  # stopping and drained
                # adaptive coalescing window: give concurrent writers a
                # beat to pile on (deadline anchored at the FIRST
                # arrival so an idle store commits promptly); a flush
                # kick cuts the batch immediately, and a barrier-only
                # queue has nothing to coalesce — fire it now
                w = self.window_us
                if w > 0 and not self._kick and any(
                        q.item is not None for q in self._queue):
                    deadline = self._queue[0].t_enq + w * 1e-6
                    while (not self._kick and not self._stopping
                           and len(self._queue) < self.MAX_BATCH):
                        left = deadline - time.monotonic()
                        if left <= 0:
                            break
                        self._cv_work.wait(left)
                if len(self._queue) > self.MAX_BATCH:
                    # bound the single vectored write (and FileStore's
                    # lock hold) even when the throttle admitted more:
                    # the remainder forms the next batch immediately
                    batch = self._queue[:self.MAX_BATCH]
                    self._queue = self._queue[self.MAX_BATCH:]
                else:
                    batch, self._queue = self._queue, []
                    self._kick = False
            self._run_batch(batch)

    def _run_batch(self, batch: list[_QueuedTx]) -> None:
        t0 = time.monotonic()
        items = [q.item for q in batch if q.item is not None]
        fsyncs = 0
        # once failed, stay failed: a later batch's records would land
        # BEHIND the torn frame — fsync'd but unreachable to replay,
        # so acking them would lose acked writes on the next crash
        err: BaseException | None = self._failed
        if items and err is None:
            try:
                fsyncs = int(self._store._commit_batch(items) or 0)
            except BaseException as e:  # noqa: BLE001 - device/WAL fail
                # a failed group commit must not ack: callbacks for this
                # batch never fire (callers' op timeouts surface it) and
                # the pipeline refuses new work — the reference asserts
                # out here; we fail the store loudly instead
                err = e
                self._failed = e
                from ..utils.log import dout
                dout("store", 0)(
                    "commit pipeline FAILED (store poisoned, "
                    "refusing new work): %r", e)
        commit_s = time.monotonic() - t0
        n = len(items)
        # book only batches that actually committed: a failed or
        # skipped-after-failure batch must not inflate store_txns (the
        # bench's fsyncs-per-txn gate reads these deltas) or steer the
        # window off phantom work
        if n and err is None:
            self.perf.inc("store_txns", n)
            self.perf.inc("store_batches")
            self.perf.inc("store_fsyncs", fsyncs)
            self.perf.hinc("store_commit_us", commit_s * 1e6)
            if fsyncs:
                self.perf.hinc("store_txns_per_fsync", n / fsyncs)
            for q in batch:
                if q.item is not None:
                    self.perf.hinc("store_queue_us",
                                   (t0 - q.t_enq) * 1e6)
            self._steer_window(n, commit_s)
        with self._cv_space:
            for q in batch:
                if q.admitted:
                    self._bytes -= q.nbytes
                    self._ops -= 1
            self.perf.set("store_queue_depth", max(self._ops, 0))
            self._cv_space.notify_all()
        cbs = [q.on_commit for q in batch
               if q.on_commit is not None
               and (err is None or q.on_error)]
        if cbs:
            with self._fin_cv:
                self._fin_q.extend(cbs)
                self._fin_cv.notify_all()

    def _steer_window(self, n: int, commit_s: float) -> None:
        """EWMA steering toward the target batch size, bounded by the
        max-latency clamp.  Growth only while batches show real
        concurrency (n > 1) — a sequential writer's window decays to 0
        so closed-loop latency never pays for coalescing that cannot
        happen; an over-target batch sheds window so a saturated store
        trades no more latency than the target needs."""
        self._ewma_n = 0.7 * self._ewma_n + 0.3 * n
        self._ewma_commit_s = 0.7 * self._ewma_commit_s + 0.3 * commit_s
        if not self.adaptive:
            return
        w = self.window_us
        if self._ewma_n >= self.target_txns:
            w *= 0.7  # coalescing enough without the extra latency
        elif n > 1:
            w = max(w * 1.3, self.window_min_us)
        else:
            w *= 0.5
        if w < 1.0:
            w = 0.0
        self.window_us = min(w, self.window_max_us)

    # ----------------------------------------------------------- finisher
    def _finisher_loop(self) -> None:
        while True:
            with self._fin_cv:
                while not self._fin_q and self._fin_open:
                    self._fin_cv.wait()
                if not self._fin_q:
                    return
                cb = self._fin_q.popleft()
            try:
                cb()
            except Exception as e:  # noqa: BLE001 - a callback must
                # not wedge the finisher behind it — but a vanished
                # reply continuation must leave a trace
                from ..utils.log import dout
                dout("store", 1)("on_commit callback raised: %r", e)


_ORDER_GUARD = threading.Lock()


def _tx_nbytes(tx: "Transaction") -> int:
    """Throttle-accounting estimate: payload bytes + a per-op floor."""
    n = 128 * len(tx.ops)
    for op in tx.ops:
        if op[0] == TxOp.WRITE:
            n += len(op[4])
    return n


class ObjectStore:
    """Abstract store; see MemStore below and the module docstring for
    the async commit pipeline every backend rides."""

    #: class-level default so existing backends need no __init__ change
    _pipeline: CommitPipeline | None = None

    @staticmethod
    def create(kind: str, **kw) -> "ObjectStore":
        """Factory (ObjectStore::create src/os/ObjectStore.cc:28):
        'memstore' (in-RAM, tests) or 'filestore' (durable, WAL-backed)."""
        if kind == "memstore":
            return MemStore(**kw)
        if kind == "filestore":
            from .filestore import FileStore
            return FileStore(**kw)
        if kind == "bluestore":
            from .bluestore import BlueStore
            return BlueStore(**kw)
        raise StoreError(f"unknown objectstore backend {kind!r}")

    # -- lifecycle ---------------------------------------------------------
    def mount(self) -> None: ...
    def umount(self) -> None: ...

    # -- KV metadata tier (BlueStore overrides) ----------------------------
    def configure_kv(self, cfg, name: str | None = None) -> None:
        """Fill unset KV-tier knobs from config before mount; no-op
        for backends without a KV metadata tier."""

    def kv_stats(self) -> dict | None:
        """KV-tier maintenance/occupancy stats (memtable seal depth,
        level shape, stall/cache tallies) or None when the backend has
        no KV tier — the ``dump_kv_stats`` admin surface."""
        return None

    # -- async commit pipeline --------------------------------------------
    def enable_async(self, *, name: str = "store",
                     perf: PerfCounters | None = None, **knobs) -> None:
        """Engage the group-commit pipeline (idempotent).  From here on
        ``queue_transaction`` returns after the in-RAM apply; durability
        and ``on_commit`` ride the kv-sync/finisher threads."""
        if self._pipeline is None:
            self._pipeline = CommitPipeline(self, name=name, perf=perf,
                                            **knobs)

    def disable_async(self) -> None:
        """Drain and dismantle the pipeline (back to inline commits).
        The order mutex serializes the transition: the pipeline is
        fully stopped (kv thread joined) before it is detached, so a
        racing submitter either lands in the queue pre-drain or takes
        the inline path post-detach — never two committers at once."""
        with self._order_mutex():
            p = self._pipeline
            if p is not None:
                p.stop()
                self._pipeline = None

    def flush(self) -> None:
        """Durability barrier: block until every transaction queued so
        far is committed and its callbacks have fired (the
        ObjectStore::flush / sync-mode escape hatch)."""
        p = self._pipeline
        if p is not None:
            p.flush()

    def commit_barrier(self, cb: Callable[[], None]) -> None:
        """Run ``cb`` once everything queued SO FAR is durable — inline
        in sync mode (nothing is pending), via the finisher (in order)
        in async mode.  Reply continuations ride this."""
        p = self._pipeline
        if p is not None:
            p.barrier(cb)
        else:
            cb()

    def _order_mutex(self) -> threading.RLock:
        """Per-instance submission-order lock, created lazily (backends
        predate the pipeline and do not call a base __init__).  Held
        across prepare+enqueue so apply order == WAL/commit order —
        without it two racing writers could apply A,B but journal B,A,
        and a crash replay would resurrect the other serialization."""
        m = getattr(self, "_order_lock", None)
        if m is None:
            with _ORDER_GUARD:
                m = getattr(self, "_order_lock", None)
                if m is None:
                    self._order_lock = m = threading.RLock()
        return m

    @staticmethod
    def _failed_now(p: CommitPipeline) -> bool:
        return p._failed is not None

    def _tx_cost(self, tx: Transaction) -> int:
        """Bytes this transaction will pin in the commit queue — the
        throttle's unit.  Default: payload bytes + a per-op floor;
        backends that hold more per queued item (FileStore's per-tx
        object snapshots) override to account it."""
        return _tx_nbytes(tx)

    def _book(self, name: str, n: int = 1) -> None:
        """Book onto the pipeline's store registry (no-op in sync
        mode — there is no registry to keep a stable schema on)."""
        p = self._pipeline
        if p is not None:
            p.perf.inc(name, n)

    # -- mutation ----------------------------------------------------------
    def queue_transaction(self, tx: Transaction,
                          on_commit: Callable[[], None] | None = None) -> None:
        """Stage + apply in THIS thread (read-your-writes holds on
        return), then commit inline (sync mode) or hand durability to
        the kv-sync thread (async mode).  ``on_commit`` fires after the
        transaction is durable, in submission order."""
        p = self._pipeline
        if p is not None:
            if p._failed is not None:
                # refuse BEFORE the in-RAM apply: an error-returned
                # write must not stay visible to reads while it can
                # never become durable
                raise StoreError(
                    f"commit pipeline failed: {p._failed}")
            nbytes = self._tx_cost(tx)
            p.admit(nbytes)  # BEFORE any lock: backpressure must not
            #                  deadlock against the committing thread
            try:
                with self._order_mutex():
                    if self._failed_now(p):
                        # the kv thread failed while we waited in the
                        # throttle: still BEFORE the in-RAM apply
                        raise StoreError(
                            f"commit pipeline failed: {p._failed}")
                    if self._pipeline is p:  # not dismantled while
                        #                      we waited for the mutex
                        item = self._prepare(tx)
                        p.submit(item, on_commit, nbytes)
                        return
            except BaseException:
                p.unadmit(nbytes)
                raise
            p.unadmit(nbytes)  # fall through to the inline path
        with self._order_mutex():
            item = self._prepare(tx)
            self._commit_batch([item])
        if on_commit is not None:
            on_commit()

    # -- backend primitives (see module docstring) -------------------------
    def _prepare(self, tx: Transaction):
        raise NotImplementedError

    def _commit_batch(self, items: list) -> int:
        raise NotImplementedError

    # -- queries -----------------------------------------------------------
    def read(self, cid, oid, offset: int = 0,
             length: int | None = None) -> BufferList:
        raise NotImplementedError

    def stat(self, cid, oid) -> dict:
        raise NotImplementedError

    def exists(self, cid, oid) -> bool:
        raise NotImplementedError

    def getattrs(self, cid, oid) -> dict[str, bytes]:
        raise NotImplementedError

    def omap_get(self, cid, oid) -> dict[str, bytes]:
        raise NotImplementedError

    def list_objects(self, cid) -> list[ObjectId]:
        raise NotImplementedError

    def list_collections(self) -> list[CollectionId]:
        raise NotImplementedError


class _Obj:
    __slots__ = ("data", "attrs", "omap")

    def __init__(self):
        self.data = bytearray()
        self.attrs: dict[str, bytes] = {}
        self.omap: dict[str, bytes] = {}


class MemStore(ObjectStore):
    """In-RAM ObjectStore with atomic transactions (MemStore.cc role)."""

    def __init__(self):
        self._colls: dict[CollectionId, dict[ObjectId, _Obj]] = {}
        self._lock = threading.RLock()
        self._mounted = False

    def mount(self) -> None:
        self._mounted = True

    def umount(self) -> None:
        self._mounted = False

    #: prepared-transaction token: MemStore has no durable work, but a
    #: real (non-None) item keeps its txns flowing through
    #: _commit_batch so the pipeline's txn/batch counters and window
    #: steering see them (None is reserved for pure barriers)
    _APPLIED = object()

    # -- transaction application (atomic under the store lock) -------------
    def _prepare(self, tx: Transaction):
        """Validate + apply atomically (MemStore's whole commit is the
        in-RAM apply; the payload detaches here — bytearray splicing —
        so a carved rx frame buffer can be reused immediately)."""
        with self._lock:
            self.validate(tx)
            for op in tx.ops:
                self._apply(op)
        return self._APPLIED

    def _commit_batch(self, items: list) -> int:
        return 0  # nothing durable to sync

    def validate(self, tx: Transaction) -> None:
        """Raise if the transaction cannot apply; no effects.  Tracks
        objects/collections materialised earlier in the SAME tx so e.g.
        touch-then-truncate sequences validate (all-or-nothing)."""
        with self._lock:
            created: set[tuple] = set()
            for op in tx.ops:
                self._check(op, created)

    def _coll(self, cid) -> dict[ObjectId, _Obj]:
        c = self._colls.get(cid)
        if c is None:
            raise NoSuchCollection(str(cid))
        return c

    _CREATES = (TxOp.TOUCH, TxOp.WRITE, TxOp.ZERO, TxOp.SETATTRS,
                TxOp.OMAP_SETKEYS, TxOp.TRUNCATE)

    def _check(self, op, created: set) -> None:
        kind = op[0]
        if kind == TxOp.CREATE_COLLECTION:
            created.add(("coll", op[1]))
            return
        if kind == TxOp.REMOVE_COLLECTION:
            if ("coll", op[1]) not in created:
                self._coll(op[1])
            return
        cid = op[1]
        if ("coll", cid) in created:
            coll = self._colls.get(cid, {})
        else:
            coll = self._coll(cid)

        def have(oid) -> bool:
            return oid in coll or ("obj", cid, oid) in created

        if kind == TxOp.CLONE:
            if not have(op[2]):
                raise NoSuchObject(str(op[2]))
            created.add(("obj", cid, op[3]))
            return
        if kind in (TxOp.REMOVE, TxOp.RMATTR,
                    TxOp.OMAP_RMKEYS) and not have(op[2]):
            raise NoSuchObject(str(op[2]))
        if kind in self._CREATES:
            created.add(("obj", cid, op[2]))

    def _apply(self, op) -> None:
        kind = op[0]
        if kind == TxOp.CREATE_COLLECTION:
            self._colls.setdefault(op[1], {})
            return
        if kind == TxOp.REMOVE_COLLECTION:
            self._colls.pop(op[1], None)
            return
        cid, oid = op[1], op[2]
        coll = self._coll(cid)
        if kind == TxOp.TOUCH:
            coll.setdefault(oid, _Obj())
        elif kind == TxOp.WRITE:
            _, _, _, offset, data = op
            o = coll.setdefault(oid, _Obj())
            raw = data.to_bytes()
            end = offset + len(raw)
            if len(o.data) < end:
                o.data.extend(b"\0" * (end - len(o.data)))
            o.data[offset:end] = raw
        elif kind == TxOp.ZERO:
            _, _, _, offset, length = op
            o = coll.setdefault(oid, _Obj())
            end = offset + length
            if len(o.data) < end:
                o.data.extend(b"\0" * (end - len(o.data)))
            o.data[offset:end] = b"\0" * length
        elif kind == TxOp.TRUNCATE:
            o = coll.setdefault(oid, _Obj())
            size = op[3]
            if len(o.data) > size:
                del o.data[size:]
            else:
                o.data.extend(b"\0" * (size - len(o.data)))
        elif kind == TxOp.REMOVE:
            coll.pop(oid, None)
        elif kind == TxOp.SETATTRS:
            coll.setdefault(oid, _Obj()).attrs.update(op[3])
        elif kind == TxOp.RMATTR:
            coll[oid].attrs.pop(op[3], None)
        elif kind == TxOp.OMAP_SETKEYS:
            coll.setdefault(oid, _Obj()).omap.update(op[3])
        elif kind == TxOp.OMAP_RMKEYS:
            o = coll[oid]
            for k in op[3]:
                o.omap.pop(k, None)
        elif kind == TxOp.CLONE:
            src = coll[op[2]]
            dst = coll.setdefault(op[3], _Obj())
            dst.data = bytearray(src.data)
            dst.attrs = dict(src.attrs)
            dst.omap = dict(src.omap)
        else:  # pragma: no cover
            raise StoreError(f"unknown tx op {kind}")

    # -- reads -------------------------------------------------------------
    def _obj(self, cid, oid) -> _Obj:
        with self._lock:
            coll = self._coll(cid)
            o = coll.get(oid)
            if o is None:
                raise NoSuchObject(f"{cid}/{oid}")
            return o

    def read(self, cid, oid, offset: int = 0,
             length: int | None = None) -> BufferList:
        o = self._obj(cid, oid)
        with self._lock:
            data = bytes(o.data[offset:None if length is None
                                else offset + length])
        return BufferList(data)

    def stat(self, cid, oid) -> dict:
        o = self._obj(cid, oid)
        return {"size": len(o.data), "attrs": len(o.attrs),
                "omap": len(o.omap)}

    def exists(self, cid, oid) -> bool:
        with self._lock:
            try:
                return oid in self._coll(cid)
            except NoSuchCollection:
                return False

    def getattrs(self, cid, oid) -> dict[str, bytes]:
        return dict(self._obj(cid, oid).attrs)

    def omap_get(self, cid, oid) -> dict[str, bytes]:
        return dict(self._obj(cid, oid).omap)

    def list_objects(self, cid) -> list[ObjectId]:
        with self._lock:
            return sorted(self._coll(cid))

    def list_collections(self) -> list[CollectionId]:
        with self._lock:
            return sorted(self._colls)
