"""Extended object ops: omap, watch/notify, object classes.

The PrimaryLogPG op breadth beyond read/write/remove/stat (ref
PrimaryLogPG::do_osd_ops op-switch :6163 — omap get/set/rm ops,
watch/notify via src/osd/Watch.cc, `call` into object classes), as a
mixin on OSDDaemon.  Replicated pools only this round: EC omap needs
the ECOmapJournal tier (planned); watch/notify state is primary-local
soft state and clients re-register on map change, the reference's
linger-op semantic.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from ..msg.messages import (MNotifyAck, MOSDOpReply, MSubWrite,
                            MWatchNotify, PgId)
from ..msg.wire import pack_value as _pack, unpack_value as _unpack
from ..ops.native import crc32c as _crc32c
from . import classes as cls_mod
from .objectstore import CollectionId, NoSuchObject, ObjectId, Transaction

EIO, ENOENT, EINVAL = -5, -2, -22


@dataclass
class _PendingNotify:
    client: str
    client_tid: int
    waiting: set
    acked: list = field(default_factory=list)
    stamp: float = field(default_factory=time.time)


class ObjOpsMixin:
    """Mixed into OSDDaemon; dispatches the extended replicated ops."""

    WATCH_TIMEOUT = 30.0  # Watch.cc timeout role; clients renew

    def _init_objops(self) -> None:
        # (pgid, oid) -> {client: (cookie, expires)}  (Watch.cc state)
        self._watchers: dict[tuple, dict[str, tuple]] = {}
        self._pending_notifies: dict[int, _PendingNotify] = {}

    # ---------------------------------------------------------- dispatch
    EXTENDED_OPS = ("omap_get", "omap_set", "omap_rm", "watch",
                    "unwatch", "notify", "call", "list_snaps",
                    "snap_rollback")

    def _handle_extended_op(self, conn, m, pgid: PgId, up: list) -> None:
        pool = self.osdmap.pools[m.pool]
        if pool.kind == "ec":
            # EC omap/watch/cls need the ECOmapJournal tier (planned)
            conn.send(MOSDOpReply(m.tid, EINVAL,
                                  epoch=self.osdmap.epoch))
            return
        handler = {
            "omap_get": self._op_omap_get,
            "omap_set": self._op_omap_mut,
            "omap_rm": self._op_omap_mut,
            "watch": self._op_watch,
            "unwatch": self._op_watch,
            "notify": self._op_notify,
            "call": self._op_call,
            "list_snaps": self._op_list_snaps,
            "snap_rollback": self._op_snap_rollback,
        }[m.op]
        handler(conn, m, pgid, up)

    # -------------------------------------------------------------- omap
    def _op_omap_get(self, conn, m, pgid: PgId, up: list) -> None:
        cid = CollectionId(pgid.pool, pgid.seed)
        try:
            omap = self.store.omap_get(cid, ObjectId(m.oid))
        except NoSuchObject:
            conn.send(MOSDOpReply(m.tid, ENOENT,
                                  epoch=self.osdmap.epoch))
            return
        conn.send(MOSDOpReply(m.tid, 0, data=_pack(omap),
                              epoch=self.osdmap.epoch))

    def _op_omap_mut(self, conn, m, pgid: PgId, up: list) -> None:
        """omap_set (data = packed {key: bytes}) / omap_rm (data =
        packed [keys]); replicated like any write."""
        payload = _unpack(m.data)
        version = self._next_version(pgid)
        if not self._apply_omap(pgid, m.oid, m.op, payload, version,
                                create_ok=(m.op == "omap_set")):
            conn.send(MOSDOpReply(m.tid, ENOENT,
                                  epoch=self.osdmap.epoch))
            return
        peers = [u for u in up if u is not None and u != self.osd_id]
        if not peers:
            conn.send(MOSDOpReply(m.tid, 0, version=version,
                                  epoch=self.osdmap.epoch))
            return
        tid = next(self._tids)
        from .daemon import _PendingWrite
        self._pending_writes[tid] = _PendingWrite(
            m.client, m.tid, len(peers), version)
        for peer in peers:
            self.messenger.send_message(
                f"osd.{peer}",
                MSubWrite(tid, pgid, m.oid, -1, version, m.op, m.data))

    def _apply_omap(self, pgid: PgId, oid: str, op: str, payload,
                    version: int, create_ok: bool = False) -> bool:
        from .pglog import LogEntry
        cid = CollectionId(pgid.pool, pgid.seed)
        obj = ObjectId(oid)
        tx = Transaction()
        exists = self.store.exists(cid, obj)
        if not exists:
            if not create_ok:
                return False
            tx.touch(cid, obj)
        if op == "omap_set":
            tx.omap_setkeys(cid, obj, {str(k): bytes(v)
                                       for k, v in payload.items()})
        else:
            keys = [str(k) for k in payload]
            have = set(self.store.omap_get(cid, obj))
            tx.omap_rmkeys(cid, obj, [k for k in keys if k in have])
        data = self.store.read(cid, obj).to_bytes() if exists else b""
        tx.setattrs(cid, obj, {"v": version, "d": _crc32c(data),
                               "len": len(data)})
        # every versioned mutation logs (last-complete must stay
        # contiguous; delta recovery replays the object WITH its omap)
        self._log_apply(tx, pgid, LogEntry(version, "omap", oid, -1,
                                           prev_version=-1))
        self.store.queue_transaction(tx)
        return True

    # ------------------------------------------------------ watch/notify
    def _op_watch(self, conn, m, pgid: PgId, up: list) -> None:
        key = (pgid, m.oid)
        watchers = self._watchers.setdefault(key, {})
        if m.op == "watch":
            # offset carries the cookie; registration doubles as renewal
            watchers[m.client] = (m.offset,
                                  time.time() + self.WATCH_TIMEOUT)
        else:
            watchers.pop(m.client, None)
            if not watchers:
                self._watchers.pop(key, None)
        conn.send(MOSDOpReply(m.tid, 0, epoch=self.osdmap.epoch))

    def _op_notify(self, conn, m, pgid: PgId, up: list) -> None:
        watchers = dict(self._watchers.get((pgid, m.oid), {}))
        watchers.pop(m.client, None)  # don't notify the notifier
        if not watchers:
            conn.send(MOSDOpReply(m.tid, 0, data=_pack([]),
                                  epoch=self.osdmap.epoch))
            return
        nid = next(self._tids)
        self._pending_notifies[nid] = _PendingNotify(
            m.client, m.tid, waiting=set(watchers))
        for watcher in watchers:
            self.messenger.send_message(
                watcher, MWatchNotify(nid, pgid.pool, m.oid, m.client,
                                      m.data))

    def _handle_notify_ack(self, conn, m: MNotifyAck) -> None:
        pn = self._pending_notifies.get(m.notify_id)
        if pn is None:
            return
        pn.waiting.discard(m.watcher)
        pn.acked.append(m.watcher)
        if pn.waiting:
            return
        del self._pending_notifies[m.notify_id]
        self.messenger.send_message(
            pn.client,
            MOSDOpReply(pn.client_tid, 0, data=_pack(sorted(pn.acked)),
                        epoch=self.osdmap.epoch))

    def _sweep_notifies(self, now: float, max_age: float) -> None:
        # expire watchers that stopped renewing (crashed clients must
        # not make every notify wait out the timeout forever)
        for key, watchers in list(self._watchers.items()):
            for client, (_c, expires) in list(watchers.items()):
                if now > expires:
                    watchers.pop(client, None)
            if not watchers:
                self._watchers.pop(key, None)
        for nid, pn in list(self._pending_notifies.items()):
            if now - pn.stamp > max_age:
                del self._pending_notifies[nid]
                # partial completion: report who DID ack (the reference
                # returns a timeout list alongside)
                self.messenger.send_message(
                    pn.client,
                    MOSDOpReply(pn.client_tid, 0,
                                data=_pack(sorted(pn.acked)),
                                epoch=self.osdmap.epoch
                                if self.osdmap else 0))

    # ---------------------------------------------------- object classes
    def _op_call(self, conn, m, pgid: PgId, up: list) -> None:
        """`call cls.method(input)`: run the class method against the
        object, then apply its queued effects through the replicated
        write path (ClassHandler + do_osd_ops `call`)."""
        req = _unpack(m.data)
        cid = CollectionId(pgid.pool, pgid.seed)
        obj = ObjectId(m.oid)
        exists = self.store.exists(cid, obj)
        data = self.store.read(cid, obj).to_bytes() if exists else b""
        omap = self.store.omap_get(cid, obj) if exists else {}
        ctx = cls_mod.ClsContext(data, omap, exists)
        try:
            out = cls_mod.call(req["cls"], req["method"], ctx,
                               req.get("input"))
        except cls_mod.ClsError as e:
            conn.send(MOSDOpReply(m.tid, e.code,
                                  data=_pack(str(e)),
                                  epoch=self.osdmap.epoch))
            return
        except Exception as e:  # noqa: BLE001 - class bug must still reply
            conn.send(MOSDOpReply(m.tid, EIO, data=_pack(repr(e)),
                                  epoch=self.osdmap.epoch))
            return
        mutated = (ctx.new_data is not None or ctx.omap_set
                   or ctx.omap_rm)
        if not mutated:
            conn.send(MOSDOpReply(m.tid, 0, data=_pack(out),
                                  epoch=self.osdmap.epoch))
            return
        version = self._next_version(pgid)
        effects = {"data": ctx.new_data, "set": dict(ctx.omap_set),
                   "rm": sorted(ctx.omap_rm)}
        self._apply_cls_effects(pgid, m.oid, effects, version)
        peers = [u for u in up if u is not None and u != self.osd_id]
        if not peers:
            conn.send(MOSDOpReply(m.tid, 0, data=_pack(out),
                                  version=version,
                                  epoch=self.osdmap.epoch))
            return
        tid = next(self._tids)
        from .daemon import _PendingWrite
        pw = _PendingWrite(m.client, m.tid, len(peers), version)
        pw.reply_data = _pack(out)
        self._pending_writes[tid] = pw
        for peer in peers:
            self.messenger.send_message(
                f"osd.{peer}",
                MSubWrite(tid, pgid, m.oid, -1, version, "cls_effects",
                          _pack(effects)))

    def _apply_cls_effects(self, pgid: PgId, oid: str, effects: dict,
                           version: int) -> None:
        from .pglog import LogEntry
        cid = CollectionId(pgid.pool, pgid.seed)
        obj = ObjectId(oid)
        tx = Transaction()
        exists = self.store.exists(cid, obj)
        if not exists:
            tx.touch(cid, obj)
        if effects.get("data") is not None:
            tx.truncate(cid, obj, 0)
            tx.write(cid, obj, 0, effects["data"])
            data = bytes(effects["data"])
        else:
            data = self.store.read(cid, obj).to_bytes() if exists \
                else b""
        if effects.get("set"):
            tx.omap_setkeys(cid, obj, {str(k): bytes(v) for k, v
                                       in effects["set"].items()})
        if effects.get("rm"):
            have = set(self.store.omap_get(cid, obj)) if exists else set()
            tx.omap_rmkeys(cid, obj,
                           [k for k in effects["rm"] if k in have])
        # digest/len must track the NEW content or deep scrub flags a
        # phantom mismatch and stat() reports the stale length
        tx.setattrs(cid, obj, {"v": version, "d": _crc32c(data),
                               "len": len(data)})
        self._log_apply(tx, pgid, LogEntry(version, "cls", oid, -1,
                                           prev_version=-1))
        self.store.queue_transaction(tx)
