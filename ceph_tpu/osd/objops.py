"""Extended object ops: omap, watch/notify, object classes.

The PrimaryLogPG op breadth beyond read/write/remove/stat (ref
PrimaryLogPG::do_osd_ops op-switch :6163 — omap get/set/rm ops,
watch/notify via src/osd/Watch.cc, `call` into object classes), as a
mixin on OSDDaemon.

EC pools: omap and user xattrs are supported via full replication to
EVERY shard holder's shard object (the ECOmapJournal capability,
doc/dev/osd_internals/erasure_coding: metadata rides the same
versioned, journaled, rollback-able path as shard data and survives
any k-of-n subset; recovery pushes carry it).  Data-mutating steps in
compound ops and data-mutating cls effects stay EINVAL on EC (they
belong to the stripe pipeline); watch/notify is primary-local soft
state (clients re-register on map change, the linger-op semantic) and
works on either pool kind.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from ..msg.messages import (MNotifyAck, MOSDOpReply, MSubWrite,
                            MWatchNotify, PgId)
from ..msg.wire import pack_value as _pack, unpack_value as _unpack
from ..ops.native import crc32c as _crc32c
from . import classes as cls_mod
from .objectstore import CollectionId, NoSuchObject, ObjectId, Transaction

EIO, ENOENT, EINVAL = -5, -2, -22
EEXIST, ERANGE = -17, -34

# user xattrs live in the object attr dict under this prefix so they can
# never collide with the internal v/d/len bookkeeping attrs
_XATTR_PREFIX = "u:"


def _user_xattrs(attrs: dict) -> dict:
    return {k[len(_XATTR_PREFIX):]: bytes(v) for k, v in attrs.items()
            if isinstance(k, str) and k.startswith(_XATTR_PREFIX)}


@dataclass
class _PendingNotify:
    client: str
    client_tid: int
    waiting: set
    acked: list = field(default_factory=list)
    stamp: float = field(default_factory=time.time)


class ObjOpsMixin:
    """Mixed into OSDDaemon; dispatches the extended replicated ops."""

    WATCH_TIMEOUT = 30.0  # Watch.cc timeout role; clients renew

    def _init_objops(self) -> None:
        # (pgid, oid) -> {client: (cookie, expires)}  (Watch.cc state)
        self._watchers: dict[tuple, dict[str, tuple]] = {}
        self._pending_notifies: dict[int, _PendingNotify] = {}

    # ------------------------------------------------------ shard routing
    def _is_ec(self, pgid: PgId) -> bool:
        return self.osdmap.pools[pgid.pool].kind == "ec"

    def _my_shard(self, pgid: PgId) -> int:
        up = self.osdmap.pg_to_up_osds(pgid.pool, pgid.seed)
        return up.index(self.osd_id) if self.osd_id in up else 0

    def _local_obj(self, pgid: PgId, oid: str) -> ObjectId:
        """The store object this OSD holds for `oid`: plain on
        replicated pools, MY shard object on EC (metadata replicates to
        every shard holder — the ECOmapJournal durability model)."""
        if self._is_ec(pgid):
            return ObjectId(oid, shard=self._my_shard(pgid))
        return ObjectId(oid)

    def _meta_fanout(self, pgid: PgId, up: list) -> list[tuple[int, int]]:
        """(peer_osd, shard_for_peer) for a metadata mutation: every
        shard holder on EC, every replica on replicated pools."""
        out = []
        for pos, osd in enumerate(up):
            if osd is None or osd == self.osd_id:
                continue
            out.append((osd, pos if self._is_ec(pgid) else -1))
        return out

    # ---------------------------------------------------------- dispatch
    EXTENDED_OPS = ("omap_get", "omap_set", "omap_rm", "watch",
                    "unwatch", "notify", "call", "list_snaps",
                    "snap_rollback", "multi_write", "multi_read",
                    "getxattrs")

    def _handle_extended_op(self, conn, m, pgid: PgId, up: list) -> None:
        handler = {
            "omap_get": self._op_omap_get,
            "omap_set": self._op_omap_mut,
            "omap_rm": self._op_omap_mut,
            "watch": self._op_watch,
            "unwatch": self._op_watch,
            "notify": self._op_notify,
            "call": self._op_call,
            "list_snaps": self._op_list_snaps,
            "snap_rollback": self._op_snap_rollback,
            "multi_write": self._op_multi_write,
            "multi_read": self._op_multi_read,
            "getxattrs": self._op_getxattrs,
        }[m.op]
        handler(conn, m, pgid, up)

    # -------------------------------------------------------------- omap
    def _op_omap_get(self, conn, m, pgid: PgId, up: list) -> None:
        cid = CollectionId(pgid.pool, pgid.seed)
        try:
            omap = self.store.omap_get(cid, self._local_obj(pgid, m.oid))
        except NoSuchObject:
            conn.send(MOSDOpReply(m.tid, ENOENT,
                                  epoch=self.osdmap.epoch))
            return
        conn.send(MOSDOpReply(m.tid, 0, data=_pack(omap),
                              epoch=self.osdmap.epoch))

    def _op_omap_mut(self, conn, m, pgid: PgId, up: list) -> None:
        """omap_set (data = packed {key: bytes}) / omap_rm (data =
        packed [keys]); replicated like any write — on EC, to every
        shard holder's shard object."""
        payload = _unpack(m.data)
        version = self._next_version(pgid)
        my_shard = self._my_shard(pgid) if self._is_ec(pgid) else -1
        if not self._apply_omap(pgid, m.oid, m.op, payload, version,
                                create_ok=(m.op == "omap_set"),
                                shard=my_shard):
            conn.send(MOSDOpReply(m.tid, ENOENT,
                                  epoch=self.osdmap.epoch))
            return
        fanout = self._meta_fanout(pgid, up)
        if not fanout:
            conn.send(MOSDOpReply(m.tid, 0, version=version,
                                  epoch=self.osdmap.epoch))
            return
        tid = next(self._tids)
        from .daemon import _PendingWrite
        self._pending_writes[tid] = _PendingWrite(
            m.client, m.tid, len(fanout), version)
        self._pending_writes[tid].span = getattr(m, '_span', None)
        for peer, shard in fanout:
            self.messenger.send_message(
                f"osd.{peer}",
                MSubWrite(tid, pgid, m.oid, shard, version, m.op,
                          m.data, epoch=self._entry_epoch(),
                          tenant=m.tenant))

    def _apply_omap(self, pgid: PgId, oid: str, op: str, payload,
                    version: int, create_ok: bool = False,
                    shard: int = -1) -> bool:
        from .pglog import LogEntry
        cid = CollectionId(pgid.pool, pgid.seed)
        obj = ObjectId(oid, shard=shard)
        tx = Transaction()
        exists = self.store.exists(cid, obj)
        if not exists:
            if not create_ok:
                return False
            tx.touch(cid, obj)
        if op == "omap_set":
            tx.omap_setkeys(cid, obj, {str(k): bytes(v)
                                       for k, v in payload.items()})
        else:
            keys = [str(k) for k in payload]
            have = set(self.store.omap_get(cid, obj))
            tx.omap_rmkeys(cid, obj, [k for k in keys if k in have])
        data = self.store.read(cid, obj).to_bytes() if exists else b""
        # d covers the STORED bytes (compressed or not) — content is
        # untouched by an omap op, so this recompute is a no-op refresh
        attrs = {"v": version, "d": _crc32c(data)}
        if shard >= 0 and exists:
            # EC shard convention: "len" holds the TOTAL object length
            # (set by the stripe write path) — preserve it
            old_len = self.store.getattrs(cid, obj).get("len")
            attrs["len"] = old_len if old_len is not None else len(data)
        elif exists:
            # replicated "len" keeps RAW semantics (a compressed blob's
            # stored size is not its logical size)
            attrs["len"] = self._obj_raw_size(cid, obj)
        else:
            attrs["len"] = len(data)
        tx.setattrs(cid, obj, attrs)
        # every versioned mutation logs (last-complete must stay
        # contiguous; delta recovery replays the object WITH its omap)
        self._log_apply(tx, pgid, LogEntry(version, "omap", oid, shard,
                                           prev_version=-1))
        self.store.queue_transaction(tx)
        return True

    # ------------------------------------------------------ watch/notify
    def _op_watch(self, conn, m, pgid: PgId, up: list) -> None:
        key = (pgid, m.oid)
        watchers = self._watchers.setdefault(key, {})
        if m.op == "watch":
            # offset carries the cookie; registration doubles as renewal
            watchers[m.client] = (m.offset,
                                  time.time() + self.WATCH_TIMEOUT)
        else:
            watchers.pop(m.client, None)
            if not watchers:
                self._watchers.pop(key, None)
        conn.send(MOSDOpReply(m.tid, 0, epoch=self.osdmap.epoch))

    def _op_notify(self, conn, m, pgid: PgId, up: list) -> None:
        watchers = dict(self._watchers.get((pgid, m.oid), {}))
        watchers.pop(m.client, None)  # don't notify the notifier
        if not watchers:
            conn.send(MOSDOpReply(m.tid, 0, data=_pack([]),
                                  epoch=self.osdmap.epoch))
            return
        nid = next(self._tids)
        self._pending_notifies[nid] = _PendingNotify(
            m.client, m.tid, waiting=set(watchers))
        for watcher in watchers:
            self.messenger.send_message(
                watcher, MWatchNotify(nid, pgid.pool, m.oid, m.client,
                                      m.data))

    def _handle_notify_ack(self, conn, m: MNotifyAck) -> None:
        pn = self._pending_notifies.get(m.notify_id)
        if pn is None:
            return
        pn.waiting.discard(m.watcher)
        pn.acked.append(m.watcher)
        if pn.waiting:
            return
        del self._pending_notifies[m.notify_id]
        self.messenger.send_message(
            pn.client,
            MOSDOpReply(pn.client_tid, 0, data=_pack(sorted(pn.acked)),
                        epoch=self.osdmap.epoch))

    def _sweep_notifies(self, now: float, max_age: float) -> None:
        # expire watchers that stopped renewing (crashed clients must
        # not make every notify wait out the timeout forever)
        for key, watchers in list(self._watchers.items()):
            for client, (_c, expires) in list(watchers.items()):
                if now > expires:
                    watchers.pop(client, None)
            if not watchers:
                self._watchers.pop(key, None)
        for nid, pn in list(self._pending_notifies.items()):
            if now - pn.stamp > max_age:
                del self._pending_notifies[nid]
                # partial completion: report who DID ack (the reference
                # returns a timeout list alongside)
                self.messenger.send_message(
                    pn.client,
                    MOSDOpReply(pn.client_tid, 0,
                                data=_pack(sorted(pn.acked)),
                                epoch=self.osdmap.epoch
                                if self.osdmap else 0))

    # ---------------------------------------------------- object classes
    def _op_call(self, conn, m, pgid: PgId, up: list) -> None:
        """`call cls.method(input)`: run the class method against the
        object, then apply its queued effects through the replicated
        write path (ClassHandler + do_osd_ops `call`).  On EC pools the
        method sees omap/xattr state but NOT assembled stripe data
        (ctx.data is empty), and data-mutating effects are rejected —
        the built-in metadata classes (cls_lock, cls_version) are
        exactly this shape."""
        req = _unpack(m.data)
        cid = CollectionId(pgid.pool, pgid.seed)
        is_ec = self._is_ec(pgid)
        obj = self._local_obj(pgid, m.oid)
        exists = self.store.exists(cid, obj)
        data = (self._read_obj_raw(cid, obj)[0]
                if exists and not is_ec else b"")
        omap = self.store.omap_get(cid, obj) if exists else {}
        ctx = cls_mod.ClsContext(data, omap, exists)
        try:
            out = cls_mod.call(req["cls"], req["method"], ctx,
                               req.get("input"))
        except cls_mod.ClsError as e:
            conn.send(MOSDOpReply(m.tid, e.code,
                                  data=_pack(str(e)),
                                  epoch=self.osdmap.epoch))
            return
        except Exception as e:  # noqa: BLE001 - class bug must still reply
            conn.send(MOSDOpReply(m.tid, EIO, data=_pack(repr(e)),
                                  epoch=self.osdmap.epoch))
            return
        if is_ec and ctx.new_data is not None:
            conn.send(MOSDOpReply(m.tid, EINVAL,
                                  data=_pack("cls data write on EC"),
                                  epoch=self.osdmap.epoch))
            return
        mutated = (ctx.new_data is not None or ctx.omap_set
                   or ctx.omap_rm)
        if not mutated:
            conn.send(MOSDOpReply(m.tid, 0, data=_pack(out),
                                  epoch=self.osdmap.epoch))
            return
        version = self._next_version(pgid)
        effects = {"data": ctx.new_data, "set": dict(ctx.omap_set),
                   "rm": sorted(ctx.omap_rm)}
        self._apply_cls_effects(pgid, m.oid, effects, version,
                                shard=self._my_shard(pgid) if is_ec
                                else -1)
        fanout = self._meta_fanout(pgid, up)
        if not fanout:
            conn.send(MOSDOpReply(m.tid, 0, data=_pack(out),
                                  version=version,
                                  epoch=self.osdmap.epoch))
            return
        tid = next(self._tids)
        from .daemon import _PendingWrite
        pw = _PendingWrite(m.client, m.tid, len(fanout), version)
        pw.span = getattr(m, '_span', None)
        pw.reply_data = _pack(out)
        self._pending_writes[tid] = pw
        for peer, shard in fanout:
            self.messenger.send_message(
                f"osd.{peer}",
                MSubWrite(tid, pgid, m.oid, shard, version,
                          "cls_effects", _pack(effects),
                          epoch=self._entry_epoch(),
                          tenant=m.tenant))

    def _apply_cls_effects(self, pgid: PgId, oid: str, effects: dict,
                           version: int, shard: int = -1) -> None:
        from .pglog import LogEntry
        cid = CollectionId(pgid.pool, pgid.seed)
        obj = ObjectId(oid, shard=shard)
        tx = Transaction()
        exists = self.store.exists(cid, obj)
        if not exists:
            tx.touch(cid, obj)
        if effects.get("data") is not None:
            tx.truncate(cid, obj, 0)
            tx.write(cid, obj, 0, effects["data"])
            # raw rewrite of a possibly-compressed blob: drop the
            # stale extent metadata (setattrs merges)
            tx.rmattr(cid, obj, "cz")
            tx.rmattr(cid, obj, "crl")
            data = bytes(effects["data"])
        else:
            data = self.store.read(cid, obj).to_bytes() if exists \
                else b""
        if effects.get("set"):
            tx.omap_setkeys(cid, obj, {str(k): bytes(v) for k, v
                                       in effects["set"].items()})
        if effects.get("rm"):
            have = set(self.store.omap_get(cid, obj)) if exists else set()
            tx.omap_rmkeys(cid, obj,
                           [k for k in effects["rm"] if k in have])
        # digest/len must track the NEW content or deep scrub flags a
        # phantom mismatch and stat() reports the stale length
        attrs = {"v": version, "d": _crc32c(data)}
        if shard >= 0 and exists and effects.get("data") is None:
            old_len = self.store.getattrs(cid, obj).get("len")
            attrs["len"] = old_len if old_len is not None else len(data)
        elif exists and effects.get("data") is None:
            attrs["len"] = self._obj_raw_size(cid, obj)
        else:
            attrs["len"] = len(data)
        tx.setattrs(cid, obj, attrs)
        self._log_apply(tx, pgid, LogEntry(version, "cls", oid, shard,
                                           prev_version=-1))
        self.store.queue_transaction(tx)

    # -------------------------------------------------------- user xattrs
    def _op_getxattrs(self, conn, m, pgid: PgId, up: list) -> None:
        cid = CollectionId(pgid.pool, pgid.seed)
        try:
            attrs = self.store.getattrs(cid, self._local_obj(pgid, m.oid))
        except NoSuchObject:
            conn.send(MOSDOpReply(m.tid, ENOENT, epoch=self.osdmap.epoch))
            return
        if attrs.get("wh"):  # whiteout tombstone = logically absent
            conn.send(MOSDOpReply(m.tid, ENOENT, epoch=self.osdmap.epoch))
            return
        conn.send(MOSDOpReply(m.tid, 0, data=_pack(_user_xattrs(attrs)),
                              epoch=self.osdmap.epoch))

    # ------------------------------------------------------- compound ops
    # The do_osd_ops batching contract (PrimaryLogPG::do_osd_ops executes
    # the op vector in order inside one transaction; any failing step
    # unwinds the whole op): guards are checked against a simulated object
    # state, mutations fold into ONE effects record, and nothing touches
    # the store until every step has passed.

    def _op_multi_read(self, conn, m, pgid: PgId, up: list) -> None:
        steps = _unpack(m.data)
        cid = CollectionId(pgid.pool, pgid.seed)
        is_ec = self._is_ec(pgid)
        obj = self._local_obj(pgid, m.oid)
        if is_ec and any(st.get("op") == "read" for st in steps):
            # stripe data reads belong to the EC read pipeline (use the
            # plain read op); metadata steps are served here
            conn.send(MOSDOpReply(m.tid, EINVAL,
                                  epoch=self.osdmap.epoch))
            return
        exists = (self.store.exists(cid, obj)
                  and not self._head_whiteout(cid, m.oid))
        data: bytes | None = None  # loaded on the first step that needs it

        def cur() -> bytes:
            nonlocal data
            if data is None:
                data = (self._read_obj_raw(cid, obj)[0]
                        if exists else b"")
            return data

        def size() -> int:
            if data is not None:
                return len(data)
            attrs = self.store.getattrs(cid, obj) if exists else {}
            ln = attrs.get("len")  # NOT .get(k, len(cur())): the
            # default would evaluate eagerly and always load the body
            return int(ln) if ln is not None else len(cur())

        results = []
        for st in steps:
            op = st.get("op")
            if op == "assert_exists":
                if not exists:
                    conn.send(MOSDOpReply(m.tid, ENOENT,
                                          epoch=self.osdmap.epoch))
                    return
                results.append(None)
            elif op == "read":
                if not exists:
                    conn.send(MOSDOpReply(m.tid, ENOENT,
                                          epoch=self.osdmap.epoch))
                    return
                off = int(st.get("off", 0))
                ln = int(st.get("len", 0)) or len(cur()) - off
                results.append(cur()[off:off + max(ln, 0)])
            elif op == "stat":
                if not exists:
                    conn.send(MOSDOpReply(m.tid, ENOENT,
                                          epoch=self.osdmap.epoch))
                    return
                results.append(size())
            elif op == "omap_get":
                results.append(self.store.omap_get(cid, obj)
                               if exists else {})
            elif op == "getxattrs":
                results.append(_user_xattrs(
                    self.store.getattrs(cid, obj) if exists else {}))
            else:
                conn.send(MOSDOpReply(m.tid, EINVAL,
                                      epoch=self.osdmap.epoch))
                return
        conn.send(MOSDOpReply(m.tid, 0, data=_pack(results),
                              epoch=self.osdmap.epoch))

    def _op_multi_write(self, conn, m, pgid: PgId, up: list) -> None:
        key = (pgid, m.oid)

        def thunk(conn=conn, m=m, pgid=pgid, key=key):
            self._exec_multi_write(conn, m, pgid, key)

        self._obj_lock(key, thunk)

    def _exec_multi_write(self, conn, m, pgid: PgId, key: tuple) -> None:
        """Runs under the object write lock.  Every reply path must
        either hand the lock to a _PendingWrite (released on final ack)
        or release it here."""
        steps = _unpack(m.data)
        cid = CollectionId(pgid.pool, pgid.seed)
        is_ec = self._is_ec(pgid)
        obj = self._local_obj(pgid, m.oid)
        if is_ec and any(st.get("op") in ("write_full", "write",
                                          "append", "truncate", "zero",
                                          "remove")
                         for st in steps):
            # stripe data mutations belong to the EC write pipeline
            conn.send(MOSDOpReply(m.tid, EINVAL,
                                  epoch=self.osdmap.epoch))
            self._obj_unlock(key)
            return
        present = self.store.exists(cid, obj)
        attrs = self.store.getattrs(cid, obj) if present else {}
        was_whiteout = present and bool(attrs.get("wh"))
        # a whiteout'd head is logically absent (snapshot tombstone)
        exists = present and not was_whiteout
        cur_version = int(attrs.get("v", 0))
        # body loads lazily: omap/xattr-only batches on a large object
        # must not pay a full read
        data = b""
        loaded = not exists

        def cur() -> bytes:
            nonlocal data, loaded
            if not loaded:
                data = self._read_obj_raw(cid, obj)[0]
                loaded = True
            return data

        def fail(code: int) -> None:
            conn.send(MOSDOpReply(m.tid, code, epoch=self.osdmap.epoch))
            self._obj_unlock(key)

        # simulate, folding mutations into the final-state effects record
        eff = {"remove": False, "create": not exists, "data": None,
               "set": {}, "rm": [], "xset": {}, "xrm": []}
        touched = False
        for st in steps:
            op = st.get("op")
            if eff["remove"]:
                # a final-state effects record cannot express
                # remove-then-mutate (stale omap would survive on
                # replicas): remove must be the batch's last step
                return fail(EINVAL)
            if op == "assert_exists":
                if not exists:
                    return fail(ENOENT)
            elif op == "assert_version":
                if cur_version != int(st.get("ver", -1)):
                    return fail(ERANGE)
            elif op == "create":
                if exists and st.get("excl"):
                    return fail(EEXIST)
                exists, touched = True, True
            elif op == "write_full":
                data, loaded = bytes(st["data"]), True
                exists = touched = True
                eff["data"] = data
            elif op == "write":
                off = int(st.get("off", 0))
                buf = bytes(st["data"])
                base = cur()
                if off > len(base):
                    base = base + b"\x00" * (off - len(base))
                data = base[:off] + buf + base[off + len(buf):]
                exists = touched = True
                eff["data"] = data
            elif op == "append":
                data = cur() + bytes(st["data"])
                exists = touched = True
                eff["data"] = data
            elif op == "truncate":
                size = int(st.get("size", 0))
                base = cur()
                data = (base[:size] if size <= len(base)
                        else base + b"\x00" * (size - len(base)))
                exists = touched = True
                eff["data"] = data
            elif op == "zero":
                off, ln = int(st.get("off", 0)), int(st.get("len", 0))
                base = cur()
                if off < len(base) and ln > 0:
                    end = min(off + ln, len(base))
                    data = base[:off] + b"\x00" * (end - off) + base[end:]
                    eff["data"] = data
                exists = touched = True
            elif op == "remove":
                if not exists:
                    return fail(ENOENT)
                exists, touched = False, True
                data, loaded = b"", True
                eff.update(remove=True, create=False, data=None,
                           set={}, rm=[], xset={}, xrm=[])
            elif op == "setxattr":
                eff["xset"][str(st["name"])] = bytes(st["value"])
                exists = touched = True
            elif op == "rmxattr":
                name = str(st["name"])
                eff["xset"].pop(name, None)
                eff["xrm"].append(name)
                touched = True
            elif op == "omap_set":
                eff["set"].update({str(k): bytes(v)
                                   for k, v in st["kv"].items()})
                eff["rm"] = [k for k in eff["rm"] if k not in st["kv"]]
                exists = touched = True
            elif op == "omap_rm":
                for k in st["keys"]:
                    eff["set"].pop(str(k), None)
                    eff["rm"].append(str(k))
                touched = True
            else:
                return fail(EINVAL)
        if eff["remove"]:
            eff["create"] = False
        if not touched:  # pure-guard batch: nothing to write or replicate
            conn.send(MOSDOpReply(m.tid, 0, version=cur_version,
                                  epoch=self.osdmap.epoch))
            self._obj_unlock(key)
            return

        # snapshots: the batch's net effect is one head write — stage
        # clone-on-write exactly like _rep_write/_rep_remove do
        # (make_writeable; the rider replicates the staged clone)
        from types import SimpleNamespace
        if eff["remove"]:
            shim_op = "remove"
        elif eff["data"] is not None:
            shim_op = "write_full"
        else:
            shim_op = "attr"  # omap/xattr only: clone, but no overlap shrink
        shim = SimpleNamespace(
            oid=m.oid, op=shim_op, offset=0,
            data=eff["data"] if eff["data"] is not None else b"",
            snap_seq=getattr(m, "snap_seq", 0),
            snaps=list(getattr(m, "snaps", []) or []))
        snap_tx, rider = self._snap_prepare(pgid, shim)
        if eff["remove"]:
            # a head with clones (or one staged this instant) must
            # whiteout, not vanish — its SnapSet serves snapshot reads
            ss = self._load_ss(cid, m.oid) or {}
            if ss.get("clones") or (rider is not None
                                    and rider.get("clone", -1) >= 0):
                eff["remove"] = False
                eff["whiteout"] = True
        if was_whiteout and not eff["remove"] and not eff.get("whiteout"):
            eff["clear_wh"] = True  # resurrection clears the tombstone

        version = self._next_version(pgid)
        self._apply_multi_effects(pgid, m.oid, eff, version,
                                  pre_tx=snap_tx,
                                  shard=self._my_shard(pgid) if is_ec
                                  else -1)
        up = self.osdmap.pg_to_up_osds(pgid.pool, pgid.seed)
        fanout = self._meta_fanout(pgid, up)
        if not fanout:
            conn.send(MOSDOpReply(m.tid, 0, version=version,
                                  epoch=self.osdmap.epoch))
            self._obj_unlock(key)
            return
        tid = next(self._tids)
        from .daemon import _PendingWrite
        pw = _PendingWrite(m.client, m.tid, len(fanout), version)
        pw.span = getattr(m, '_span', None)
        pw.lock_key = key
        self._pending_writes[tid] = pw
        payload = _pack(eff)
        sub_attrs = {"_snap": rider} if rider is not None else {}
        for peer, shard in fanout:
            self.messenger.send_message(
                f"osd.{peer}",
                MSubWrite(tid, pgid, m.oid, shard, version,
                          "multi_effects", payload,
                          attrs=dict(sub_attrs),
                          epoch=self._entry_epoch(),
                          tenant=m.tenant))

    def _apply_multi_effects(self, pgid: PgId, oid: str, eff: dict,
                             version: int, pre_tx=None,
                             shard: int = -1) -> None:
        """Apply one compound-write effects record in ONE transaction
        (primary and replicas run the identical code; pre_tx carries the
        staged clone-on-write from _snap_prepare / the replica rider)."""
        from .pglog import LogEntry
        if eff.get("whiteout"):
            self._apply_whiteout(pgid, oid, version, pre_tx=pre_tx)
            return
        if eff.get("remove"):
            self._apply_remove(pgid, oid, shard, version)
            return
        cid = CollectionId(pgid.pool, pgid.seed)
        obj = ObjectId(oid, shard=shard)
        tx = pre_tx if pre_tx is not None else Transaction()
        exists = self.store.exists(cid, obj)
        if not exists:
            tx.touch(cid, obj)
            if not eff.get("create") and eff.get("data") is None:
                # replica lagging a previous create: the touch above
                # materializes it, deltas below still apply cleanly
                pass
        if eff.get("data") is not None:
            tx.truncate(cid, obj, 0)
            tx.write(cid, obj, 0, bytes(eff["data"]))
            tx.rmattr(cid, obj, "cz")
            tx.rmattr(cid, obj, "crl")
            data = bytes(eff["data"])
        else:
            data = None  # content untouched: existing d/len stay valid
        if eff.get("set"):
            tx.omap_setkeys(cid, obj, {str(k): bytes(v)
                                       for k, v in eff["set"].items()})
        if eff.get("rm"):
            have = set(self.store.omap_get(cid, obj)) if exists else set()
            tx.omap_rmkeys(cid, obj,
                           [k for k in eff["rm"] if k in have])
        if data is not None:
            newattrs = {"v": version, "d": _crc32c(data),
                        "len": len(data)}
        elif exists:
            newattrs = {"v": version}
        else:  # fresh object with no data step (e.g. bare create)
            newattrs = {"v": version, "d": _crc32c(b""), "len": 0}
        if eff.get("clear_wh"):
            newattrs["wh"] = 0
        for name, value in (eff.get("xset") or {}).items():
            newattrs[_XATTR_PREFIX + str(name)] = bytes(value)
        tx.setattrs(cid, obj, newattrs)
        if eff.get("xrm") and exists:
            have = self.store.getattrs(cid, obj)
            for name in eff["xrm"]:
                k = _XATTR_PREFIX + str(name)
                if k in have and k not in newattrs:
                    tx.rmattr(cid, obj, k)
        self._log_apply(tx, pgid, LogEntry(version, "multi", oid, shard,
                                           prev_version=-1))
        self.store.queue_transaction(tx)
