"""PGLog: the per-PG durable op log with rollback info.

The capability of the reference's PGLog (src/osd/PGLog.{h,cc}: per-PG
op log enabling log-based delta recovery instead of full backfill,
divergent-entry handling on peering, and EC partial-apply rollback via
stashed rollback info — doc/dev/osd_internals/erasure_coding/
ecbackend.rst:10-27), re-shaped for this runtime:

- entries live in the omap of a reserved meta object inside the PG
  collection, appended in the SAME Transaction as the data mutation, so
  the FileStore WAL makes log+data atomic (a crash never records a
  write without its log entry or vice versa);
- each partial-write entry stashes the OLD extent bytes it overwrote
  (the rollback generation role): a shard that applied a write the rest
  of the stripe never committed can roll back to the agreed version
  instead of poisoning decode;
- the log keeps a bounded tail window (osd_min_pg_log_entries role):
  peers within the window delta-resync by replaying entry object names;
  older peers fall back to the full inventory exchange (backfill).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..utils.codec import Decoder, Encoder
from .objectstore import ObjectId, Transaction

# reserved shard id for PG metadata objects (never collides with EC
# shard ids >= 0 or the replicated marker -1)
PGLOG_OID = ObjectId("__pglog__", shard=-9)


@dataclass
class LogEntry:
    version: int        # pg-wide version of this mutation
    op: str             # write | rows | delta | remove
    oid: str
    shard: int          # the LOCAL shard the mutation touched
    prev_version: int   # object version this apply was based on
    # stashed pre-image for rollback: [(shard_off, old bytes)]; empty
    # for whole-object writes (rollback = drop + rebuild from peers)
    rollback: list = field(default_factory=list)
    old_len: int = -1   # object 'len' attr before the write (-1 unknown)
    old_shard_len: int = -1  # stored shard-stream bytes before the write
    # map epoch of the interval the PRIMARY served this write in (the
    # eversion_t epoch half, src/osd/osd_types.h): two entries at the
    # same version from different epochs are divergent forks and the
    # newer interval wins (src/osd/PGLog.h:1344 merge rule).  0 = legacy
    # entry written before epochs were stamped (never treated as a fork)
    epoch: int = 0

    def encode_bytes(self) -> bytes:
        e = Encoder()

        def body(se: Encoder):
            se.u64(self.version)
            se.string(self.op)
            se.string(self.oid)
            se.i64(self.shard)
            se.i64(self.prev_version)
            se.u32(len(self.rollback))
            for off, data in self.rollback:
                se.u64(off)
                se.blob(bytes(data))
            se.i64(self.old_len)
            se.i64(self.old_shard_len)  # v2 tail
            se.u64(self.epoch)          # v3 tail
        e.versioned(3, 1, body)
        return e.tobytes()

    @classmethod
    def decode_bytes(cls, raw: bytes) -> "LogEntry":
        d = Decoder(raw)

        def body(sd: Decoder, v: int):
            ent = cls(sd.u64(), sd.string(), sd.string(), sd.i64(),
                      sd.i64())
            ent.rollback = [(sd.u64(), sd.blob())
                            for _ in range(sd.u32())]
            ent.old_len = sd.i64()
            if v >= 2:
                ent.old_shard_len = sd.i64()
            if v >= 3:
                ent.epoch = sd.u64()
            return ent
        return d.versioned(3, body)


def _key(version: int) -> str:
    return f"v{version:016x}"


class PGLog:
    """One PG's log view over the store omap.  All mutation goes through
    Transactions the caller queues (atomicity with the data write)."""

    KEEP = 128  # tail window retained (osd_min_pg_log_entries role)

    def __init__(self, store, cid):
        self._store = store
        self._cid = cid
        self._count: int | None = None  # cached entry count (hot path)

    # -- append (rides the caller's Transaction) ---------------------------
    def append_to(self, tx: Transaction, entry: LogEntry) -> None:
        if not self._store.exists(self._cid, PGLOG_OID):
            tx.touch(self._cid, PGLOG_OID)
        tx.omap_setkeys(self._cid, PGLOG_OID,
                        {_key(entry.version): entry.encode_bytes()})
        if self._count is None:
            self._count = len(self._raw())
        self._count += 1

    def trim_to(self, tx: Transaction, keep: int | None = None) -> None:
        """Drop entries beyond the tail window (paxos-trim analogue).
        The cached count keeps the per-write cost O(1); the actual key
        scan only runs when the window overflows."""
        keep = keep or self.KEEP
        if self._count is not None and self._count <= 2 * keep:
            return
        entries = self._raw()
        self._count = len(entries)
        if len(entries) <= keep:
            return
        drop = sorted(entries)[: len(entries) - keep]
        tx.omap_rmkeys(self._cid, PGLOG_OID, drop)
        self._count -= len(drop)

    # -- queries -----------------------------------------------------------
    def _raw(self) -> dict[str, bytes]:
        try:
            omap = self._store.omap_get(self._cid, PGLOG_OID)
        except Exception:  # noqa: BLE001 - no log object yet
            return {}
        # the meta object also carries non-entry keys (e.g. "_lc")
        return {k: v for k, v in omap.items() if k.startswith("v")}

    def entries(self) -> list[LogEntry]:
        raw = self._raw()
        return [LogEntry.decode_bytes(raw[k]) for k in sorted(raw)]

    def last_version(self) -> int:
        raw = self._raw()
        if not raw:
            return 0
        return LogEntry.decode_bytes(raw[max(raw)]).version

    def last_epoch_version(self) -> tuple[int, int]:
        """(epoch, version) of the newest entry — the log head as an
        eversion: the divergence comparator (PGLog.h:1344)."""
        raw = self._raw()
        if not raw:
            return (0, 0)
        e = LogEntry.decode_bytes(raw[max(raw)])
        return (e.epoch, e.version)

    def floor(self) -> int:
        """Oldest version still logged (0 = empty log)."""
        raw = self._raw()
        if not raw:
            return 0
        return LogEntry.decode_bytes(raw[min(raw)]).version

    def entries_after(self, version: int) -> list[LogEntry]:
        return [e for e in self.entries() if e.version > version]

    def entries_for(self, oid: str) -> list[LogEntry]:
        return [e for e in self.entries() if e.oid == oid]

    # -- rollback (the EC partial-apply rollback role) ---------------------
    @staticmethod
    def _undoable(e: LogEntry) -> bool:
        """Entries we can revert in place: stashed pre-images, or pure
        version bumps (a partial write's untouched data shards get an
        empty-extent apply just to move 'v' — nothing to restore)."""
        return bool(e.rollback) or (e.op == "rows" and not e.rollback
                                    and e.prev_version >= 0)

    def rollback_object(self, oid: str, shard: int,
                        to_version: int) -> bool:
        """Undo this shard's applies on `oid` down to `to_version` using
        stashed pre-images, newest first, in ONE transaction (data +
        attrs + log-span removal commit together — a crash mid-rollback
        must never leave rolled-back bytes stamped with the new
        version).  Returns False when any entry in the span lacks
        rollback info (whole-object write or trimmed log) — the caller
        must then drop the shard object and let recovery rebuild it."""
        obj = ObjectId(oid, shard=shard)
        ents = self.entries_for(oid)  # one decode: span + ev lookup
        span = sorted((e for e in ents
                       if e.shard == shard and e.version > to_version),
                      key=lambda e: -e.version)
        if not span:
            return True
        if any(not self._undoable(e) for e in span):
            return False
        # compute the rolled-back content in memory so the digest can
        # ride the same transaction as the writes
        data = bytearray(self._store.read(self._cid, obj).to_bytes())
        for e in span:
            for off, old in e.rollback:
                end = off + len(old)
                if len(data) < end:
                    data.extend(b"\0" * (end - len(data)))
                data[off:end] = old
        final = span[-1]
        if 0 <= final.old_shard_len < len(data):
            # a grown write extended the shard stream: restore its length
            # or decode against peers' shorter streams would misalign
            data = data[: final.old_shard_len]
        attrs = dict(self._store.getattrs(self._cid, obj))
        attrs["v"] = to_version
        # restore the entry-epoch attr too: leaving the discarded
        # interval's stamp on a rolled-back object would ride a later
        # recovery push and resurrect the dead interval's epoch in a
        # fresh log entry (a phantom fork).  0 when the entry at
        # to_version is trimmed/legacy — never treated as a fork.
        attrs["ev"] = next((e.epoch for e in ents
                            if e.version == to_version), 0)
        if final.old_len >= 0:
            attrs["len"] = final.old_len
        from ..ops.native import crc32c
        attrs["d"] = crc32c(bytes(data))
        tx = Transaction()
        for e in span:
            for off, old in e.rollback:
                tx.write(self._cid, obj, off, old)
        if 0 <= final.old_shard_len:
            tx.truncate(self._cid, obj, final.old_shard_len)
        tx.setattrs(self._cid, obj, attrs)
        tx.omap_rmkeys(self._cid, PGLOG_OID,
                       [_key(e.version) for e in span])
        self._store.queue_transaction(tx)
        return True
