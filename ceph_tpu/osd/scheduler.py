"""mClock-style op scheduler: QoS between client, recovery, and scrub.

The capability of the reference's OpScheduler + mClockScheduler
(src/osd/scheduler/OpScheduler.h:37, mClockScheduler.cc, vendored
dmclock): ops are tagged per class with reservation / weight / limit
(R, W, L) tags and served reservation-first, then by weighted
proportional share among classes under their limit — so background
recovery and scrub cannot starve client IO, yet keep a guaranteed
floor when the client is idle.

Sharding (the reference's sharded OpWQ, osd_op_num_shards): ops hash
by PG to one of N independent scheduler shards, each with its own
dmclock state and dequeue worker — PGs execute in parallel inside one
OSD while everything touching one object stays ordered on its shard.
The messenger dispatch thread only classifies and enqueues.
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass

from ..utils.perf import CounterType, PerfCounters


@dataclass
class ClassParams:
    reservation: float  # guaranteed ops/sec (0 = none)
    weight: float       # proportional share when past reservation
    limit: float        # max ops/sec (0 = unlimited)


def register_qos_counters(perf: PerfCounters, classes) -> None:
    """Per-class QoS counters on a daemon registry — the exporter face
    of the scheduler's Python dicts (served/dropped were invisible to a
    live scrape before this).  Idempotent: shards share one registry,
    and re-adding would RESET live counters (PerfCounters.has)."""
    for c in classes:
        for name in (f"mclock_served_{c}", f"mclock_dropped_{c}"):
            if not perf.has(name):
                perf.add(name)
        if not perf.has(f"mclock_depth_{c}"):
            perf.add(f"mclock_depth_{c}", CounterType.U64)
        if not perf.has(f"mclock_qwait_us_{c}"):
            # enqueue->service wait: the quantity QoS actually moves —
            # prom_rules.py stands p50/p99 recording rules on these
            perf.add(f"mclock_qwait_us_{c}", CounterType.HISTOGRAM)


class MClockScheduler:
    """Single-server dmclock over named classes.

    Tag rules (dmclock paper / mClockScheduler.cc):
      r_tag = max(now, prev_r + 1/R)    (reservation clock)
      p_tag = max(now, prev_p + 1/W)    (proportional virtual clock)
      l_tag = max(now, prev_l + 1/L)    (limit clock)
    Serve: earliest r_tag <= now first; otherwise smallest p_tag among
    classes whose l_tag <= now; otherwise wait for the nearest tag.
    """

    #: per-class queue bound: a rate-limited class must not buffer an
    #: unbounded backlog of full message payloads (drops are the lossy
    #: messenger semantic; recovery retries via requery rounds)
    QUEUE_CAP = 512

    def __init__(self, handler, classes: dict[str, ClassParams],
                 name: str = "mclock", clock=time.monotonic,
                 perf: PerfCounters | None = None):
        self._handler = handler
        self._classes = {}
        for c, p in classes.items():
            self._classes[c] = self._clamp(p)
        self._clock = clock
        self.dropped: dict[str, int] = {c: 0 for c in classes}
        self._queues: dict[str, collections.deque] = {
            c: collections.deque() for c in classes}
        # parallel enqueue stamps feeding the per-class wait histogram;
        # tests that append to _queues directly simply record no stamp
        self._stamps: dict[str, collections.deque] = {
            c: collections.deque() for c in classes}
        self._tags = {c: {"r": 0.0, "p": 0.0, "l": 0.0} for c in classes}
        self._cv = threading.Condition()
        self._stop = False
        self.served: dict[str, int] = {c: 0 for c in classes}
        self._perf = perf
        if perf is not None:
            register_qos_counters(perf, classes)
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)

    @staticmethod
    def _clamp(p: ClassParams) -> ClassParams:
        if p.limit > 0 and p.reservation > p.limit:
            # limit is the hard upper bound: a reservation above it
            # would silently exceed the configured cap
            return ClassParams(p.limit, p.weight, p.limit)
        return p

    def set_params(self, klass: str, p: ClassParams) -> None:
        """Live QoS reconfiguration (the `config set osd_mclock_*` +
        reset path): swap one class's (R, W, L) under the lock; queued
        items keep their positions, tags re-pace from the next pick."""
        with self._cv:
            if klass not in self._classes:
                raise KeyError(f"unknown scheduler class {klass!r}")
            self._classes[klass] = self._clamp(p)
            self._cv.notify_all()

    def start(self) -> None:
        self._thread.start()

    def shutdown(self) -> None:
        with self._cv:
            self._stop = True
            # reconcile the depth gauges for items dying in the queues:
            # the daemon's perf registry OUTLIVES a kill/revive cycle
            # (global_perf().create returns the existing registry), so
            # an unreconciled gauge would stay inflated forever on the
            # revived daemon's scrapes
            for c, q in self._queues.items():
                if q and self._perf is not None:
                    self._perf.inc(f"mclock_depth_{c}", -len(q))
                q.clear()
                self._stamps[c].clear()
            self._cv.notify_all()
        if self._thread.ident is not None:  # never-started: no join
            self._thread.join(timeout=5)

    def enqueue(self, klass: str, item) -> None:
        with self._cv:
            q = self._queues[klass]
            if len(q) >= self.QUEUE_CAP:
                self.dropped[klass] += 1
                if self._perf is not None:
                    self._perf.inc(f"mclock_dropped_{klass}")
                return  # lossy backpressure; senders retry/requery
            if not q:
                # idle->busy: catch the proportional clock up to the
                # busy minimum so an idle class cannot burst unfairly
                busy = [self._tags[c]["p"]
                        for c, qq in self._queues.items() if qq]
                if busy:
                    t = self._tags[klass]
                    t["p"] = max(t["p"], min(busy))
            q.append(item)
            self._stamps[klass].append(self._clock())
            if self._perf is not None:
                self._perf.inc(f"mclock_depth_{klass}")
            self._cv.notify()

    def queue_depth(self, klass: str | None = None) -> int:
        with self._cv:
            if klass is not None:
                return len(self._queues[klass])
            return sum(len(q) for q in self._queues.values())

    def queue_depths(self) -> dict[str, int]:
        with self._cv:
            return {c: len(q) for c, q in self._queues.items()}

    # ------------------------------------------------------------ worker
    def _pick(self, now: float):
        """(klass, phase) to serve now, or (None, wake_at).

        Tags hold NEXT-ELIGIBLE instants: "r" the next reservation
        service, "l" the next limit-allowed service; "p" is a virtual
        round number compared only among busy classes."""
        best_r = None
        wake = None
        for c, q in self._queues.items():
            if not q:
                continue
            p = self._classes[c]
            if p.reservation > 0:
                r_next = self._tags[c]["r"]
                if r_next <= now and (best_r is None
                                      or r_next < best_r[1]):
                    best_r = (c, r_next)
                elif r_next > now:
                    wake = r_next if wake is None else min(wake, r_next)
        if best_r is not None:
            return best_r[0], "reservation"
        best_p = None
        for c, q in self._queues.items():
            if not q:
                continue
            p = self._classes[c]
            if p.limit > 0 and self._tags[c]["l"] > now:
                l_next = self._tags[c]["l"]
                wake = l_next if wake is None else min(wake, l_next)
                continue
            p_tag = self._tags[c]["p"]
            if best_p is None or p_tag < best_p[1]:
                best_p = (c, p_tag)
        if best_p is not None:
            return best_p[0], "weight"
        return None, wake

    def _account(self, c: str, phase: str, now: float) -> None:
        p = self._classes[c]
        t = self._tags[c]
        if p.reservation > 0 and phase == "reservation":
            # bounded burst of one: an idle class's clock resets near now
            t["r"] = max(t["r"], now - 1.0 / p.reservation) \
                + 1.0 / p.reservation
        if p.limit > 0:
            t["l"] = max(t["l"], now - 1.0 / p.limit) + 1.0 / p.limit
        if phase == "weight":
            # reservation-phase service must NOT also consume the
            # class's proportional share (the dmclock P-tag compensation)
            t["p"] = t["p"] + 1.0 / max(p.weight, 1e-9)

    def _run(self) -> None:
        while True:
            with self._cv:
                while True:
                    if self._stop:
                        return
                    now = self._clock()
                    klass, res = self._pick(now)
                    if klass is not None:
                        item = self._queues[klass].popleft()
                        self._account(klass, res, now)
                        self.served[klass] += 1
                        if self._perf is not None:
                            self._perf.inc(f"mclock_served_{klass}")
                            self._perf.inc(f"mclock_depth_{klass}", -1)
                            if self._stamps[klass]:
                                self._perf.hinc(
                                    f"mclock_qwait_us_{klass}",
                                    max(0.0, now - self._stamps[klass]
                                        .popleft()) * 1e6)
                        elif self._stamps[klass]:
                            self._stamps[klass].popleft()
                        break
                    timeout = None if res is None \
                        else max(0.001, res - now)
                    self._cv.wait(timeout=timeout)
            try:
                self._handler(klass, item)
            except Exception:  # noqa: BLE001 - worker must survive
                from ..utils.log import dout
                import traceback
                dout("osd", 0)("scheduler handler error: %s",
                               traceback.format_exc())


class ShardedScheduler:
    """N MClockScheduler shards keyed by placement group (the sharded
    OpWQ of src/osd/scheduler/: per-PG parallelism inside one OSD,
    per-shard dmclock QoS, per-object ordering preserved because a
    given key always lands on the same shard)."""

    def __init__(self, handler, classes: dict[str, ClassParams],
                 shards: int = 2, name: str = "mclock",
                 perf: PerfCounters | None = None):
        # every shard increments the SAME per-class counters: the
        # registry aggregates naturally, one schema per daemon
        self.shards = [MClockScheduler(handler, dict(classes),
                                       name=f"{name}-s{i}", perf=perf)
                       for i in range(max(1, shards))]

    def start(self) -> None:
        for s in self.shards:
            s.start()

    def shutdown(self) -> None:
        for s in self.shards:
            s.shutdown()

    def set_params(self, klass: str, p: ClassParams) -> None:
        for s in self.shards:
            s.set_params(klass, p)

    def enqueue(self, klass: str, item, key=None) -> None:
        shard = self.shards[hash(key) % len(self.shards)] \
            if key is not None else self.shards[0]
        shard.enqueue(klass, item)

    def queue_depth(self, klass: str | None = None) -> int:
        return sum(s.queue_depth(klass) for s in self.shards)

    def queue_depths(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for s in self.shards:
            for c, n in s.queue_depths().items():
                out[c] = out.get(c, 0) + n
        return out

    @property
    def served(self) -> dict:
        out: dict[str, int] = {}
        for s in self.shards:
            for c, n in s.served.items():
                out[c] = out.get(c, 0) + n
        return out

    @property
    def dropped(self) -> dict:
        out: dict[str, int] = {}
        for s in self.shards:
            for c, n in s.dropped.items():
                out[c] = out.get(c, 0) + n
        return out
