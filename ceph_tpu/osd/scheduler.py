"""mClock-style op scheduler: QoS between client, recovery, and scrub —
and, inside the client class, between named TENANTS.

The capability of the reference's OpScheduler + mClockScheduler
(src/osd/scheduler/OpScheduler.h:37, mClockScheduler.cc, vendored
dmclock): ops are tagged per class with reservation / weight / limit
(R, W, L) tags and served reservation-first, then by weighted
proportional share among classes under their limit — so background
recovery and scrub cannot starve client IO, yet keep a guaranteed
floor when the client is idle.

Tenant sub-queues (the dmclock server half, dmclock_server.h role):
client ops carrying a tenant tag land in dynamic per-tenant sub-queues
under the client class.  Tenant tags are assigned at ARRIVAL using the
client-shipped (delta, rho) pair (qos/dmclock.py):

    r_tag = max(prev_r, now - rho/R) + rho/R     (reservation clock)
    p_tag = prev_p + delta/W                     (proportional clock)

so a tenant also being served by OTHER OSDs advances its clocks here
without any cross-server coordination — the multi-server dmclock
correctness property.  Untagged client traffic rides the plain client
queue as the DEFAULT tenant stream.  Tenant profiles (qos/profiles.py)
arrive via the OSDMap; unknown tenants get the default profile.

Sharding (the reference's sharded OpWQ, osd_op_num_shards): ops hash
by PG to one of N independent scheduler shards, each with its own
dmclock state and dequeue worker — PGs execute in parallel inside one
OSD while everything touching one object stays ordered on its shard.
The messenger dispatch thread only classifies and enqueues.
"""

from __future__ import annotations

import collections
import re
import threading
import time
from dataclasses import dataclass

from ..qos.dmclock import (PHASE_NONE, PHASE_RESERVATION,
                           PHASE_WEIGHT, TAG_CAP)
from ..qos.profiles import DEFAULT_TENANT
from ..utils.perf import CounterType, PerfCounters

#: clamp on wire-carried dmclock tags (THE client-side cap, imported:
#: a hostile delta must not fast-forward a tenant's clocks to
#: infinity, and the two ends must agree on the bound)
_TAG_CAP = TAG_CAP

_TENANT_METRIC_RE = re.compile(r"[^a-z0-9_]")


def _tenant_metric(tenant: str) -> str:
    """Sanitized exporter-label stem for a tenant name."""
    return _TENANT_METRIC_RE.sub("_", tenant.lower())[:32] or "default"


#: thread-local service context: the dequeue worker publishes what it
#: is serving (class, phase, tenant) just before running the handler,
#: so the handler — which runs synchronously on the same thread — can
#: stamp the phase onto the op's reply (the dmclock feedback channel)
_service_tls = threading.local()


def current_service() -> tuple[str | None, int, str | None]:
    """(klass, phase, tenant) of the op the CURRENT thread is serving;
    (None, PHASE_NONE, None) off the scheduler workers (fifo mode)."""
    return (getattr(_service_tls, "klass", None),
            getattr(_service_tls, "phase", PHASE_NONE),
            getattr(_service_tls, "tenant", None))


@dataclass
class ClassParams:
    reservation: float  # guaranteed ops/sec (0 = none)
    weight: float       # proportional share when past reservation
    limit: float        # max ops/sec (0 = unlimited)


def register_qos_counters(perf: PerfCounters, classes) -> None:
    """Per-class QoS counters on a daemon registry — the exporter face
    of the scheduler's Python dicts (served/dropped were invisible to a
    live scrape before this).  Idempotent: shards share one registry,
    and re-adding would RESET live counters (PerfCounters.has)."""
    for c in classes:
        for name in (f"mclock_served_{c}", f"mclock_dropped_{c}"):
            if not perf.has(name):
                perf.add(name)
        if not perf.has(f"mclock_depth_{c}"):
            perf.add(f"mclock_depth_{c}", CounterType.U64)
        if not perf.has(f"mclock_qwait_us_{c}"):
            # enqueue->service wait: the quantity QoS actually moves —
            # prom_rules.py stands p50/p99 recording rules on these
            perf.add(f"mclock_qwait_us_{c}", CounterType.HISTOGRAM)


def register_tenant_counters(perf: PerfCounters, tenants) -> None:
    """Per-tenant served/depth/qwait series (``mclock_*_tenant_<t>``).
    The DEFAULT tenant registers at scheduler construction so the
    zeroed schema is stable across backends; named tenants register
    lazily, LRU-bounded by osd_qos_max_tenants — beyond the bound they
    fold into the default series (bounded exporter cardinality)."""
    for t in tenants:
        t = _tenant_metric(t)
        if not perf.has(f"mclock_served_tenant_{t}"):
            perf.add(f"mclock_served_tenant_{t}")
        if not perf.has(f"mclock_depth_tenant_{t}"):
            perf.add(f"mclock_depth_tenant_{t}", CounterType.U64)
        if not perf.has(f"mclock_qwait_us_tenant_{t}"):
            perf.add(f"mclock_qwait_us_tenant_{t}",
                     CounterType.HISTOGRAM)


class MClockScheduler:
    """Single-server dmclock over named classes (+ tenant sub-queues
    under the client class).

    Class tag rules (dmclock paper / mClockScheduler.cc):
      r_tag = max(now, prev_r + 1/R)    (reservation clock)
      p_tag = max(now, prev_p + 1/W)    (proportional virtual clock)
      l_tag = max(now, prev_l + 1/L)    (limit clock)
    Serve: earliest r_tag <= now first; otherwise smallest p_tag among
    classes whose l_tag <= now; otherwise wait for the nearest tag.
    When the client class wins, a second-level dmclock pick chooses
    among its tenant streams by the arrival-assigned tags.
    """

    #: per-class queue bound: a rate-limited class must not buffer an
    #: unbounded backlog of full message payloads (drops are the lossy
    #: messenger semantic; recovery retries via requery rounds)
    QUEUE_CAP = 512

    #: the client class (the only one with tenant sub-queues)
    CLIENT = "client"

    def __init__(self, handler, classes: dict[str, ClassParams],
                 name: str = "mclock", clock=time.monotonic,
                 perf: PerfCounters | None = None,
                 tenant_profiles: dict[str, ClassParams] | None = None,
                 max_tenants: int = 64):
        self._handler = handler
        self._classes = {}
        for c, p in classes.items():
            self._classes[c] = self._clamp(p)
        self._clock = clock
        self.dropped: dict[str, int] = {c: 0 for c in classes}
        self._queues: dict[str, collections.deque] = {
            c: collections.deque() for c in classes}
        # parallel enqueue stamps feeding the per-class wait histogram;
        # tests that append to _queues directly simply record no stamp
        self._stamps: dict[str, collections.deque] = {
            c: collections.deque() for c in classes}
        self._tags = {c: {"r": 0.0, "p": 0.0, "l": 0.0} for c in classes}
        # ---- tenant sub-queue state (client class only) ----
        self._tparams: dict[str, ClassParams] = {
            t: self._clamp(p)
            for t, p in (tenant_profiles or {}).items()}
        self._max_tenants = max(1, int(max_tenants))
        # tenant -> deque of (item, stamp, r_tag|None, p_tag)
        self._tqueues: dict[str, collections.deque] = {}
        self._ttags: dict[str, dict] = {}   # tenant -> {"r","p","l"}
        self._ttouch: dict[str, float] = {}  # tenant -> last enqueue
        self.tenant_served: dict[str, int] = {}
        self.tenant_dropped: dict[str, int] = {}
        self.tenant_evicted = 0   # LRU evictions (profile state dropped)
        self.tenant_folded = 0    # ops folded into the default stream
        # client-stream virtual time: the proportional round of the
        # most recent client serve.  A stream that registers (or goes
        # idle->busy) while NOTHING else is queued seeds its p clock
        # here — joining the current round — instead of starting at 0
        # and outranking every stream that has been paying share all
        # along (symmetrically: untagged history must not starve a
        # new tenant, and tenant history must not starve untagged)
        self._client_vtime = 0.0
        # metric names are registered at most max_tenants deep, EVER:
        # tenants past the bound account into the default series
        self._tenant_metrics: set[str] = set()
        # cached pick of the client sub-stream, computed by _pick under
        # the lock and consumed by _dequeue_locked in the same hold
        self._client_choice: tuple | None = None
        self._cv = threading.Condition()
        self._stop = False
        self.served: dict[str, int] = {c: 0 for c in classes}
        self._perf = perf
        if perf is not None:
            register_qos_counters(perf, classes)
            register_tenant_counters(perf, (DEFAULT_TENANT,))
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)

    @staticmethod
    def _clamp(p: ClassParams) -> ClassParams:
        if p.limit > 0 and p.reservation > p.limit:
            # limit is the hard upper bound: a reservation above it
            # would silently exceed the configured cap
            return ClassParams(p.limit, p.weight, p.limit)
        return p

    def set_params(self, klass: str, p: ClassParams) -> None:
        """Live QoS reconfiguration (the `config set osd_mclock_*` +
        reset path): swap one class's (R, W, L) under the lock; queued
        items keep their positions, tags re-pace from the next pick.
        A class this scheduler has not served yet AUTO-REGISTERS with
        the clamped params — `reset_mclock` against a daemon that
        never saw (say) scrub traffic must configure the class, not
        500 the admin socket with a KeyError."""
        with self._cv:
            if klass not in self._classes:
                self._queues[klass] = collections.deque()
                self._stamps[klass] = collections.deque()
                self._tags[klass] = {"r": 0.0, "p": 0.0, "l": 0.0}
                self.served.setdefault(klass, 0)
                self.dropped.setdefault(klass, 0)
                if self._perf is not None:
                    register_qos_counters(self._perf, (klass,))
            self._classes[klass] = self._clamp(p)
            self._cv.notify_all()

    def set_tenant_profiles(self,
                            profiles: dict[str, ClassParams]) -> None:
        """Swap the named tenant profile book (the OSDMap push): live
        tenant streams re-pace from their next arrival; tenants the new
        book no longer names fall back to the default profile."""
        with self._cv:
            self._tparams = {t: self._clamp(p)
                             for t, p in (profiles or {}).items()}
            self._cv.notify_all()

    def start(self) -> None:
        self._thread.start()

    def shutdown(self) -> None:
        with self._cv:
            self._stop = True
            # reconcile the depth gauges for items dying in the queues:
            # the registry is injected and may outlive this scheduler
            # (an embedding daemon shutting the scheduler down without
            # dying itself), so an unreconciled gauge would stay
            # inflated forever on later scrapes
            for c, q in self._queues.items():
                if q and self._perf is not None:
                    self._perf.inc(f"mclock_depth_{c}", -len(q))
                q.clear()
                self._stamps[c].clear()
            for t, q in self._tqueues.items():
                if q and self._perf is not None:
                    self._perf.inc(f"mclock_depth_{self.CLIENT}",
                                   -len(q))
                    self._perf.inc(
                        f"mclock_depth_tenant_{self._tenant_key(t)}",
                        -len(q))
                q.clear()
            self._cv.notify_all()
        if self._thread.ident is not None:  # never-started: no join
            self._thread.join(timeout=5)

    # ------------------------------------------------------ tenant plumbing
    def _tenant_key(self, tenant: str) -> str:
        """Metric stem this tenant's counters land on: its own name
        while the registered set is under the cardinality bound, the
        default series beyond it."""
        m = _tenant_metric(tenant)
        if m in self._tenant_metrics:
            return m
        if len(self._tenant_metrics) < self._max_tenants:
            self._tenant_metrics.add(m)
            if self._perf is not None:
                register_tenant_counters(self._perf, (m,))
            return m
        return _tenant_metric(DEFAULT_TENANT)

    def _tenant_params(self, tenant: str) -> ClassParams:
        p = self._tparams.get(tenant)
        if p is None:
            p = self._tparams.get(DEFAULT_TENANT)
        return p if p is not None else ClassParams(0.0, 1.0, 0.0)

    def _register_tenant_locked(self, tenant: str,
                                now: float) -> bool:
        """Admit a tenant stream, LRU-evicting an IDLE stream when at
        the osd_qos_max_tenants bound.  Returns False when no stream
        can be admitted (every existing one has queued work) — the
        caller folds the op into the default/untagged stream instead
        of growing state without bound."""
        if tenant in self._tqueues:
            return True
        if len(self._tqueues) >= self._max_tenants:
            idle = [t for t, q in self._tqueues.items() if not q]
            if not idle:
                return False
            victim = min(idle, key=lambda t: self._ttouch.get(t, 0.0))
            del self._tqueues[victim]
            self._ttags.pop(victim, None)
            self._ttouch.pop(victim, None)
            # fold the victim's tallies into the default key: these
            # dicts must stay bounded under tenant-name churn (the
            # wire-supplied name is never validated — a hostile client
            # rotating names must not grow per-shard state forever)
            for book in (self.tenant_served, self.tenant_dropped):
                n = book.pop(victim, 0)
                if n:
                    book[DEFAULT_TENANT] = \
                        book.get(DEFAULT_TENANT, 0) + n
            self.tenant_evicted += 1
        self._tqueues[tenant] = collections.deque()
        self._ttags.setdefault(tenant,
                               {"r": 0.0, "p": self._client_vtime,
                                "l": 0.0})
        self._ttouch[tenant] = now
        return True

    def _busy_tenant_p_floor(self) -> float | None:
        """min proportional tag among busy client sub-streams (idle ->
        busy catch-up base, same rule as the class level).  The
        untagged stream's floor is the DEFAULT tenant's sub-clock —
        the class-level p tag lives in a different clock domain
        (1/W_class per serve vs 1/W_tenant) and would set a floor
        orders of magnitude off."""
        floors = [q[0][3] for q in self._tqueues.values() if q]
        if self._queues[self.CLIENT]:
            t = self._ttags.setdefault(DEFAULT_TENANT,
                                       {"r": 0.0, "p": 0.0, "l": 0.0})
            floors.append(t["p"])
        return min(floors) if floors else None

    def _enqueue_tenant_locked(self, tenant: str, item,
                               tags, now: float,
                               trace_id=None) -> bool:
        """Queue one tenant-tagged client op with arrival-time dmclock
        tags.  Returns False when the op should ride the untagged
        stream instead (tenant table full of busy streams)."""
        if not self._register_tenant_locked(tenant, now):
            self.tenant_folded += 1
            return False
        q = self._tqueues[tenant]
        if len(q) >= self.QUEUE_CAP:
            self.dropped[self.CLIENT] += 1
            self.tenant_dropped[tenant] = \
                self.tenant_dropped.get(tenant, 0) + 1
            if self._perf is not None:
                self._perf.inc(f"mclock_dropped_{self.CLIENT}")
            return True  # consumed (dropped) — do not re-route
        p = self._tenant_params(tenant)
        t = self._ttags[tenant]
        delta = min(_TAG_CAP, max(1, int(tags[0]) if tags else 1))
        rho = min(_TAG_CAP, max(1, int(tags[1]) if tags else 1))
        if not q:
            # idle->busy: catch the proportional clock up to the busy
            # minimum — or the current round when nothing is queued —
            # so an idle tenant cannot burst unfairly
            floor = self._busy_tenant_p_floor()
            if floor is None:
                floor = self._client_vtime
            t["p"] = max(t["p"], floor)
        r_tag = None
        if p.reservation > 0:
            # rho responses were served by reservation ELSEWHERE since
            # this tenant's last op here: advance the clock by rho/R,
            # bounded-burst floored at now - 1/R like the class level
            r_tag = max(t["r"], now - 1.0 / p.reservation) \
                + rho / p.reservation
            t["r"] = r_tag
        # arrival-time proportional tag: advanced here for EVERY op,
        # with the increment REMEMBERED so a reservation-phase serve
        # can refund it (dmclock's P-tag compensation: service paid
        # for by the reservation clock must not also consume the
        # tenant's proportional share — without the refund a tenant
        # whose burst rode its reservation starts every later
        # weight-phase round behind tenants that never reserved)
        p_cost = delta / max(p.weight, 1e-9)
        p_tag = t["p"] + p_cost
        t["p"] = p_tag
        q.append((item, now, r_tag, p_tag, p_cost, trace_id))
        self._ttouch[tenant] = now
        if self._perf is not None:
            self._perf.inc(f"mclock_depth_{self.CLIENT}")
            self._perf.inc(
                f"mclock_depth_tenant_{self._tenant_key(tenant)}")
        self._cv.notify()
        return True

    def _client_ready(self, now: float):
        """Second-level dmclock pick among the client sub-streams.
        Returns (choice, wake): choice is ("tenant", name, phase) or
        ("untagged", None, None) when something is serveable now, else
        None with the earliest wake instant among blocked streams."""
        best_r = None    # (r_tag, tenant)
        best_p = None    # (p_tag, tenant | None)
        wake = None
        if self._queues[self.CLIENT]:
            # the untagged stream = the DEFAULT tenant: service-time
            # paced from the default profile's tags
            p = self._tenant_params(DEFAULT_TENANT)
            t = self._ttags.setdefault(DEFAULT_TENANT,
                                       {"r": 0.0, "p": 0.0, "l": 0.0})
            if p.limit > 0 and t["l"] > now:
                wake = t["l"] if wake is None else min(wake, t["l"])
            else:
                if p.reservation > 0 and t["r"] <= now:
                    best_r = (t["r"], None)
                elif p.reservation > 0 and t["r"] > now:
                    wake = t["r"] if wake is None \
                        else min(wake, t["r"])
                if best_p is None or t["p"] < best_p[0]:
                    best_p = (t["p"], None)
        for tenant, q in self._tqueues.items():
            if not q:
                continue
            p = self._tenant_params(tenant)
            t = self._ttags[tenant]
            if p.limit > 0 and t["l"] > now:
                wake = t["l"] if wake is None else min(wake, t["l"])
                continue
            _item, _stamp, r_tag, p_tag, _pc, _tid = q[0]
            if r_tag is not None:
                if r_tag <= now and (best_r is None
                                     or r_tag < best_r[0]):
                    best_r = (r_tag, tenant)
                elif r_tag > now:
                    wake = r_tag if wake is None else min(wake, r_tag)
            if best_p is None or p_tag < best_p[0]:
                best_p = (p_tag, tenant)
        if best_r is not None:
            who = best_r[1]
            if who is None:
                return ("untagged", None, PHASE_RESERVATION), None
            return ("tenant", who, PHASE_RESERVATION), None
        if best_p is not None:
            who = best_p[1]
            if who is None:
                return ("untagged", None, PHASE_WEIGHT), None
            return ("tenant", who, PHASE_WEIGHT), None
        return None, wake

    def _class_catchup_locked(self, klass: str) -> None:
        """Idle->busy: catch the class's proportional clock up to the
        busy minimum so an idle class cannot burst unfairly.  Depth
        counts tenant sub-queues too — in BOTH directions: a client
        class whose work all lives in tenant streams is busy (its p
        must be in everyone else's floor) and is NOT idle (its own p
        must not be yanked up).  Runs for EVERY enqueue path of an
        idle class, tenant-tagged included."""
        if self._cls_depth_locked(klass) != 0:
            return
        busy = [self._tags[c]["p"] for c in self._queues
                if c != klass and self._cls_depth_locked(c)]
        if busy:
            t = self._tags[klass]
            t["p"] = max(t["p"], min(busy))

    # ---------------------------------------------------------------- API
    def enqueue(self, klass: str, item, tenant: str | None = None,
                tags: tuple | None = None, force: bool = False,
                trace_id=None) -> None:
        """``force`` bypasses the lossy QUEUE_CAP drop: completion
        continuations (store commit acks/replies) have no retry path —
        dropping one would wedge its object lock forever — and their
        count is bounded by in-flight ops, not by hostile senders.

        ``trace_id`` rides the queue-wait stamp when the op belongs to
        a SAMPLED trace, landing as the bucket exemplar on the
        ``mclock_qwait_us_*`` histogram at dequeue."""
        with self._cv:
            now = self._clock()
            self._class_catchup_locked(klass)
            if klass == self.CLIENT and tenant \
                    and tenant != DEFAULT_TENANT:
                if self._enqueue_tenant_locked(tenant, item, tags,
                                               now,
                                               trace_id=trace_id):
                    return
                # fold-through: ride the untagged stream below
            q = self._queues[klass]
            if len(q) >= self.QUEUE_CAP and not force:
                self.dropped[klass] += 1
                if self._perf is not None:
                    self._perf.inc(f"mclock_dropped_{klass}")
                return  # lossy backpressure; senders retry/requery
            if not q and klass == self.CLIENT:
                # the untagged stream's own sub-clock catches up to
                # the busy tenant floor (or the current round when
                # nothing is queued) on idle->busy — a burst of
                # untagged ops must not outrank tenants that have
                # been paying proportional share all along
                floors = [qq[0][3]
                          for qq in self._tqueues.values() if qq]
                floor = min(floors) if floors \
                    else self._client_vtime
                td = self._ttags.setdefault(
                    DEFAULT_TENANT, {"r": 0.0, "p": 0.0, "l": 0.0})
                td["p"] = max(td["p"], floor)
            q.append(item)
            self._stamps[klass].append((now, trace_id))
            if self._perf is not None:
                self._perf.inc(f"mclock_depth_{klass}")
            self._cv.notify()

    def _cls_depth_locked(self, klass: str) -> int:
        n = len(self._queues[klass])
        if klass == self.CLIENT:
            n += sum(len(q) for q in self._tqueues.values())
        return n

    def queue_depth(self, klass: str | None = None) -> int:
        with self._cv:
            if klass is not None:
                return self._cls_depth_locked(klass)
            return sum(self._cls_depth_locked(c) for c in self._queues)

    def queue_depths(self) -> dict[str, int]:
        with self._cv:
            return {c: self._cls_depth_locked(c) for c in self._queues}

    def tenant_depths(self) -> dict[str, int]:
        with self._cv:
            out = {t: len(q) for t, q in self._tqueues.items()}
            if self._queues.get(self.CLIENT):
                out[DEFAULT_TENANT] = out.get(DEFAULT_TENANT, 0) \
                    + len(self._queues[self.CLIENT])
            return out

    def tenant_served_snapshot(self) -> dict[str, int]:
        """Locked copy for monitor paths: the worker inserts first-
        seen tenant keys concurrently, and iterating the live dict
        from a sampler/admin thread can blow up mid-walk."""
        with self._cv:
            return dict(self.tenant_served)

    # ------------------------------------------------------------ worker
    def _pick(self, now: float):
        """(klass, phase) to serve now, or (None, wake_at).

        Tags hold NEXT-ELIGIBLE instants: "r" the next reservation
        service, "l" the next limit-allowed service; "p" is a virtual
        round number compared only among busy classes.  For the client
        class the second-level tenant pick must also be serveable —
        its choice is cached for _dequeue_locked (same lock hold)."""
        self._client_choice = None
        client_wake = None
        client_ok = True
        if self.CLIENT in self._queues \
                and self._cls_depth_locked(self.CLIENT):
            # consult the sub-pick when tenant streams hold work, OR
            # when a committed DEFAULT profile carries a reservation/
            # limit (the untagged stream's pacing lives in the sub-
            # pick — it must not depend on unrelated tenants being
            # busy); otherwise the plain path stays byte-identical to
            # the pre-tenant logic
            dp = self._tparams.get(DEFAULT_TENANT)
            if any(q for q in self._tqueues.values()) \
                    or (dp is not None
                        and (dp.reservation > 0 or dp.limit > 0)):
                choice, client_wake = self._client_ready(now)
                self._client_choice = choice
                client_ok = choice is not None
        best_r = None
        wake = client_wake
        for c, q in self._queues.items():
            if not self._cls_depth_locked(c):
                continue
            if c == self.CLIENT and not client_ok:
                continue
            p = self._classes[c]
            if p.reservation > 0:
                r_next = self._tags[c]["r"]
                if r_next <= now and (best_r is None
                                      or r_next < best_r[1]):
                    best_r = (c, r_next)
                elif r_next > now:
                    wake = r_next if wake is None else min(wake, r_next)
        if best_r is not None:
            return best_r[0], "reservation"
        best_p = None
        for c, q in self._queues.items():
            if not self._cls_depth_locked(c):
                continue
            if c == self.CLIENT and not client_ok:
                continue
            p = self._classes[c]
            if p.limit > 0 and self._tags[c]["l"] > now:
                l_next = self._tags[c]["l"]
                wake = l_next if wake is None else min(wake, l_next)
                continue
            p_tag = self._tags[c]["p"]
            if best_p is None or p_tag < best_p[1]:
                best_p = (c, p_tag)
        if best_p is not None:
            return best_p[0], "weight"
        return None, wake

    def _account(self, c: str, phase: str, now: float) -> None:
        p = self._classes[c]
        t = self._tags[c]
        if p.reservation > 0 and phase == "reservation":
            # bounded burst of one: an idle class's clock resets near now
            t["r"] = max(t["r"], now - 1.0 / p.reservation) \
                + 1.0 / p.reservation
        if p.limit > 0:
            t["l"] = max(t["l"], now - 1.0 / p.limit) + 1.0 / p.limit
        if phase == "weight":
            # reservation-phase service must NOT also consume the
            # class's proportional share (the dmclock P-tag compensation)
            t["p"] = t["p"] + 1.0 / max(p.weight, 1e-9)

    def _account_tenant(self, tenant: str, phase_code: int,
                        now: float) -> None:
        """Service-time accounting for a sub-stream: the limit clock
        paces here (arrival tags already advanced r/p at enqueue for
        named tenants; the untagged/default stream paces all three)."""
        p = self._tenant_params(tenant)
        t = self._ttags.setdefault(tenant,
                                   {"r": 0.0, "p": 0.0, "l": 0.0})
        if p.limit > 0:
            t["l"] = max(t["l"], now - 1.0 / p.limit) + 1.0 / p.limit
        if tenant == DEFAULT_TENANT:
            # untagged items carry no arrival tags: pace like a class
            if p.reservation > 0 and phase_code == PHASE_RESERVATION:
                t["r"] = max(t["r"], now - 1.0 / p.reservation) \
                    + 1.0 / p.reservation
            if phase_code == PHASE_WEIGHT:
                t["p"] = t["p"] + 1.0 / max(p.weight, 1e-9)

    def _book_service_locked(self, tenant: str, stamp: float | None,
                             now: float, exemplar=None) -> None:
        self.tenant_served[tenant] = \
            self.tenant_served.get(tenant, 0) + 1
        if self._perf is not None:
            key = self._tenant_key(tenant)
            self._perf.inc(f"mclock_served_tenant_{key}")
            if tenant != DEFAULT_TENANT:
                self._perf.inc(f"mclock_depth_tenant_{key}", -1)
            if stamp is not None:
                self._perf.hinc(f"mclock_qwait_us_tenant_{key}",
                                max(0.0, now - stamp) * 1e6,
                                exemplar=exemplar)

    def _dequeue_locked(self, klass: str, res: str, now: float):
        """Pop + account the op the class-level pick chose.  Returns
        (item, phase_code, tenant) — phase is the TENANT-level phase
        for client ops (what the dmclock client's rho consumes)."""
        phase_code = PHASE_RESERVATION if res == "reservation" \
            else PHASE_WEIGHT
        tenant = None
        stamp = None
        if klass == self.CLIENT and self._client_choice is not None:
            kind, who, sub_phase = self._client_choice
            if kind == "tenant":
                q = self._tqueues[who]
                item, stamp, _r, _p, _pc, tid = q.popleft()
                tenant = who
                phase_code = sub_phase
                if sub_phase == PHASE_RESERVATION and _pc > 0.0:
                    # P-tag compensation (the dmclock rule the class
                    # level already applies in _account): this op was
                    # served by the RESERVATION clock, so refund the
                    # proportional advance its arrival charged — from
                    # the tenant's stored tag AND from every op still
                    # queued behind it (their tags were computed on
                    # top of the refunded increment).  The round clock
                    # (_client_vtime) does not advance either: the op
                    # consumed no proportional share.
                    t = self._ttags[who]
                    t["p"] -= _pc
                    if q:
                        self._tqueues[who] = collections.deque(
                            (it, st, r, pt - _pc, pc, ti)
                            for it, st, r, pt, pc, ti in q)
                else:
                    self._client_vtime = max(self._client_vtime, _p)
                self._account(klass, res, now)
                self._account_tenant(who, sub_phase, now)
                self.served[klass] += 1
                if self._perf is not None:
                    self._perf.inc(f"mclock_served_{klass}")
                    self._perf.inc(f"mclock_depth_{klass}", -1)
                    if stamp is not None:
                        self._perf.hinc(f"mclock_qwait_us_{klass}",
                                        max(0.0, now - stamp) * 1e6,
                                        exemplar=tid)
                self._book_service_locked(who, stamp, now,
                                          exemplar=tid)
                return item, phase_code, tenant
            # untagged pick: fall through to the plain pop below,
            # using the sub-pick's phase for the default stream
            phase_code = sub_phase
            tenant = DEFAULT_TENANT
        item = self._queues[klass].popleft()
        self._account(klass, res, now)
        if klass == self.CLIENT:
            self._account_tenant(DEFAULT_TENANT, phase_code, now)
            self._client_vtime = max(
                self._client_vtime,
                self._ttags[DEFAULT_TENANT]["p"])
        self.served[klass] += 1
        tid = None
        if self._perf is not None:
            self._perf.inc(f"mclock_served_{klass}")
            self._perf.inc(f"mclock_depth_{klass}", -1)
            if self._stamps[klass]:
                stamp, tid = self._stamps[klass].popleft()
                self._perf.hinc(f"mclock_qwait_us_{klass}",
                                max(0.0, now - stamp) * 1e6,
                                exemplar=tid)
        elif self._stamps[klass]:
            stamp, tid = self._stamps[klass].popleft()
        if klass == self.CLIENT:
            self._book_service_locked(DEFAULT_TENANT, stamp, now,
                                      exemplar=tid)
            tenant = DEFAULT_TENANT
        return item, phase_code, tenant

    def _run(self) -> None:
        while True:
            with self._cv:
                while True:
                    if self._stop:
                        return
                    now = self._clock()
                    klass, res = self._pick(now)
                    if klass is not None:
                        item, phase_code, tenant = \
                            self._dequeue_locked(klass, res, now)
                        break
                    timeout = None if res is None \
                        else max(0.001, res - now)
                    self._cv.wait(timeout=timeout)
            _service_tls.klass = klass
            _service_tls.phase = phase_code
            _service_tls.tenant = tenant
            try:
                self._handler(klass, item)
            except Exception:  # noqa: BLE001 - worker must survive
                from ..utils.log import dout
                import traceback
                dout("osd", 0)("scheduler handler error: %s",
                               traceback.format_exc())
            finally:
                _service_tls.klass = None
                _service_tls.phase = PHASE_NONE
                _service_tls.tenant = None


class ShardedScheduler:
    """N MClockScheduler shards keyed by placement group (the sharded
    OpWQ of src/osd/scheduler/: per-PG parallelism inside one OSD,
    per-shard dmclock QoS, per-object ordering preserved because a
    given key always lands on the same shard)."""

    def __init__(self, handler, classes: dict[str, ClassParams],
                 shards: int = 2, name: str = "mclock",
                 perf: PerfCounters | None = None,
                 tenant_profiles: dict[str, ClassParams] | None = None,
                 max_tenants: int = 64):
        # every shard increments the SAME per-class counters: the
        # registry aggregates naturally, one schema per daemon
        n = max(1, shards)
        self.shards = [MClockScheduler(
            handler, dict(classes), name=f"{name}-s{i}", perf=perf,
            tenant_profiles=self._split_profiles(tenant_profiles, n),
            max_tenants=max_tenants)
            for i in range(n)]

    @staticmethod
    def _split_profiles(profiles, n: int):
        """A committed tenant reservation/limit is a PER-OSD figure:
        each of the N independent shard schedulers enforces 1/N of it,
        so the shards' floors SUM to the committed number instead of
        multiplying it (class-level osd_mclock_* knobs are documented
        per-shard; tenant profiles are operator-facing and are not).
        Weights are ratios — unscaled."""
        if not profiles or n <= 1:
            return profiles
        return {t: ClassParams(p.reservation / n, p.weight,
                               p.limit / n)
                for t, p in profiles.items()}

    def start(self) -> None:
        for s in self.shards:
            s.start()

    def shutdown(self) -> None:
        for s in self.shards:
            s.shutdown()

    def set_params(self, klass: str, p: ClassParams) -> None:
        for s in self.shards:
            s.set_params(klass, p)

    def set_tenant_profiles(self,
                            profiles: dict[str, ClassParams]) -> None:
        split = self._split_profiles(profiles, len(self.shards))
        for s in self.shards:
            s.set_tenant_profiles(split)

    def enqueue(self, klass: str, item, key=None,
                tenant: str | None = None,
                tags: tuple | None = None, force: bool = False,
                trace_id=None) -> None:
        shard = self.shards[hash(key) % len(self.shards)] \
            if key is not None else self.shards[0]
        shard.enqueue(klass, item, tenant=tenant, tags=tags,
                      force=force, trace_id=trace_id)

    def queue_depth(self, klass: str | None = None) -> int:
        return sum(s.queue_depth(klass) for s in self.shards)

    def queue_depths(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for s in self.shards:
            for c, n in s.queue_depths().items():
                out[c] = out.get(c, 0) + n
        return out

    def tenant_depths(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for s in self.shards:
            for t, n in s.tenant_depths().items():
                out[t] = out.get(t, 0) + n
        return out

    @property
    def served(self) -> dict:
        out: dict[str, int] = {}
        for s in self.shards:
            for c, n in s.served.items():
                out[c] = out.get(c, 0) + n
        return out

    @property
    def tenant_served(self) -> dict:
        out: dict[str, int] = {}
        for s in self.shards:
            for t, n in s.tenant_served_snapshot().items():
                out[t] = out.get(t, 0) + n
        return out

    @property
    def dropped(self) -> dict:
        out: dict[str, int] = {}
        for s in self.shards:
            for c, n in s.dropped.items():
                out[c] = out.get(c, 0) + n
        return out
