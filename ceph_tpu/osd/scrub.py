"""Scrub: consistency auditing of PG replicas/shards, with repair.

The capability of the reference's scrubber (src/osd/scrubber/ — SURVEY.md
§2.5: shallow scrub compares object metadata across replicas, deep scrub
compares full-data digests via scrub_backend.cc; EC shards check local
checksums; `pg repair` rewrites bad copies from the authoritative one) and
of the EC consistency checker tool
(src/erasure-code/consistency/ceph_ec_consistency_checker.cc: re-encode
parity from data shards and compare).

Mixin for OSDDaemon: the primary fans MScrubShard to members, each returns
a scrub map (size/version per object; + crc32c digest when deep), and the
primary compares:
- replicated: every copy must match the authoritative (max-version) one;
- EC: each shard's stored digest attr must match its recomputed data (the
  per-shard local check), and with deep+repair the stripe is re-encoded
  from data shards and compared against stored parity (the consistency-
  checker pass), rebuilding any bad shard.
"""

from __future__ import annotations

import time
import zlib
from dataclasses import dataclass, field

import numpy as np

from ..ec.verify import verifier
from ..msg.messages import (MPGPull, MPGPush, MScrubMap, MScrubRequest,
                            MScrubResult, MScrubShard, PgId)
from ..ops import native
from ..ops.checksum import crc32c_extend_zeros, crc32c_ref
from ..utils.log import dout
from .objectstore import (CollectionId, NoSuchCollection, NoSuchObject,
                          ObjectId, Transaction)
from .snaps import to_oid, vname_of


def _host_crc32c(data: bytes) -> int:
    """Backend-independent host CRC for mismatch confirmation."""
    try:
        return native.crc32c(data)
    except Exception:  # noqa: BLE001 - ctypes lib unavailable
        return crc32c_ref(data)


@dataclass
class _PendingScrub:
    client: str
    client_tid: int
    pgid: PgId
    deep: bool
    repair: bool
    waiting_for: set = field(default_factory=set)
    maps: dict = field(default_factory=dict)  # osd -> scrub map
    span: object = None  # head-sampled root span (finished at compare)


class ScrubMixin:
    """Scrub handlers; mixed into OSDDaemon."""

    def _scrub_map_local(self, pgid: PgId, deep: bool) -> dict:
        cid = CollectionId(pgid.pool, pgid.seed)
        out = {}
        try:
            oids = self.store.list_objects(cid)
        except Exception:  # noqa: BLE001 - no collection yet
            return out
        for oid in oids:
            if oid.shard <= -2:
                continue  # PG metadata (pglog/snapmapper), not user data
            key = (vname_of(oid), oid.shard)  # clones scrub as vnames
            try:
                attrs = self.store.getattrs(cid, oid)
                entry = {"size": self.store.stat(cid, oid)["size"],
                         "version": int(attrs.get("v", 0))}
                if deep:
                    data = self.store.read(cid, oid).to_bytes()
                    entry["digest"] = native.crc32c(data)
                    entry["stored_digest"] = attrs.get("d")
                out[key] = entry
            except Exception as e:  # noqa: BLE001 - count unreadable objects
                out[key] = {"error": repr(e)}
        return out

    def _handle_scrub_request(self, conn, m: MScrubRequest) -> None:
        up = self.osdmap.pg_to_up_osds(m.pgid.pool, m.pgid.seed)
        if self._primary_of(up) != self.osd_id:
            conn.send(MScrubResult(m.tid, m.pgid, -116, []))
            return
        tid = next(self._tids)
        members = {u for u in up if u is not None}
        ps = _PendingScrub(m.client, m.tid, m.pgid, m.deep, m.repair,
                           waiting_for=set(members))
        # scrubs are ROOT ops for the head sampler (trace_sample_rate):
        # the span covers request -> shard maps -> compare/repair
        ps.span = self.tracer.sample_root(
            "scrub", pg=self._pgstr(m.pgid), deep=m.deep)
        self._pending_scrubs[tid] = ps
        for osd in members:
            if osd == self.osd_id:
                self._on_scrub_map(tid, self.osd_id,
                                   self._scrub_map_local(m.pgid, m.deep))
            else:
                self.messenger.send_message(
                    f"osd.{osd}", MScrubShard(tid, m.pgid, m.deep))

    def _handle_scrub_shard(self, conn, m: MScrubShard) -> None:
        conn.send(MScrubMap(m.tid, m.pgid, self.osd_id,
                            self._scrub_map_local(m.pgid, m.deep)))

    def _handle_scrub_map(self, conn, m: MScrubMap) -> None:
        self._on_scrub_map(m.tid, m.from_osd, m.objects)

    def _on_scrub_map(self, tid: int, from_osd: int, objects: dict) -> None:
        ps = self._pending_scrubs.get(tid)
        if ps is None:
            return
        ps.maps[from_osd] = objects
        ps.waiting_for.discard(from_osd)
        if ps.waiting_for:
            return
        del self._pending_scrubs[tid]
        self._finish_scrub(ps)

    # ------------------------------------------------------------- compare
    def _finish_scrub(self, ps: _PendingScrub) -> None:
        pool = self.osdmap.pools[ps.pgid.pool]
        issues: list[dict] = []
        for osd, omap_ in ps.maps.items():
            for key, entry in omap_.items():
                if "error" in entry:
                    issues.append({"osd": osd, "object": key[0],
                                   "shard": key[1], "kind": "read_error",
                                   "detail": entry["error"]})
                elif ps.deep and entry.get("stored_digest") is None:
                    # a non-empty object with no stored digest is NOT
                    # clean — it is unverifiable, which deep scrub must
                    # surface (every write path stamps "d"; an absent
                    # one means an interrupted transaction or attr rot)
                    if entry.get("size", 0) > 0:
                        issues.append({"osd": osd, "object": key[0],
                                       "shard": key[1],
                                       "kind": "digest_missing"})
                elif ps.deep and entry["digest"] != entry["stored_digest"]:
                    issues.append({"osd": osd, "object": key[0],
                                   "shard": key[1],
                                   "kind": "digest_mismatch"})
        if pool.kind == "ec":
            issues += self._scrub_compare_ec(ps)
        else:
            issues += self._scrub_compare_replicated(ps)
        repaired = 0
        if ps.repair and issues:
            repaired = self._scrub_repair(ps, issues)
        self.perf.inc("scrubs")
        if ps.span is not None:
            ps.span.tag("errors", len(issues)).tag("repaired", repaired)
            ps.span.finish()
        self.events.emit(
            "scrub",
            f"pg {self._pgstr(ps.pgid)} "
            f"{'deep-' if ps.deep else ''}scrub done"
            + (f": {len(issues)} inconsistencies" if issues else ""),
            severity="warn" if issues else "info",
            pg=self._pgstr(ps.pgid), deep=ps.deep,
            errors=len(issues), repaired=repaired)
        if issues:
            self.perf.inc("scrub_errors", len(issues))
            dout("osd", 1)("%s: scrub %s found %d inconsistencies",
                           self.name, ps.pgid, len(issues))
            if not ps.repair and any(
                    i["kind"] in ("missing_shard", "stale_version",
                                  "missing_copy")
                    for i in issues):
                # close the detect->repair->converge loop: a scrub that
                # SEES recoverable damage re-arms recovery even without
                # the explicit repair verb — a rebuild lost to a racing
                # map change or swept read must not leave a permanent
                # hole that only an operator command would fix (the
                # round-3 thrash fixed point: 4/5 shards healthy,
                # recovery idle, nothing ever retried)
                self._requery_pg(ps.pgid, force_full=True)
        self.messenger.send_message(
            ps.client, MScrubResult(ps.client_tid, ps.pgid, 0, issues,
                                    repaired))

    def _scrub_compare_replicated(self, ps: _PendingScrub) -> list[dict]:
        issues = []
        names: dict[str, dict[int, dict]] = {}
        for osd, omap_ in ps.maps.items():
            for (name, _shard), entry in omap_.items():
                if "error" not in entry:
                    names.setdefault(name, {})[osd] = entry
        for name, per_osd in names.items():
            # authority: max version; then majority digest
            auth_v = max(e["version"] for e in per_osd.values())
            auth_size = max((e["size"] for e in per_osd.values()
                             if e["version"] == auth_v), default=0)
            for osd, e in per_osd.items():
                if e["version"] != auth_v:
                    issues.append({"osd": osd, "object": name, "shard": -1,
                                   "kind": "stale_version"})
                elif e["size"] != auth_size:
                    # same version, truncated copy (lost tail)
                    issues.append({"osd": osd, "object": name, "shard": -1,
                                   "kind": "size_mismatch"})
            if ps.deep:
                digests = [e["digest"] for e in per_osd.values()
                           if e["version"] == auth_v]
                if len(set(digests)) > 1:
                    issues.append({"osd": None, "object": name, "shard": -1,
                                   "kind": "replica_digest_mismatch"})
            missing = set(ps.maps) - set(per_osd)
            for osd in missing:
                issues.append({"osd": osd, "object": name, "shard": -1,
                               "kind": "missing_copy"})
        return issues

    def _scrub_compare_ec(self, ps: _PendingScrub) -> list[dict]:
        """Cross-shard EC comparison: every up shard member must hold an
        entry for every object at the authoritative version (a missing or
        stale shard is a scrub finding, not just a recovery condition)."""
        issues = []
        up = self.osdmap.pg_to_up_osds(ps.pgid.pool, ps.pgid.seed)
        shard_owner = {shard: osd for shard, osd in enumerate(up)
                       if osd is not None and osd in ps.maps}
        names: dict[str, int] = {}
        for omap_ in ps.maps.values():
            for (name, _shard), entry in omap_.items():
                if "error" not in entry:
                    names[name] = max(names.get(name, 0), entry["version"])
        for name, auth_v in names.items():
            for shard, osd in shard_owner.items():
                entry = ps.maps[osd].get((name, shard))
                if entry is None or "error" in entry:
                    issues.append({"osd": osd, "object": name,
                                   "shard": shard, "kind": "missing_shard"})
                elif entry["version"] != auth_v:
                    issues.append({"osd": osd, "object": name,
                                   "shard": shard, "kind": "stale_version"})
        return issues

    # -------------------------------------------------------------- repair
    def _scrub_repair(self, ps: _PendingScrub, issues: list[dict]) -> int:
        """Repair by re-running recovery against the scrub findings:
        replicated bad/stale/missing copies get pushed from the
        authoritative copy; EC bad shards are rebuilt from survivors."""
        pool = self.osdmap.pools[ps.pgid.pool]
        repaired = 0
        if pool.kind == "ec":
            for issue in issues:
                if issue["kind"] in ("digest_mismatch", "digest_missing",
                                     "read_error", "missing_shard",
                                     "stale_version"):
                    # version: the object's authoritative version from the
                    # scrub maps, NOT the pg-wide counter
                    name = issue["object"]
                    v = max((e["version"] for om in ps.maps.values()
                             for (n, _s), e in om.items()
                             if n == name and "error" not in e), default=0)
                    self._rebuild_shard(ps.pgid, name, issue["shard"],
                                        issue["osd"], version=v, force=True)
                    repaired += 1
            return repaired
        cid = CollectionId(ps.pgid.pool, ps.pgid.seed)
        # which copies does scrub consider bad, per object?
        bad: dict[str, set[int]] = {}
        for issue in issues:
            if issue["osd"] is not None:
                bad.setdefault(issue["object"], set()).add(issue["osd"])
        for issue in issues:
            name = issue["object"]
            target = issue["osd"]
            if target is None:
                continue
            if self.osd_id in bad.get(name, ()):
                # my own copy is flagged: pull from a good peer instead of
                # propagating my (possibly corrupt) bytes
                if target == self.osd_id:
                    good = [o for o, om in ps.maps.items()
                            if o not in bad.get(name, ())
                            and (name, -1) in om]
                    if good:
                        self.messenger.send_message(
                            f"osd.{good[0]}",
                            MPGPull(ps.pgid, [name], force=True))
                        repaired += 1
                continue
            obj = to_oid(name)
            if target == self.osd_id or not self.store.exists(cid, obj):
                continue
            data, attrs = self._read_obj_raw(cid, obj)
            v = int(attrs.get("v", 0))
            omap = self.store.omap_get(cid, obj)
            self.messenger.send_message(
                f"osd.{target}",
                MPGPush(ps.pgid, -1,
                        {name: (v, data, None, omap,
                                self._push_attrs(attrs))},
                        force=True))
            repaired += 1
        return repaired


    # ------------------------------------------------ background deep scrub
    #
    # Continuous folded deep scrub (the reference's osd_scrub_min/max_
    # interval scheduler, src/osd/scrubber/osd_scrub_sched.cc, folded
    # through the PR's batching seam): each OSD audits ITS OWN shard
    # bytes per hosted PG — chunked object ranges, a name cursor
    # persisted in the PG's scrub meta object's omap (kill/revive
    # resumes where it stopped), chunks executing on the PG's shard
    # thread under the scrub mclock class (serialized with client ops
    # on the PG: no torn reads; paced by the scrub reservation).
    #
    # Verification is FOLDED: a chunk's objects are grouped into pow2
    # length buckets, each object's STORED bytes zero-padded to the
    # bucket and stacked into one (n, B) launch through
    # ECBatcher.verify — one fused device CRC sweep for many objects —
    # while the EXPECTED padded digest derives host-side from the
    # stored digest via the CRC32C zero-extension operator
    # (crc32c_extend_zeros), so no per-object device work remains.  A
    # folded mismatch is only a CANDIDATE: the object is re-checked
    # with a host CRC before anything is counted or repaired (zero
    # false mismatches by construction).

    SCRUB_META = "scrub_cursor"  # per-PG meta object (shard -2)

    def _scrub_meta_oid(self) -> ObjectId:
        return ObjectId(self.SCRUB_META, shard=-2)

    def _scrub_cursor_load(self, cid: CollectionId) -> tuple | None:
        try:
            raw = self.store.omap_get(
                cid, self._scrub_meta_oid()).get("cursor")
        except (NoSuchObject, NoSuchCollection):
            return None
        if not raw:
            return None
        name, _, shard = bytes(raw).decode().rpartition("\x00")
        return (name, int(shard))

    def _scrub_cursor_store(self, cid: CollectionId,
                            cursor: tuple | None) -> None:
        obj = self._scrub_meta_oid()
        tx = Transaction()
        if not self.store.exists(cid, obj):
            tx.touch(cid, obj)
        if cursor is None:
            tx.omap_rmkeys(cid, obj, ["cursor"])
        else:
            tx.omap_setkeys(cid, obj, {
                "cursor": f"{cursor[0]}\x00{cursor[1]}".encode()})
        self.store.queue_transaction(tx)

    def _scrub_tick(self, now: float) -> None:
        """Heartbeat hook: arm due PGs.  One cycle in flight per PG;
        chunks self-requeue through the scheduler until the cursor
        wraps."""
        if not self.cfg["osd_scrub_auto"] or self.osdmap is None:
            return
        mn = float(self.cfg["osd_scrub_min_interval"])
        mx = max(float(self.cfg["osd_scrub_max_interval"]), mn)
        for pool_id, seed, _up in self._pools_pgs_for_me():
            key = (pool_id, seed)
            st = self._scrub_auto.get(key)
            if st is None:
                # deterministic per-PG stagger spreads a cold fleet's
                # first cycles across [min, max); a PERSISTED cursor
                # means a cycle died mid-flight (OSD restart) — resume
                # promptly instead of waiting a whole interval
                frac = (zlib.crc32(f"{self.osd_id}/{pool_id}/{seed}"
                                   .encode()) & 0xFFFF) / 0x10000
                resume = self._scrub_cursor_load(
                    CollectionId(pool_id, seed)) is not None
                st = {"due": now if resume
                      else now + mn + frac * (mx - mn),
                      "running": False, "objects": 0, "bytes": 0,
                      "mismatches": 0, "started": 0.0, "total": 0}
                self._scrub_auto[key] = st
            if st["running"] or now < st["due"]:
                continue
            st.update(running=True, objects=0, bytes=0, mismatches=0,
                      started=now, total=0)
            pgid = PgId(pool_id, seed)
            self.events.emit(
                "scrub", f"pg {self._pgstr(pgid)} auto deep-scrub start",
                pg=self._pgstr(pgid), event="scrub_start",
                start_ts=st["started"], done=0, total=0)
            self._scrub_auto_enqueue(pgid)

    def _scrub_auto_enqueue(self, pgid: PgId) -> None:
        if self._use_mclock:
            self.scheduler.enqueue(
                "scrub",
                (lambda _c, _m: self._scrub_auto_chunk(pgid),
                 None, None),
                key=(pgid.pool, pgid.seed))
        else:
            # fifo queue: no scheduler threads to drain a scrub class —
            # the whole cycle runs inline on the caller (chunked loop
            # inside _scrub_auto_chunk, no recursion)
            self._scrub_auto_chunk(pgid)

    def _scrub_auto_chunk(self, pgid: PgId) -> None:
        key = (pgid.pool, pgid.seed)
        st = self._scrub_auto.get(key)
        if st is None:
            return
        done = False
        while not done:
            try:
                done = self._scrub_auto_run_chunk(pgid, st)
            except Exception as e:  # noqa: BLE001 - abort cycle, re-arm
                dout("osd", 1)("%s: auto-scrub chunk %s failed: %r",
                               self.name, pgid, e)
                done = True
            if not done and self._use_mclock:
                # yield the shard thread between chunks: client ops on
                # this PG interleave, mclock paces the scrub class
                self._scrub_auto_enqueue(pgid)
                return
        now = time.time()
        mn = float(self.cfg["osd_scrub_min_interval"])
        st.update(running=False, due=now + mn)
        self.perf.inc("scrubs")
        self.events.emit(
            "scrub",
            f"pg {self._pgstr(pgid)} auto deep-scrub done: "
            f"{st['objects']} objects, {st['bytes']} bytes"
            + (f", {st['mismatches']} mismatches"
               if st["mismatches"] else ""),
            severity="warn" if st["mismatches"] else "info",
            pg=self._pgstr(pgid), event="scrub_done",
            start_ts=st["started"], done=st["objects"],
            total=max(st["total"], st["objects"]),
            mismatches=st["mismatches"])

    def _scrub_auto_run_chunk(self, pgid: PgId, st: dict) -> bool:
        """Verify one cursor-bounded object range; True = cycle done."""
        cid = CollectionId(pgid.pool, pgid.seed)
        cursor = self._scrub_cursor_load(cid)
        try:
            # generation objects are rollback stashes (transient, no
            # digest contract) — skip them, like the -2 PG metadata
            oids = sorted(
                (o for o in self.store.list_objects(cid)
                 if o.shard > -2 and o.generation < 0),
                key=lambda o: (o.name, o.shard))
        except NoSuchCollection:
            return True
        st["total"] = max(st["total"], len(oids))
        if cursor is not None:
            oids = [o for o in oids if (o.name, o.shard) > cursor]
        chunk = oids[:int(self.cfg["osd_scrub_chunk_max"])]
        if not chunk:
            self._scrub_cursor_store(cid, None)
            return True
        self.perf.inc("scrub_auto_chunks")
        self._scrub_verify_folded(pgid, cid, chunk, st)
        last = chunk[-1]
        if len(chunk) == len(oids):
            # tail chunk: the cycle wrapped — clear the cursor so the
            # next cycle starts fresh (and a restart doesn't resume)
            self._scrub_cursor_store(cid, None)
            return True
        self._scrub_cursor_store(cid, (last.name, last.shard))
        self.events.emit(
            "scrub", f"pg {self._pgstr(pgid)} auto deep-scrub progress",
            pg=self._pgstr(pgid), event="scrub_progress",
            start_ts=st["started"], done=st["objects"],
            total=st["total"])
        return False

    def _scrub_verify_folded(self, pgid: PgId, cid: CollectionId,
                             chunk: list, st: dict) -> None:
        """Fold one chunk's objects through the batcher and confirm/
        repair any candidate mismatches."""
        ver = verifier(str(self.cfg["osd_scrub_fold"]))
        todo = []  # (oid, stored bytes, stored digest, attrs)
        for oid in chunk:
            try:
                attrs = dict(self.store.getattrs(cid, oid))
                data = self.store.read(cid, oid).to_bytes()
            except (NoSuchObject, NoSuchCollection):
                continue  # deleted under the cursor: not a finding
            d = attrs.get("d")
            if d is None:
                if data:
                    self.perf.inc("scrub_digest_missing")
                    dout("osd", 2)("%s: scrub %s %s/%d: no stored digest",
                                   self.name, pgid, oid.name, oid.shard)
                continue
            todo.append((oid, data, int(d), attrs))
        if not todo:
            return
        # pow2 length buckets: uniform row length per launch; the
        # stored digest extends over the zero pad host-side so the
        # folded compare is exact for every ragged length
        buckets: dict[int, list] = {}
        for item in todo:
            n = len(item[1])
            b = 4 if n <= 4 else 1 << (n - 1).bit_length()
            buckets.setdefault(b, []).append(item)
        for blen, items in sorted(buckets.items()):
            rows = np.zeros((len(items), blen), dtype=np.uint8)
            expected = np.empty(len(items), dtype=np.uint32)
            for i, (_oid, data, d, _attrs) in enumerate(items):
                rows[i, :len(data)] = np.frombuffer(data, dtype=np.uint8)
                expected[i] = crc32c_extend_zeros(d, blen - len(data))
            digs = self._ec_batcher.verify(ver, rows)
            self.perf.inc("scrub_verify_launches")
            st["objects"] += len(items)
            for i in np.nonzero(digs != expected)[0]:
                oid, data, d, attrs = items[int(i)]
                # candidate only: confirm with a host CRC over the
                # exact stored bytes before counting or repairing
                if _host_crc32c(data) == d:
                    dout("osd", 1)(
                        "%s: scrub %s %s/%d: folded false positive",
                        self.name, pgid, oid.name, oid.shard)
                    continue
                self._scrub_auto_mismatch(pgid, cid, oid, attrs, st)
        st["bytes"] += sum(len(it[1]) for it in todo)
        self.perf.inc("scrub_verified_bytes",
                      sum(len(it[1]) for it in todo))

    def _scrub_auto_mismatch(self, pgid: PgId, cid: CollectionId,
                             oid, attrs: dict, st: dict) -> None:
        """One confirmed bad local copy: count, report, repair via the
        existing per-object paths (EC rebuild / replicated pull)."""
        st["mismatches"] += 1
        self.perf.inc("scrub_mismatches")
        self.perf.inc("scrub_errors")
        name = vname_of(oid)
        self.events.emit(
            "scrub",
            f"pg {self._pgstr(pgid)} auto deep-scrub: digest mismatch "
            f"{name}/{oid.shard}",
            severity="warn", pg=self._pgstr(pgid), object=name,
            shard=oid.shard, kind="digest_mismatch")
        pool = self.osdmap.pools.get(pgid.pool)
        if pool is not None and pool.kind == "ec" and oid.shard >= 0:
            self._rebuild_shard(pgid, name, oid.shard, self.osd_id,
                                version=int(attrs.get("v", 0)),
                                force=True)
            return
        up = self.osdmap.pg_to_up_osds(pgid.pool, pgid.seed)
        peers = [u for u in up if u is not None and u != self.osd_id]
        if peers:
            # my copy is the corrupt one: pull clean bytes from a peer
            self.messenger.send_message(
                f"osd.{peers[0]}", MPGPull(pgid, [name], force=True))


# ---------------------------------------------------------------------------
# Fault injection (the ECInject role, src/osd/ECInject.{h,cc}: arm
# read/write/parity errors checked from the IO paths; driven by tests)
# ---------------------------------------------------------------------------

class FaultInjection:
    def __init__(self):
        self.corrupt_data: set = set()   # (pgid, name, shard)
        self.drop_shard_writes: set = set()  # shard ids to drop

    def corrupt_object(self, store, pgid: PgId, name: str,
                       shard: int = -1, offset: int = 0) -> bool:
        """Flip a byte in a stored object (silent corruption for scrub
        tests) — bypasses the transaction path on purpose."""
        cid = CollectionId(pgid.pool, pgid.seed)
        oid = ObjectId(name, shard=shard)
        if hasattr(store, "_dev"):  # bluestore: rot a byte on the device
            try:
                onode = store._onode(cid, oid)
            except (NoSuchObject, NoSuchCollection):
                return False
            idx = offset // 4096
            if idx >= len(onode.pages) or onode.pages[idx][0] < 0:
                return False
            with store._lock:
                store._flush_deferred()  # the device must hold the page
                phys = onode.pages[idx][0]
                page = bytearray(store._dev_read(phys))
                page[offset % 4096] ^= 0xFF
                store._dev_write(phys, page)
                store._dev.flush()
            return True
        try:
            obj = store._mem._obj(cid, oid) if hasattr(store, "_mem") \
                else store._obj(cid, oid)
        except NoSuchObject:
            return False
        if not obj.data:
            return False
        obj.data[offset] ^= 0xFF
        return True
