"""RADOS self-managed snapshots: SnapSet clones, SnapMapper, trimming.

The PrimaryLogPG snapshot machinery (ref src/osd/PrimaryLogPG.cc
make_writeable — clone-on-first-write-after-snap, SnapSet bookkeeping in
src/osd/osd_types.h, snapid resolution in find_object_context, SnapMapper
in src/osd/SnapMapper.{h,cc}, trimming in src/osd/PrimaryLogPG.cc
SnapTrimmer), redesigned for this codebase's single-dispatch daemons:

- the client sends a SnapContext (seq + snap ids) on writes and a snapid
  on reads (MOSDOp v2 tail);
- on the first write after a new snap, the primary stages a `clone` op
  into the SAME store transaction as the write: the head is cloned to
  ObjectId(name, generation=snapid) (COW is free on BlueStore), the
  head's "ss" attr (SnapSet: seq, clone list, sizes, overlaps) is
  updated, and the PG-local SnapMapper object's omap gains a
  snapid->name row for trim lookup;
- replicas perform the identical clone via a "_snap" rider on the
  sub-write's attrs (deterministic: the primary ships the new SnapSet
  bytes, replicas don't recompute);
- clones travel recovery, scrub, and the PG log under a VIRTUAL NAME
  ("name\\0g<gen>"), so every (name, shard)-keyed subsystem handles them
  unchanged — `to_oid`/`vname` translate at the store boundary;
- deleting a head that has clones (or a live SnapContext) leaves a
  WHITEOUT head (attr "wh"=1, size 0) so the SnapSet survives — the
  reference's snapdir object role; a later write resurrects the head;
- snap removal is a map change (pool.removed_snaps): each primary trims
  asynchronously — remove the clone, update the SnapSet, tombstone the
  virtual name so recovery never resurrects it.

EC pools take the same machinery SHARD-WISE: every helper carries a
shard id (-1 = replicated head), the clone op copies each shard object
to its generation variant (the encoded bytes of the head at snap time
ARE the clone's encoded bytes — no re-encode), the SnapSet rides every
shard's attrs like "len"/"wh" already do, and the rider travels on the
EC sub-ops.  Rollback is per-shard clone->head copy; trim removes each
shard's clone object.  (The reference keeps EC snapshots behind the
overwrite journal — src/osd/PrimaryLogPG.cc snap paths + SnapMapper.cc;
here the rollback-capable pglog plays that role.)
"""

from __future__ import annotations

from ..msg.messages import MOSDOpReply, MSubWrite, PgId
from ..msg.wire import pack_value as _pack, unpack_value as _unpack
from ..ops.native import crc32c as _crc32c
from .objectstore import (CollectionId, NoSuchCollection, NoSuchObject,
                          ObjectId, Transaction)
from .pglog import LogEntry

ENOENT, EINVAL = -2, -22

_VSEP = "\x00g"
SNAPMAPPER = "_snapmapper"  # per-PG local metadata object (shard -2)


# ----------------------------------------------------- virtual-name algebra
def vname(name: str, gen: int = -1) -> str:
    """Flatten (name, generation) into the single string every
    (name, shard)-keyed subsystem (inventory, pushes, scrub, tombstones,
    PG log) already carries."""
    return name if gen < 0 else f"{name}{_VSEP}{gen}"


def vname_of(oid: ObjectId) -> str:
    return vname(oid.name, oid.generation)


def split_vname(n: str) -> tuple[str, int]:
    base, sep, g = n.partition(_VSEP)
    if not sep:
        return n, -1
    try:
        return base, int(g)
    except ValueError:
        return n, -1


def to_oid(n: str, shard: int = -1) -> ObjectId:
    base, gen = split_vname(n)
    return ObjectId(base, shard=shard, generation=gen)


def _sub_intervals(iv: list, off: int, length: int) -> list:
    """Subtract [off, off+length) from an interval list (clone_overlap
    maintenance, interval_set::subtract role)."""
    out = []
    lo, hi = off, off + length
    for s, ln in iv:
        e = s + ln
        if e <= lo or s >= hi:
            out.append([s, ln])
            continue
        if s < lo:
            out.append([s, lo - s])
        if e > hi:
            out.append([hi, e - hi])
    return out


class SnapMixin:
    """Mixed into OSDDaemon: clone-on-write, snap reads, trimming."""

    def _init_snaps(self) -> None:
        # (pool, seed, snapid) this OSD has trimmed AS PRIMARY.  Keyed
        # per-PG so a failover makes the new primary re-trim (the trim is
        # idempotent — the SnapMapper omap records what is left to do).
        self._trimmed_snaps: set[tuple[int, int, int]] = set()

    # ------------------------------------------------------------ SnapSet
    def _smap_oid(self) -> ObjectId:
        return ObjectId(SNAPMAPPER, shard=-2)

    def _load_ss(self, cid: CollectionId, name: str,
                 shard: int = -1) -> dict | None:
        try:
            raw = self.store.getattrs(
                cid, ObjectId(name, shard=shard)).get("ss")
        except (NoSuchObject, NoSuchCollection):
            return None
        return _unpack(raw) if raw else None

    def _ec_load_ss(self, pgid: PgId, name: str) -> dict | None:
        """SnapSet from ANY shard copy (the primary may hold any
        position; a behind shard may lack the attr)."""
        cid = CollectionId(pgid.pool, pgid.seed)
        for shard in range(self.osdmap.pools[pgid.pool].size):
            ss = self._load_ss(cid, name, shard=shard)
            if ss is not None:
                return ss
        return None

    def _head_whiteout(self, cid: CollectionId, name: str,
                       shard: int = -1) -> bool:
        try:
            return bool(self.store.getattrs(
                cid, ObjectId(name, shard=shard)).get("wh"))
        except (NoSuchObject, NoSuchCollection):
            return False

    def _ec_whiteout(self, pgid: PgId, name: str) -> bool:
        cid = CollectionId(pgid.pool, pgid.seed)
        for shard in range(self.osdmap.pools[pgid.pool].size):
            try:
                a = self.store.getattrs(cid, ObjectId(name, shard=shard))
            except (NoSuchObject, NoSuchCollection):
                continue
            return bool(a.get("wh"))
        return False

    # --------------------------------------------- clone-on-write staging
    def _snap_prepare(self, pgid: PgId, m) -> tuple[Transaction | None,
                                                    dict | None]:
        """Primary, before a head write/remove: stage the make_writeable
        work.  Returns (pre_tx, rider) — pre_tx prepends to the write's
        transaction, rider travels to replicas in the sub-write attrs."""
        if not m.snap_seq:
            return None, None
        if self.osdmap.pools[pgid.pool].kind == "ec":
            return None, self._snap_prepare_ec(pgid, m)
        cid = CollectionId(pgid.pool, pgid.seed)
        name = m.oid
        head = ObjectId(name)
        newest = max(m.snaps) if m.snaps else m.snap_seq
        if not self.store.exists(cid, head):
            # creating write under a snapc: record the birth seq so (a)
            # later writes under the SAME snapc don't spuriously clone
            # content written after the snap, and (b) reads at snapids
            # from before the birth answer ENOENT
            ss = {"seq": max(m.snap_seq, newest), "clones": [],
                  "sz": {}, "ov": {}, "born": max(m.snap_seq, newest)}
            ss_b = _pack(ss)
            tx = Transaction()
            tx.setattrs(cid, head, {"ss": ss_b})
            return tx, {"clone": -1, "ss": ss_b, "v": -1}
        ss = self._load_ss(cid, name) or \
            {"seq": 0, "clones": [], "sz": {}, "ov": {}}
        whiteout = self._head_whiteout(cid, name)
        if whiteout:
            # resurrection: a new birth epoch — snapids in the dead
            # window (after the last clone, before now) stay ENOENT
            ss["born"] = max(ss.get("born", 0), m.snap_seq, newest)
        need_clone = (m.snap_seq > ss["seq"] and newest not in ss["clones"]
                      and not whiteout)
        # overlap shrink applies on EVERY head write once clones exist
        written: tuple[int, int] | None = None
        if m.op == "write":
            written = (m.offset, len(m.data))
        elif m.op in ("write_full", "remove", "snap_rollback"):
            try:
                # raw (logical) size: overlap intervals live in raw space
                old_size = self._obj_raw_size(cid, head)
            except (NoSuchObject, NoSuchCollection):
                old_size = 0
            written = (0, max(old_size, len(getattr(m, "data", b""))))
        tx = Transaction()
        cloneid = -1
        clone_v = -1
        if need_clone:
            cloneid = newest
            clone = ObjectId(name, generation=cloneid)
            tx.clone(cid, head, clone)
            size = self._obj_raw_size(cid, head)
            ss["clones"] = sorted(set(ss["clones"]) | {cloneid})
            ss["sz"][cloneid] = size
            ss["ov"][cloneid] = [[0, size]]
            clone_v = self._next_version(pgid)
            self._log_apply(tx, pgid, LogEntry(
                clone_v, "write", vname(name, cloneid), -1,
                prev_version=-1))
            tx.omap_setkeys(cid, self._smap_oid(),
                            {f"{cloneid:016x}.{name}": b""})
        ss["seq"] = max(ss["seq"], m.snap_seq, newest)
        if written and ss["clones"]:
            top = ss["clones"][-1]
            ss["ov"][top] = _sub_intervals(
                ss["ov"].get(top, []), written[0], written[1])
        ss_b = _pack(ss)
        tx.setattrs(cid, head, {"ss": ss_b})
        rider = {"clone": cloneid, "ss": ss_b, "v": clone_v}
        return tx, rider

    def _snap_prepare_ec(self, pgid: PgId, m) -> dict | None:
        """Primary, EC pool: compute the make_writeable decision and
        the final SnapSet.  Returns the rider every shard holder
        (primary included) applies locally via _snap_apply_rider — the
        clone itself is per-shard (the encoded head bytes at snap time
        ARE the clone's encoded bytes), so no pre-tx is staged here."""
        name = m.oid
        newest = max(m.snaps) if m.snaps else m.snap_seq
        existing = self._ec_object_len(pgid, name)
        if existing is None:
            ss = {"seq": max(m.snap_seq, newest), "clones": [],
                  "sz": {}, "ov": {},
                  "born": max(m.snap_seq, newest)}
            return {"clone": -1, "ss": _pack(ss), "v": -1}
        ss = self._ec_load_ss(pgid, name) or \
            {"seq": 0, "clones": [], "sz": {}, "ov": {}}
        whiteout = self._ec_whiteout(pgid, name)
        if whiteout:
            ss["born"] = max(ss.get("born", 0), m.snap_seq, newest)
        need_clone = (m.snap_seq > ss["seq"]
                      and newest not in ss["clones"] and not whiteout)
        written: tuple[int, int] | None = None
        if m.op == "write":
            written = (m.offset, len(m.data))
        elif m.op in ("write_full", "remove", "snap_rollback"):
            written = (0, max(existing, len(getattr(m, "data", b""))))
        cloneid = clone_v = -1
        if need_clone:
            cloneid = newest
            ss["clones"] = sorted(set(ss["clones"]) | {cloneid})
            ss["sz"][cloneid] = existing
            ss["ov"][cloneid] = [[0, existing]]
            clone_v = self._next_version(pgid)
        ss["seq"] = max(ss["seq"], m.snap_seq, newest)
        if written and ss["clones"]:
            top = ss["clones"][-1]
            ss["ov"][top] = _sub_intervals(
                ss["ov"].get(top, []), written[0], written[1])
        return {"clone": cloneid, "ss": _pack(ss), "v": clone_v}

    def _snap_apply_rider(self, pgid: PgId, name: str,
                          rider: dict, shard: int = -1) -> Transaction:
        """Any holder: rebuild the primary's snap pre-tx
        deterministically from the rider (ships the final SnapSet
        bytes).  shard >= 0 clones/stamps that EC shard object."""
        cid = CollectionId(pgid.pool, pgid.seed)
        head = ObjectId(name, shard=shard)
        tx = Transaction()
        cloneid = int(rider.get("clone", -1))
        clone = ObjectId(name, shard=shard, generation=cloneid)
        if cloneid >= 0 and self.store.exists(cid, head) and \
                not self.store.exists(cid, clone):
            tx.clone(cid, head, clone)
            self._log_apply(tx, pgid, LogEntry(
                int(rider.get("v", -1)), "write", vname(name, cloneid),
                shard, prev_version=-1))
            tx.omap_setkeys(cid, self._smap_oid(),
                            {f"{cloneid:016x}.{name}": b""})
        if not self.store.exists(cid, head):
            # creating write: the SnapSet (with its birth seq) must land
            # WITH the object, or the next write under the same snapc
            # sees no ss and stages a spurious clone of post-snap data
            tx.touch(cid, head)
        tx.setattrs(cid, head, {"ss": bytes(rider["ss"])})
        return tx

    # ------------------------------------------------------- read resolve
    def _snap_resolve(self, cid: CollectionId, name: str,
                      snapid: int) -> ObjectId | None:
        """find_object_context role: which object serves a read at
        snapid?  None = ENOENT."""
        if snapid == 0:
            if self._head_whiteout(cid, name):
                return None
            return ObjectId(name)
        ss = self._load_ss(cid, name)
        clones = (ss or {}).get("clones", [])
        covering = [c for c in clones if c >= snapid]
        if covering:
            target = ObjectId(name, generation=min(covering))
            if self.store.exists(cid, target):
                return target
            return None
        # before the object's birth (created under a later snapc, or
        # resurrected after a whiteout): it did not exist at that snap
        if ss and snapid <= ss.get("born", 0):
            return None
        # newer than every clone: the head is the living state
        if self._head_whiteout(cid, name):
            return None
        return ObjectId(name)

    def _ec_snap_resolve(self, pgid: PgId, name: str,
                         snapid: int) -> str | None:
        """find_object_context for EC pools: which VNAME serves a read
        at snapid?  None = ENOENT.  Existence is judged from the
        SnapSet, not per-shard probes — degraded clones decode from the
        surviving shards like any other object."""
        if snapid == 0:
            return None if self._ec_whiteout(pgid, name) else name
        ss = self._ec_load_ss(pgid, name)
        clones = (ss or {}).get("clones", [])
        covering = [c for c in clones if c >= snapid]
        if covering:
            return vname(name, min(covering))
        if ss and snapid <= ss.get("born", 0):
            return None
        if self._ec_whiteout(pgid, name):
            return None
        return name

    # ------------------------------------------------------- extended ops
    def _op_list_snaps(self, conn, m, pgid: PgId, up: list) -> None:
        cid = CollectionId(pgid.pool, pgid.seed)
        if self.osdmap.pools[pgid.pool].kind == "ec":
            if self._ec_object_len(pgid, m.oid) is None:
                conn.send(MOSDOpReply(m.tid, ENOENT,
                                      epoch=self.osdmap.epoch))
                return
            ss = self._ec_load_ss(pgid, m.oid) or \
                {"seq": 0, "clones": [], "sz": {}, "ov": {}}
            out = dict(ss)
            out["head"] = not self._ec_whiteout(pgid, m.oid)
            conn.send(MOSDOpReply(m.tid, 0, data=_pack(out),
                                  epoch=self.osdmap.epoch))
            return
        if not self.store.exists(cid, ObjectId(m.oid)):
            conn.send(MOSDOpReply(m.tid, ENOENT, epoch=self.osdmap.epoch))
            return
        ss = self._load_ss(cid, m.oid) or \
            {"seq": 0, "clones": [], "sz": {}, "ov": {}}
        out = dict(ss)
        out["head"] = not self._head_whiteout(cid, m.oid)
        conn.send(MOSDOpReply(m.tid, 0, data=_pack(out),
                              epoch=self.osdmap.epoch))

    def _op_snap_rollback(self, conn, m, pgid: PgId, up: list) -> None:
        """Roll the head back to its state at snapid (the rados rollback
        op: PrimaryLogPG _rollback_to)."""
        key = (pgid, m.oid)

        def thunk(conn=conn, m=m, pgid=pgid, key=key):
            up2 = self.osdmap.pg_to_up_osds(pgid.pool, pgid.seed)
            self._do_snap_rollback(conn, m, pgid, up2, lock_key=key)

        self._obj_lock(key, thunk)

    def _do_snap_rollback(self, conn, m, pgid: PgId, up: list,
                          lock_key) -> None:
        if self.osdmap.pools[pgid.pool].kind == "ec":
            self._do_snap_rollback_ec(conn, m, pgid, up, lock_key)
            return
        cid = CollectionId(pgid.pool, pgid.seed)
        name = m.oid
        # rollback is a head WRITE: it goes through make_writeable, so
        # head state owed to a newer snapshot gets its clone first
        snap_tx, rider = self._snap_prepare(pgid, m)
        ss = (_unpack(bytes(rider["ss"])) if rider is not None
              else self._load_ss(cid, name)) or \
            {"seq": 0, "clones": [], "sz": {}, "ov": {}}
        covering = [c for c in ss["clones"] if c >= m.snapid]
        if not covering:
            if self._snap_resolve(cid, name, m.snapid) is None and \
                    self.store.exists(cid, ObjectId(name)):
                # the object did NOT exist at snapid (born later, or
                # whiteout window): rolling back means it ceases to
                # exist — a replicated remove (find_object_context
                # pre-birth + PrimaryLogPG _rollback_to ENOENT path)
                self._rep_remove(conn, m, pgid, up)
                self._obj_unlock(lock_key)
                return
            # head already IS the state at snapid (or nothing exists)
            code = 0 if (self.store.exists(cid, ObjectId(name))
                         and not self._head_whiteout(cid, name)) else ENOENT
            conn.send(MOSDOpReply(m.tid, code, epoch=self.osdmap.epoch))
            self._obj_unlock(lock_key)
            return
        cloneid = min(covering)
        version = self._next_version(pgid)
        ss_b = _pack(ss)
        self._apply_snap_rollback(pgid, name, cloneid, ss_b, version,
                                  pre_tx=snap_tx)
        peers = [u for u in up if u is not None and u != self.osd_id]
        if not peers:
            conn.send(MOSDOpReply(m.tid, 0, version=version,
                                  epoch=self.osdmap.epoch))
            self._obj_unlock(lock_key)
            return
        tid = next(self._tids)
        from .daemon import _PendingWrite
        pw = _PendingWrite(m.client, m.tid, len(peers), version)
        pw.span = getattr(m, '_span', None)
        pw.lock_key = lock_key
        self._pending_writes[tid] = pw
        payload = _pack({"cloneid": cloneid, "ss": ss_b,
                         "rider": rider})
        for peer in peers:
            self.messenger.send_message(
                f"osd.{peer}",
                MSubWrite(tid, pgid, name, -1, version, "snap_rollback",
                          payload, epoch=self._entry_epoch()))

    def _apply_snap_rollback(self, pgid: PgId, name: str, cloneid: int,
                             ss_b: bytes, version: int,
                             pre_tx: Transaction | None = None,
                             shard: int = -1,
                             total_len: int = -1) -> None:
        cid = CollectionId(pgid.pool, pgid.seed)
        head = ObjectId(name, shard=shard)
        clone = ObjectId(name, shard=shard, generation=cloneid)
        if not self.store.exists(cid, clone):
            return
        tx = Transaction()
        if pre_tx is not None:  # make_writeable clone of the current head
            tx.append(pre_tx)
        data = self.store.read(cid, clone).to_bytes()
        clone_attrs = dict(self.store.getattrs(cid, clone))
        if self.store.exists(cid, head):
            tx.remove(cid, head)
        tx.clone(cid, clone, head)
        # the clone's copied attrs carry a STALE SnapSet and version:
        # restamp with the live ones (and clear any whiteout).  EC
        # shards carry the WHOLE-object length in "len" (the snapshot's
        # size from the SnapSet), not the shard-stream length.  The
        # clone op copies the STORED bytes (and their cz/crl extent
        # metadata): d covers them as stored, and a compressed clone's
        # logical length is its recorded raw length.
        rep_len = (int(clone_attrs["crl"]) if "cz" in clone_attrs
                   else len(data))
        tx.setattrs(cid, head, {"ss": ss_b, "v": version, "wh": 0,
                                "len": (total_len if shard >= 0
                                        and total_len >= 0
                                        else rep_len),
                                "d": _crc32c(data)})
        self._log_apply(tx, pgid, LogEntry(version, "write", name, shard,
                                           prev_version=-1))
        self.store.queue_transaction(tx)

    def _do_snap_rollback_ec(self, conn, m, pgid: PgId, up: list,
                             lock_key) -> None:
        """EC rollback: each shard copies its clone object back over
        its head shard — the clone's encoded bytes ARE the head's bytes
        at snap time, so no decode/re-encode round trip is needed."""
        name = m.oid
        _tx, rider = self._snap_prepare(pgid, m)  # may clone the head
        ss = (_unpack(bytes(rider["ss"])) if rider is not None
              else self._ec_load_ss(pgid, name)) or \
            {"seq": 0, "clones": [], "sz": {}, "ov": {}}
        covering = [c for c in ss["clones"] if c >= m.snapid]
        if not covering:
            exists = self._ec_object_len(pgid, name) is not None
            if exists and \
                    self._ec_snap_resolve(pgid, name, m.snapid) is None:
                # born after the snap: rollback removes it (pre-birth)
                self._ec_remove(conn, m, pgid, up, lock_key=lock_key)
                return
            code = 0 if (exists and not self._ec_whiteout(pgid, name)) \
                else ENOENT
            conn.send(MOSDOpReply(m.tid, code, epoch=self.osdmap.epoch))
            self._obj_unlock(lock_key)
            return
        cloneid = min(covering)
        total = int(ss.get("sz", {}).get(cloneid, 0))
        version = self._next_version(pgid)
        ss_b = _pack(ss)
        payload = _pack({"cloneid": cloneid, "ss": ss_b,
                         "rider": rider, "total": total})
        tid = next(self._tids)
        remote = sum(1 for o in up
                     if o is not None and o != self.osd_id)
        if remote:  # registered before any send (sharded dispatch)
            from .daemon import _PendingWrite
            pw = _PendingWrite(m.client, m.tid, remote, version)
            pw.span = getattr(m, '_span', None)
            pw.lock_key = lock_key
            self._pending_writes[tid] = pw
        epoch = self._entry_epoch()
        for shard, osd in enumerate(up):
            if osd is None:
                continue
            if osd == self.osd_id:
                pre = (self._snap_apply_rider(pgid, name, rider,
                                              shard=shard)
                       if rider is not None else None)
                self._apply_snap_rollback(pgid, name, cloneid, ss_b,
                                          version, pre_tx=pre,
                                          shard=shard, total_len=total)
            else:
                self.messenger.send_message(
                    f"osd.{osd}",
                    MSubWrite(tid, pgid, name, shard, version,
                              "snap_rollback", payload, epoch=epoch))
        self._ec_cache.invalidate(pgid, name)
        if remote == 0:
            conn.send(MOSDOpReply(m.tid, 0, version=version,
                                  epoch=self.osdmap.epoch))
            self._obj_unlock(lock_key)
            return

    # ----------------------------------------------------------- whiteout
    def _apply_whiteout(self, pgid: PgId, name: str, version: int,
                        pre_tx: Transaction | None = None,
                        shard: int = -1) -> None:
        """Delete a head that has clones: the object becomes a zero-size
        whiteout so the SnapSet survives (the snapdir role).  For EC,
        each shard object whiteouts independently (shard >= 0)."""
        cid = CollectionId(pgid.pool, pgid.seed)
        head = ObjectId(name, shard=shard)
        tx = Transaction()
        if pre_tx is not None:
            tx.append(pre_tx)
        if not self.store.exists(cid, head):
            return
        tx.truncate(cid, head, 0)
        tx.setattrs(cid, head, {"wh": 1, "v": version, "len": 0,
                                "d": _crc32c(b"")})
        self._log_apply(tx, pgid, LogEntry(version, "write", name, shard,
                                           prev_version=-1))
        self.store.queue_transaction(tx)

    # ---------------------------------------------------------- trimming
    def _snap_trim_check(self) -> None:
        """After a map update: trim clones of newly removed snaps on
        every PG this OSD leads (SnapTrimmer role; idempotent)."""
        if self.osdmap is None:
            return
        for pool in list(self.osdmap.pools.values()):
            for snapid in pool.removed_snaps:
                self._trim_snap(pool, snapid)

    def _trim_snap(self, pool, snapid: int) -> None:
        for seed in range(pool.pg_num):
            up = self.osdmap.pg_to_up_osds(pool.pool_id, seed)
            if self._primary_of(up) != self.osd_id:
                continue
            key = (pool.pool_id, seed, snapid)
            if key in self._trimmed_snaps:
                continue
            self._trimmed_snaps.add(key)
            pgid = PgId(pool.pool_id, seed)
            cid = CollectionId(pool.pool_id, seed)
            try:
                smap = self.store.omap_get(cid, self._smap_oid())
            except (NoSuchObject, NoSuchCollection):
                continue
            prefix = f"{snapid:016x}."
            for k in sorted(smap):
                if not k.startswith(prefix):
                    continue
                name = k[len(prefix):]
                key = (pgid, name)

                def thunk(name=name, pgid=pgid, snapid=snapid, key=key):
                    try:
                        self._trim_one(pgid, name, snapid)
                    finally:
                        self._obj_unlock(key)

                self._obj_lock(key, thunk)

    def _trim_one(self, pgid: PgId, name: str, snapid: int) -> None:
        cid = CollectionId(pgid.pool, pgid.seed)
        is_ec = self.osdmap.pools[pgid.pool].kind == "ec"
        version = self._next_version(pgid)
        ss = (self._ec_load_ss(pgid, name) if is_ec
              else self._load_ss(cid, name)) or \
            {"seq": 0, "clones": [], "sz": {}, "ov": {}}
        ss["clones"] = [c for c in ss["clones"] if c != snapid]
        ss["sz"].pop(snapid, None)
        ss["ov"].pop(snapid, None)
        drop_head = (not ss["clones"]
                     and (self._ec_whiteout(pgid, name) if is_ec
                          else self._head_whiteout(cid, name)))
        ss_b = _pack(ss)
        up = self.osdmap.pg_to_up_osds(pgid.pool, pgid.seed)
        payload = _pack({"snapid": snapid, "ss": ss_b,
                         "drop_head": drop_head})
        tid = next(self._tids)
        epoch = self._entry_epoch()
        if is_ec:
            # per-shard: each holder trims its own shard clone object
            for shard, osd in enumerate(up):
                if osd is None:
                    continue
                if osd == self.osd_id:
                    self._apply_trim(pgid, name, snapid, ss_b,
                                     drop_head, version, shard=shard)
                else:
                    self.messenger.send_message(
                        f"osd.{osd}",
                        MSubWrite(tid, pgid, name, shard, version,
                                  "trim_clone", payload, epoch=epoch))
        else:
            self._apply_trim(pgid, name, snapid, ss_b, drop_head,
                             version)
            for peer in up:
                if peer is not None and peer != self.osd_id:
                    self.messenger.send_message(
                        f"osd.{peer}",
                        MSubWrite(tid, pgid, name, -1, version,
                                  "trim_clone", payload, epoch=epoch))
        self.perf.inc("snap_trims")

    def _apply_trim(self, pgid: PgId, name: str, snapid: int, ss_b: bytes,
                    drop_head: bool, version: int,
                    shard: int = -1) -> None:
        cid = CollectionId(pgid.pool, pgid.seed)
        clone = ObjectId(name, shard=shard, generation=snapid)
        head = ObjectId(name, shard=shard)
        tx = Transaction()
        if self.store.exists(cid, clone):
            tx.remove(cid, clone)
        if self.store.exists(cid, head):
            if drop_head:
                tx.remove(cid, head)
            else:
                tx.setattrs(cid, head, {"ss": ss_b})
        try:
            if f"{snapid:016x}.{name}" in self.store.omap_get(
                    cid, self._smap_oid()):
                tx.omap_rmkeys(cid, self._smap_oid(),
                               [f"{snapid:016x}.{name}"])
        except (NoSuchObject, NoSuchCollection):
            pass
        self._log_apply(tx, pgid, LogEntry(
            version, "remove", vname(name, snapid), shard,
            prev_version=-1))
        if not tx.empty():
            self.store.queue_transaction(tx)
        self._record_tombstone(pgid, vname(name, snapid), version)
        if drop_head:
            self._record_tombstone(pgid, name, version)
