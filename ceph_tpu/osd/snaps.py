"""RADOS self-managed snapshots: SnapSet clones, SnapMapper, trimming.

The PrimaryLogPG snapshot machinery (ref src/osd/PrimaryLogPG.cc
make_writeable — clone-on-first-write-after-snap, SnapSet bookkeeping in
src/osd/osd_types.h, snapid resolution in find_object_context, SnapMapper
in src/osd/SnapMapper.{h,cc}, trimming in src/osd/PrimaryLogPG.cc
SnapTrimmer), redesigned for this codebase's single-dispatch daemons:

- the client sends a SnapContext (seq + snap ids) on writes and a snapid
  on reads (MOSDOp v2 tail);
- on the first write after a new snap, the primary stages a `clone` op
  into the SAME store transaction as the write: the head is cloned to
  ObjectId(name, generation=snapid) (COW is free on BlueStore), the
  head's "ss" attr (SnapSet: seq, clone list, sizes, overlaps) is
  updated, and the PG-local SnapMapper object's omap gains a
  snapid->name row for trim lookup;
- replicas perform the identical clone via a "_snap" rider on the
  sub-write's attrs (deterministic: the primary ships the new SnapSet
  bytes, replicas don't recompute);
- clones travel recovery, scrub, and the PG log under a VIRTUAL NAME
  ("name\\0g<gen>"), so every (name, shard)-keyed subsystem handles them
  unchanged — `to_oid`/`vname` translate at the store boundary;
- deleting a head that has clones (or a live SnapContext) leaves a
  WHITEOUT head (attr "wh"=1, size 0) so the SnapSet survives — the
  reference's snapdir object role; a later write resurrects the head;
- snap removal is a map change (pool.removed_snaps): each primary trims
  asynchronously — remove the clone, update the SnapSet, tombstone the
  virtual name so recovery never resurrects it.

Replicated pools only (the reference gates snaps behind the same op
breadth; EC-pool snapshot parity needs the EC overwrite log tier).
"""

from __future__ import annotations

from ..msg.messages import MOSDOpReply, MSubWrite, PgId
from ..msg.wire import pack_value as _pack, unpack_value as _unpack
from ..ops.native import crc32c as _crc32c
from .objectstore import (CollectionId, NoSuchCollection, NoSuchObject,
                          ObjectId, Transaction)
from .pglog import LogEntry

ENOENT, EINVAL = -2, -22

_VSEP = "\x00g"
SNAPMAPPER = "_snapmapper"  # per-PG local metadata object (shard -2)


# ----------------------------------------------------- virtual-name algebra
def vname(name: str, gen: int = -1) -> str:
    """Flatten (name, generation) into the single string every
    (name, shard)-keyed subsystem (inventory, pushes, scrub, tombstones,
    PG log) already carries."""
    return name if gen < 0 else f"{name}{_VSEP}{gen}"


def vname_of(oid: ObjectId) -> str:
    return vname(oid.name, oid.generation)


def split_vname(n: str) -> tuple[str, int]:
    base, sep, g = n.partition(_VSEP)
    if not sep:
        return n, -1
    try:
        return base, int(g)
    except ValueError:
        return n, -1


def to_oid(n: str, shard: int = -1) -> ObjectId:
    base, gen = split_vname(n)
    return ObjectId(base, shard=shard, generation=gen)


def _sub_intervals(iv: list, off: int, length: int) -> list:
    """Subtract [off, off+length) from an interval list (clone_overlap
    maintenance, interval_set::subtract role)."""
    out = []
    lo, hi = off, off + length
    for s, ln in iv:
        e = s + ln
        if e <= lo or s >= hi:
            out.append([s, ln])
            continue
        if s < lo:
            out.append([s, lo - s])
        if e > hi:
            out.append([hi, e - hi])
    return out


class SnapMixin:
    """Mixed into OSDDaemon: clone-on-write, snap reads, trimming."""

    def _init_snaps(self) -> None:
        # (pool, seed, snapid) this OSD has trimmed AS PRIMARY.  Keyed
        # per-PG so a failover makes the new primary re-trim (the trim is
        # idempotent — the SnapMapper omap records what is left to do).
        self._trimmed_snaps: set[tuple[int, int, int]] = set()

    # ------------------------------------------------------------ SnapSet
    def _smap_oid(self) -> ObjectId:
        return ObjectId(SNAPMAPPER, shard=-2)

    def _load_ss(self, cid: CollectionId, name: str) -> dict | None:
        try:
            raw = self.store.getattrs(cid, ObjectId(name)).get("ss")
        except (NoSuchObject, NoSuchCollection):
            return None
        return _unpack(raw) if raw else None

    def _head_whiteout(self, cid: CollectionId, name: str) -> bool:
        try:
            return bool(self.store.getattrs(cid,
                                            ObjectId(name)).get("wh"))
        except (NoSuchObject, NoSuchCollection):
            return False

    # --------------------------------------------- clone-on-write staging
    def _snap_prepare(self, pgid: PgId, m) -> tuple[Transaction | None,
                                                    dict | None]:
        """Primary, before a head write/remove: stage the make_writeable
        work.  Returns (pre_tx, rider) — pre_tx prepends to the write's
        transaction, rider travels to replicas in the sub-write attrs."""
        if not m.snap_seq or self.osdmap.pools[pgid.pool].kind == "ec":
            return None, None
        cid = CollectionId(pgid.pool, pgid.seed)
        name = m.oid
        head = ObjectId(name)
        newest = max(m.snaps) if m.snaps else m.snap_seq
        if not self.store.exists(cid, head):
            # creating write under a snapc: record the birth seq so (a)
            # later writes under the SAME snapc don't spuriously clone
            # content written after the snap, and (b) reads at snapids
            # from before the birth answer ENOENT
            ss = {"seq": max(m.snap_seq, newest), "clones": [],
                  "sz": {}, "ov": {}, "born": max(m.snap_seq, newest)}
            ss_b = _pack(ss)
            tx = Transaction()
            tx.setattrs(cid, head, {"ss": ss_b})
            return tx, {"clone": -1, "ss": ss_b, "v": -1}
        ss = self._load_ss(cid, name) or \
            {"seq": 0, "clones": [], "sz": {}, "ov": {}}
        whiteout = self._head_whiteout(cid, name)
        if whiteout:
            # resurrection: a new birth epoch — snapids in the dead
            # window (after the last clone, before now) stay ENOENT
            ss["born"] = max(ss.get("born", 0), m.snap_seq, newest)
        need_clone = (m.snap_seq > ss["seq"] and newest not in ss["clones"]
                      and not whiteout)
        # overlap shrink applies on EVERY head write once clones exist
        written: tuple[int, int] | None = None
        if m.op == "write":
            written = (m.offset, len(m.data))
        elif m.op in ("write_full", "remove", "snap_rollback"):
            try:
                old_size = self.store.stat(cid, head)["size"]
            except (NoSuchObject, NoSuchCollection):
                old_size = 0
            written = (0, max(old_size, len(getattr(m, "data", b""))))
        tx = Transaction()
        cloneid = -1
        clone_v = -1
        if need_clone:
            cloneid = newest
            clone = ObjectId(name, generation=cloneid)
            tx.clone(cid, head, clone)
            size = self.store.stat(cid, head)["size"]
            ss["clones"] = sorted(set(ss["clones"]) | {cloneid})
            ss["sz"][cloneid] = size
            ss["ov"][cloneid] = [[0, size]]
            clone_v = self._next_version(pgid)
            self._log_apply(tx, pgid, LogEntry(
                clone_v, "write", vname(name, cloneid), -1,
                prev_version=-1))
            tx.omap_setkeys(cid, self._smap_oid(),
                            {f"{cloneid:016x}.{name}": b""})
        ss["seq"] = max(ss["seq"], m.snap_seq, newest)
        if written and ss["clones"]:
            top = ss["clones"][-1]
            ss["ov"][top] = _sub_intervals(
                ss["ov"].get(top, []), written[0], written[1])
        ss_b = _pack(ss)
        tx.setattrs(cid, head, {"ss": ss_b})
        rider = {"clone": cloneid, "ss": ss_b, "v": clone_v}
        return tx, rider

    def _snap_apply_rider(self, pgid: PgId, name: str,
                          rider: dict) -> Transaction:
        """Replica: rebuild the primary's snap pre-tx deterministically
        from the rider (ships the final SnapSet bytes)."""
        cid = CollectionId(pgid.pool, pgid.seed)
        head = ObjectId(name)
        tx = Transaction()
        cloneid = int(rider.get("clone", -1))
        if cloneid >= 0 and self.store.exists(cid, head) and \
                not self.store.exists(cid, ObjectId(name,
                                                    generation=cloneid)):
            tx.clone(cid, head, ObjectId(name, generation=cloneid))
            self._log_apply(tx, pgid, LogEntry(
                int(rider.get("v", -1)), "write", vname(name, cloneid),
                -1, prev_version=-1))
            tx.omap_setkeys(cid, self._smap_oid(),
                            {f"{cloneid:016x}.{name}": b""})
        if self.store.exists(cid, head):
            tx.setattrs(cid, head, {"ss": bytes(rider["ss"])})
        return tx

    # ------------------------------------------------------- read resolve
    def _snap_resolve(self, cid: CollectionId, name: str,
                      snapid: int) -> ObjectId | None:
        """find_object_context role: which object serves a read at
        snapid?  None = ENOENT."""
        if snapid == 0:
            if self._head_whiteout(cid, name):
                return None
            return ObjectId(name)
        ss = self._load_ss(cid, name)
        clones = (ss or {}).get("clones", [])
        covering = [c for c in clones if c >= snapid]
        if covering:
            target = ObjectId(name, generation=min(covering))
            if self.store.exists(cid, target):
                return target
            return None
        # before the object's birth (created under a later snapc, or
        # resurrected after a whiteout): it did not exist at that snap
        if ss and snapid <= ss.get("born", 0):
            return None
        # newer than every clone: the head is the living state
        if self._head_whiteout(cid, name):
            return None
        return ObjectId(name)

    # ------------------------------------------------------- extended ops
    def _op_list_snaps(self, conn, m, pgid: PgId, up: list) -> None:
        cid = CollectionId(pgid.pool, pgid.seed)
        if not self.store.exists(cid, ObjectId(m.oid)):
            conn.send(MOSDOpReply(m.tid, ENOENT, epoch=self.osdmap.epoch))
            return
        ss = self._load_ss(cid, m.oid) or \
            {"seq": 0, "clones": [], "sz": {}, "ov": {}}
        out = dict(ss)
        out["head"] = not self._head_whiteout(cid, m.oid)
        conn.send(MOSDOpReply(m.tid, 0, data=_pack(out),
                              epoch=self.osdmap.epoch))

    def _op_snap_rollback(self, conn, m, pgid: PgId, up: list) -> None:
        """Roll the head back to its state at snapid (the rados rollback
        op: PrimaryLogPG _rollback_to)."""
        key = (pgid, m.oid)

        def thunk(conn=conn, m=m, pgid=pgid, key=key):
            up2 = self.osdmap.pg_to_up_osds(pgid.pool, pgid.seed)
            self._do_snap_rollback(conn, m, pgid, up2, lock_key=key)

        self._obj_lock(key, thunk)

    def _do_snap_rollback(self, conn, m, pgid: PgId, up: list,
                          lock_key) -> None:
        cid = CollectionId(pgid.pool, pgid.seed)
        name = m.oid
        # rollback is a head WRITE: it goes through make_writeable, so
        # head state owed to a newer snapshot gets its clone first
        snap_tx, rider = self._snap_prepare(pgid, m)
        ss = (_unpack(bytes(rider["ss"])) if rider is not None
              else self._load_ss(cid, name)) or \
            {"seq": 0, "clones": [], "sz": {}, "ov": {}}
        covering = [c for c in ss["clones"] if c >= m.snapid]
        if not covering:
            # head already IS the state at snapid (or nothing exists)
            code = 0 if (self.store.exists(cid, ObjectId(name))
                         and not self._head_whiteout(cid, name)) else ENOENT
            conn.send(MOSDOpReply(m.tid, code, epoch=self.osdmap.epoch))
            self._obj_unlock(lock_key)
            return
        cloneid = min(covering)
        version = self._next_version(pgid)
        ss_b = _pack(ss)
        self._apply_snap_rollback(pgid, name, cloneid, ss_b, version,
                                  pre_tx=snap_tx)
        peers = [u for u in up if u is not None and u != self.osd_id]
        if not peers:
            conn.send(MOSDOpReply(m.tid, 0, version=version,
                                  epoch=self.osdmap.epoch))
            self._obj_unlock(lock_key)
            return
        tid = next(self._tids)
        from .daemon import _PendingWrite
        pw = _PendingWrite(m.client, m.tid, len(peers), version)
        pw.lock_key = lock_key
        self._pending_writes[tid] = pw
        payload = _pack({"cloneid": cloneid, "ss": ss_b,
                         "rider": rider})
        for peer in peers:
            self.messenger.send_message(
                f"osd.{peer}",
                MSubWrite(tid, pgid, name, -1, version, "snap_rollback",
                          payload))

    def _apply_snap_rollback(self, pgid: PgId, name: str, cloneid: int,
                             ss_b: bytes, version: int,
                             pre_tx: Transaction | None = None) -> None:
        cid = CollectionId(pgid.pool, pgid.seed)
        head, clone = ObjectId(name), ObjectId(name, generation=cloneid)
        if not self.store.exists(cid, clone):
            return
        tx = Transaction()
        if pre_tx is not None:  # make_writeable clone of the current head
            tx.append(pre_tx)
        data = self.store.read(cid, clone).to_bytes()
        if self.store.exists(cid, head):
            tx.remove(cid, head)
        tx.clone(cid, clone, head)
        # the clone's copied attrs carry a STALE SnapSet and version:
        # restamp with the live ones (and clear any whiteout)
        tx.setattrs(cid, head, {"ss": ss_b, "v": version, "wh": 0,
                                "len": len(data), "d": _crc32c(data)})
        self._log_apply(tx, pgid, LogEntry(version, "write", name, -1,
                                           prev_version=-1))
        self.store.queue_transaction(tx)

    # ----------------------------------------------------------- whiteout
    def _apply_whiteout(self, pgid: PgId, name: str, version: int,
                        pre_tx: Transaction | None = None) -> None:
        """Delete a head that has clones: the object becomes a zero-size
        whiteout so the SnapSet survives (the snapdir role)."""
        cid = CollectionId(pgid.pool, pgid.seed)
        head = ObjectId(name)
        tx = Transaction()
        if pre_tx is not None:
            tx.append(pre_tx)
        if not self.store.exists(cid, head):
            return
        tx.truncate(cid, head, 0)
        tx.setattrs(cid, head, {"wh": 1, "v": version, "len": 0,
                                "d": _crc32c(b"")})
        self._log_apply(tx, pgid, LogEntry(version, "write", name, -1,
                                           prev_version=-1))
        self.store.queue_transaction(tx)

    # ---------------------------------------------------------- trimming
    def _snap_trim_check(self) -> None:
        """After a map update: trim clones of newly removed snaps on
        every PG this OSD leads (SnapTrimmer role; idempotent)."""
        if self.osdmap is None:
            return
        for pool in list(self.osdmap.pools.values()):
            for snapid in pool.removed_snaps:
                self._trim_snap(pool, snapid)

    def _trim_snap(self, pool, snapid: int) -> None:
        for seed in range(pool.pg_num):
            up = self.osdmap.pg_to_up_osds(pool.pool_id, seed)
            if self._primary_of(up) != self.osd_id:
                continue
            key = (pool.pool_id, seed, snapid)
            if key in self._trimmed_snaps:
                continue
            self._trimmed_snaps.add(key)
            pgid = PgId(pool.pool_id, seed)
            cid = CollectionId(pool.pool_id, seed)
            try:
                smap = self.store.omap_get(cid, self._smap_oid())
            except (NoSuchObject, NoSuchCollection):
                continue
            prefix = f"{snapid:016x}."
            for k in sorted(smap):
                if not k.startswith(prefix):
                    continue
                name = k[len(prefix):]
                key = (pgid, name)

                def thunk(name=name, pgid=pgid, snapid=snapid, key=key):
                    try:
                        self._trim_one(pgid, name, snapid)
                    finally:
                        self._obj_unlock(key)

                self._obj_lock(key, thunk)

    def _trim_one(self, pgid: PgId, name: str, snapid: int) -> None:
        cid = CollectionId(pgid.pool, pgid.seed)
        version = self._next_version(pgid)
        ss = self._load_ss(cid, name) or \
            {"seq": 0, "clones": [], "sz": {}, "ov": {}}
        ss["clones"] = [c for c in ss["clones"] if c != snapid]
        ss["sz"].pop(snapid, None)
        ss["ov"].pop(snapid, None)
        drop_head = (not ss["clones"]
                     and self._head_whiteout(cid, name))
        ss_b = _pack(ss)
        self._apply_trim(pgid, name, snapid, ss_b, drop_head, version)
        up = self.osdmap.pg_to_up_osds(pgid.pool, pgid.seed)
        payload = _pack({"snapid": snapid, "ss": ss_b,
                         "drop_head": drop_head})
        tid = next(self._tids)
        for peer in up:
            if peer is not None and peer != self.osd_id:
                self.messenger.send_message(
                    f"osd.{peer}",
                    MSubWrite(tid, pgid, name, -1, version, "trim_clone",
                              payload))
        self.perf.inc("snap_trims")

    def _apply_trim(self, pgid: PgId, name: str, snapid: int, ss_b: bytes,
                    drop_head: bool, version: int) -> None:
        cid = CollectionId(pgid.pool, pgid.seed)
        clone = ObjectId(name, generation=snapid)
        tx = Transaction()
        if self.store.exists(cid, clone):
            tx.remove(cid, clone)
        if self.store.exists(cid, ObjectId(name)):
            if drop_head:
                tx.remove(cid, ObjectId(name))
            else:
                tx.setattrs(cid, ObjectId(name), {"ss": ss_b})
        try:
            if f"{snapid:016x}.{name}" in self.store.omap_get(
                    cid, self._smap_oid()):
                tx.omap_rmkeys(cid, self._smap_oid(),
                               [f"{snapid:016x}.{name}"])
        except (NoSuchObject, NoSuchCollection):
            pass
        self._log_apply(tx, pgid, LogEntry(
            version, "remove", vname(name, snapid), -1, prev_version=-1))
        if not tx.empty():
            self.store.queue_transaction(tx)
        self._record_tombstone(pgid, vname(name, snapid), version)
        if drop_head:
            self._record_tombstone(pgid, name, version)
