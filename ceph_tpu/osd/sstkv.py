"""SstKV: a leveled LSM / SSTable KeyValueDB backend with background
maintenance.

The capability of the reference's RocksDBStore tier (src/kv/
RocksDBStore.cc over RocksDB's LSM): writes land in a WAL-backed
memtable; a full memtable SEALS into an immutable memtable (writes
continue into a fresh one + a fresh WAL segment) and a background
flush thread writes it to an L0 sorted-table file, retiring the sealed
segment; a background compaction thread streams L0 files (overlapping,
newest-first) into non-overlapping L1/L2/... runs with a k-way heap
merge (O(block) RAM, never O(level)); reads resolve against an
atomically-swapped ``(memtables, levels)`` snapshot — they never block
behind a flush or merge — consulting memtable -> sealed memtables ->
L0 newest->oldest -> one file per deeper level, each gated by a bloom
filter, located via a sparse block index and served through a shared
byte-budgeted LRU block cache; tombstones shadow older values and drop
when a compaction reaches the bottom level.

Backpressure (the RocksDB slowdown/stop write-stall roles): when
maintenance falls behind (sealed memtables or L0 files past their
thresholds) writers first pace (brief counted sleeps), then STALL
until the flush/compaction threads catch up — ``kv_stall_us`` +
per-cause counters on the ``kv.<store>`` registry.  Inside the store
commit pipeline the stall lands on the kv-sync thread, which keeps
the commit queue full, which blocks the admission throttle — the
backpressure chain stays honest end to end instead of an unbounded
inline merge under the store lock.

File format (sst_NNNNNNNN.sst — UNCHANGED on disk):
    [records: u32 klen | key | u8 tomb | u32 vlen | value]*
    [bloom bits]
    [index: u32 n | (u32 koff_len | first_key | u64 file_off)*]
    [footer: u64 bloom_off | u64 index_off | u32 n_records |
             u32 crc32c(bloom..index) | magic "SSTB"]

The MANIFEST (levels layout + next file seq) rewrites atomically via
tmp+rename; memtable WAL segments (wal_NNNNNNNN.log, one per
memtable) use the store family's crc-framed fsync'd record contract
with torn-tail discard.  Crash contract: a segment is unlinked only
AFTER its memtable's L0 file is in a durable manifest, and a dead
SST is unlinked only AFTER the manifest that drops it — the crash
windows leave either a replayable segment or an orphan file, and
open-time GC removes any ``sst_*.sst`` absent from the manifest
(after WAL replay).  Composite keys are ``prefix \\x00 key`` so
per-prefix iteration is a contiguous range.
"""

from __future__ import annotations

import bisect
import hashlib
import itertools
import os
import struct
import threading
import time
import weakref
from collections import OrderedDict

from ..ops.native import crc32c
from ..utils.codec import Decoder, Encoder
from ..utils.perf import PerfCounters, global_perf
from .kvstore import KeyValueDB, KVTransaction, resolve_kv_perf

_MAGIC = b"SSTB"
_TOMB = 1
_BLOCK = 4096              # sparse-index granularity (bytes of records)
_REC_TX = 1


def _ckey(prefix: str, key: str) -> bytes:
    return prefix.encode() + b"\x00" + key.encode()


def _split(ck: bytes) -> tuple[str, str]:
    p, _, k = ck.partition(b"\x00")
    return p.decode(), k.decode()


class _Bloom:
    """Fixed-k bloom filter (BloomFilterPolicy role): ~10 bits/key."""

    K = 3

    def __init__(self, bits: bytearray):
        self.bits = bits

    @classmethod
    def build(cls, keys: list[bytes]) -> "_Bloom":
        m = max(64, 10 * len(keys))
        bits = bytearray((m + 7) // 8)
        bloom = cls(bits)
        for k in keys:
            for h in bloom._hashes(k):
                bits[h >> 3] |= 1 << (h & 7)
        return bloom

    def _hashes(self, key: bytes):
        m = len(self.bits) * 8
        for i in range(self.K):
            d = hashlib.blake2b(key, digest_size=8,
                                salt=bytes([i]) * 16).digest()
            yield int.from_bytes(d, "little") % m

    def maybe(self, key: bytes) -> bool:
        return all(self.bits[h >> 3] & (1 << (h & 7))
                   for h in self._hashes(key))


class BlockCache:
    """Byte-budgeted LRU over PARSED sst blocks, shared across every
    table of one store (the rocksdb shared block-cache role): a hot
    onode probe pays one dict move instead of a file read + reparse.
    Compaction scans bypass it — a merge touching a whole level must
    not evict the working set."""

    #: recently-invalidated table uids remembered so a reader holding
    #: a pre-compaction snapshot can't re-insert a dead table's blocks
    #: after invalidate() ran (they would pin budget unreachably);
    #: bounded — an ancient uid's race window is long past
    DEAD_KEEP = 1024

    def __init__(self, max_bytes: int, perf: PerfCounters | None = None):
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._map: OrderedDict[tuple, tuple[list, int]] = OrderedDict()
        self._dead: OrderedDict[int, None] = OrderedDict()
        self._bytes = 0
        self._perf = perf

    def _book(self, name: str, n: int = 1) -> None:
        if self._perf is not None:
            self._perf.inc(name, n)

    def lookup(self, key: tuple):
        with self._lock:
            hit = self._map.get(key)
            if hit is not None:
                self._map.move_to_end(key)
        if hit is not None:
            self._book("kv_cache_hit")
            return hit[0]
        self._book("kv_cache_miss")
        return None

    def insert(self, key: tuple, records: list) -> None:
        if self.max_bytes <= 0:
            return
        nbytes = 64 + sum(len(ck) + len(v) + 48 for ck, _t, v in records)
        evicted = 0
        with self._lock:
            if key[0] in self._dead:
                return  # the table died between lookup and insert
            old = self._map.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._map[key] = (records, nbytes)
            self._bytes += nbytes
            while self._bytes > self.max_bytes and len(self._map) > 1:
                _k, (_r, nb) = self._map.popitem(last=False)
                self._bytes -= nb
                evicted += 1
            nb_now = self._bytes
        if evicted:
            self._book("kv_cache_evict", evicted)
        if self._perf is not None:
            self._perf.set("kv_cache_bytes", nb_now)

    def invalidate(self, uid: int) -> None:
        self.invalidate_many((uid,))

    def invalidate_many(self, uids) -> None:
        """Drop every block of the (dead) tables and refuse late
        inserts for them (a reader racing this on an old snapshot) —
        ONE map scan however many tables died (a wide merge retiring
        a whole level must not hold the cache lock for N scans while
        foreground lookups contend)."""
        doomed = set(uids)
        if not doomed:
            return
        with self._lock:
            for uid in doomed:
                self._dead[uid] = None
            while len(self._dead) > self.DEAD_KEEP:
                self._dead.popitem(last=False)
            for key in [k for k in self._map if k[0] in doomed]:
                _r, nb = self._map.pop(key)
                self._bytes -= nb
            nb_now = self._bytes
        if self._perf is not None:
            self._perf.set("kv_cache_bytes", nb_now)

    def stats(self) -> dict:
        with self._lock:
            return {"bytes": self._bytes, "blocks": len(self._map),
                    "max_bytes": self.max_bytes}


def _parse_records(raw: bytes) -> list[tuple[bytes, int, bytes]]:
    """Decode one block's record run (always record-aligned: index
    entries are cut at record boundaries)."""
    out: list[tuple[bytes, int, bytes]] = []
    pos, n = 0, len(raw)
    while pos < n:
        (klen,) = struct.unpack_from("<I", raw, pos)
        pos += 4
        ck = raw[pos: pos + klen]
        pos += klen
        tomb, vlen = struct.unpack_from("<BI", raw, pos)
        pos += 5
        out.append((ck, tomb, raw[pos: pos + vlen]))
        pos += vlen
    return out


class _Sst:
    """One immutable sorted table: bloom + sparse index resident, data
    read block-at-a-time on demand (via the shared cache).  Reads go
    through ``os.pread`` on a lazily-(re)opened handle: process-wide
    at most ``MAX_OPEN`` LIVE tables keep their fd (an LRU evicts the
    rest — a store past ~1000 tables must not exhaust the fd rlimit;
    the next pread reopens by path).  A table whose path a compaction
    unlinked is PINNED first (``pin_fd``) — its fd is the only
    remaining route to the bytes, so an in-flight reader holding an
    old levels snapshot keeps working."""

    _ids = itertools.count(1)

    #: soft cap of simultaneously-open LIVE sst handles, process-wide
    MAX_OPEN = 512
    _open: "OrderedDict[int, weakref.ref]" = OrderedDict()
    _open_lock = threading.Lock()

    def __init__(self, path: str):
        self.path = path
        self.uid = next(_Sst._ids)
        self._flock = threading.Lock()
        self._pinned = False
        self._f = open(path, "rb")
        self._note_open()
        fd = self._f.fileno()
        end = os.fstat(fd).st_size
        footer = self._pread(28, end - 28)
        bloom_off, index_off, self.n, crc = struct.unpack(
            "<QQII", footer[:-4])
        if footer[-4:] != _MAGIC:
            raise IOError(f"{path}: bad sst magic")
        meta = self._pread(end - 28 - bloom_off, bloom_off)
        if crc32c(meta) != crc:
            raise IOError(f"{path}: sst meta crc mismatch")
        self.bloom = _Bloom(bytearray(meta[:index_off - bloom_off]))
        d = Decoder(meta[index_off - bloom_off:])
        self.index: list[tuple[bytes, int]] = []
        for _ in range(d.u32()):
            first = d.blob()
            off = d.u64()
            self.index.append((first, off))
        self._index_keys = [f for f, _off in self.index]
        self._data_end = bloom_off
        self.nbytes = end
        self.first = self.index[0][0] if self.index else b""
        self.last = self._last_key() if self.index else b""

    def _note_open(self) -> None:
        """Register this table's open handle in the process-wide LRU;
        past MAX_OPEN, close the least-recently-opened UNPINNED live
        handle (its next pread reopens by path — self-correcting for
        hot tables)."""
        with _Sst._open_lock:
            _Sst._open[self.uid] = weakref.ref(self)
            _Sst._open.move_to_end(self.uid)
            if len(_Sst._open) <= _Sst.MAX_OPEN:
                return
            for uid in list(_Sst._open):
                if len(_Sst._open) <= _Sst.MAX_OPEN:
                    break
                if uid == self.uid:
                    continue
                victim = _Sst._open[uid]()
                if victim is None:
                    del _Sst._open[uid]
                    continue
                if victim._pinned:
                    del _Sst._open[uid]  # exempt from the cap
                    continue
                if not victim._flock.acquire(blocking=False):
                    continue  # mid-pread: pick another victim
                try:
                    if victim._f is not None:
                        victim._f.close()
                        victim._f = None
                finally:
                    victim._flock.release()
                del _Sst._open[uid]

    def _drop_open(self) -> None:
        with _Sst._open_lock:
            _Sst._open.pop(self.uid, None)

    def _pread(self, n: int, off: int) -> bytes:
        reopened = False
        with self._flock:
            if self._f is None:  # evicted from the handle LRU
                self._f = open(self.path, "rb")
                reopened = True
            data = os.pread(self._f.fileno(), n, off)
        if reopened:
            self._note_open()
        return data

    def pin_fd(self) -> None:
        """Called by compaction BEFORE unlinking this (dead) table:
        ensure the handle is open and exempt it from the LRU cap —
        once the path is gone the fd is the only route to the bytes
        an in-flight reader snapshot may still need."""
        with self._flock:
            if self._f is None:
                self._f = open(self.path, "rb")
            self._pinned = True
        self._drop_open()

    def close(self) -> None:
        self._drop_open()
        with self._flock:
            try:
                if self._f is not None:
                    self._f.close()
            except OSError:  # pragma: no cover
                pass
            self._f = None  # a later _pread reopens by path

    def __del__(self):  # a compacted-away table closes when the last
        try:            # reader snapshot referencing it is dropped
            self._drop_open()
            if self._f is not None:
                self._f.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass

    def _block_span(self, bi: int) -> tuple[int, int]:
        off = self.index[bi][1]
        end = (self.index[bi + 1][1] if bi + 1 < len(self.index)
               else self._data_end)
        return off, end

    def _load_block(self, bi: int) -> list[tuple[bytes, int, bytes]]:
        off, end = self._block_span(bi)
        return _parse_records(self._pread(end - off, off))

    def _block(self, bi: int,
               cache: BlockCache | None) -> list[tuple[bytes, int, bytes]]:
        if cache is None:
            return self._load_block(bi)
        key = (self.uid, bi)
        records = cache.lookup(key)
        if records is None:
            records = self._load_block(bi)
            cache.insert(key, records)
        return records

    def _last_key(self) -> bytes:
        return self._load_block(len(self.index) - 1)[-1][0]

    def _block_for(self, ck: bytes) -> int:
        """Index of the block that could hold ck (the last block whose
        first key is <= ck; 0 when ck precedes everything)."""
        return max(0, bisect.bisect_right(self._index_keys, ck) - 1)

    @staticmethod
    def write(path: str, items: list[tuple[bytes, int, bytes]]) -> "_Sst":
        """items: sorted (composite_key, tomb, value)."""
        tmp = path + ".tmp"
        index: list[tuple[bytes, int]] = []
        with open(tmp, "wb") as f:
            block_start = 0
            for ck, tomb, val in items:
                off = f.tell()
                if off == 0 or off - block_start >= _BLOCK:
                    index.append((ck, off))
                    block_start = off
                f.write(struct.pack("<I", len(ck)))
                f.write(ck)
                f.write(struct.pack("<BI", tomb, len(val)))
                f.write(val)
            bloom_off = f.tell()
            bloom = _Bloom.build([ck for ck, _t, _v in items])
            e = Encoder()
            e.u32(len(index))
            for first, off in index:
                e.blob(first)
                e.u64(off)
            meta = bytes(bloom.bits) + e.tobytes()
            f.write(meta)
            f.write(struct.pack("<QQII", bloom_off,
                                bloom_off + len(bloom.bits),
                                len(items), crc32c(meta)))
            f.write(_MAGIC)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return _Sst(path)

    def scan(self, start_ck: bytes = b"", stop_ck: bytes | None = None,
             cache: BlockCache | None = None):
        """Yield (ck, tomb, value) for start_ck <= ck < stop_ck —
        streaming block by block (bounded memory: one parsed block at
        a time, never the whole file)."""
        if not self.index:
            return
        for bi in range(self._block_for(start_ck), len(self.index)):
            if stop_ck is not None and self.index[bi][0] >= stop_ck:
                return
            for rec in self._block(bi, cache):
                ck = rec[0]
                if stop_ck is not None and ck >= stop_ck:
                    return
                if ck >= start_ck:
                    yield rec

    def get(self, ck: bytes, cache: BlockCache | None = None):
        """(tomb, value) or None."""
        if not (self.first <= ck <= self.last) or not self.bloom.maybe(ck):
            return None
        records = self._block(self._block_for(ck), cache)
        i = bisect.bisect_left(records, (ck,))
        if i < len(records) and records[i][0] == ck:
            return records[i][1], records[i][2]
        return None


class _State:
    """One atomically-published read snapshot: sealed memtables
    (newest first, frozen dicts) + the level lists (immutable tuples,
    levels[0] newest-first).  Readers load ``store._state`` ONCE and
    resolve against it — a flush or merge publishing a new snapshot
    never blocks them."""

    __slots__ = ("imm", "levels")

    def __init__(self, imm: tuple = (), levels: tuple = ((),)):
        self.imm = imm
        self.levels = levels


def _merge_streams(sources: list):
    """K-way heap merge, newest-wins: ``sources`` are iterators of
    sorted (ck, tomb, value) runs ordered newest FIRST — for equal
    keys the lowest source index pops first (tuple order) and later
    duplicates are skipped.  Streaming: O(k) heap entries resident."""
    import heapq
    heap: list[tuple[bytes, int, tuple, object]] = []
    for si, it in enumerate(sources):
        first = next(it, None)
        if first is not None:
            heap.append((first[0], si, first, it))
    heapq.heapify(heap)
    prev: bytes | None = None
    while heap:
        ck, si, item, it = heapq.heappop(heap)
        nxt = next(it, None)
        if nxt is not None:
            heapq.heappush(heap, (nxt[0], si, nxt, it))
        if ck == prev:
            continue  # an older source's shadowed duplicate
        prev = ck
        yield item


class SstKV(KeyValueDB):
    L0_COMPACT_FILES = 4
    LEVEL_BASE_BYTES = 1 << 20      # L1 target; 10x per deeper level
    LEVEL_FANOUT = 10
    SST_SPLIT_BYTES = 1 << 20       # split compaction output files

    # write-stall thresholds (rocksdb slowdown/stop roles, instance
    # attrs so tests/benches can tune them per store)
    STALL_IMM_SLOWDOWN = 2          # sealed memtables pending: pace
    STALL_IMM_STOP = 4              # ...and stop
    STALL_L0_SLOWDOWN = 8           # L0 files: pace
    STALL_L0_STOP = 16              # ...and stop
    SLOWDOWN_SLEEP_S = 0.002        # per-write pacing delay

    #: crash-test points: names in this class-level set call
    #: ``os._exit(3)`` when reached (subprocess kill tests inject the
    #: PR-14-style mid-maintenance crashes deterministically)
    CRASH_POINTS: frozenset = frozenset()

    def __init__(self, path: str, memtable_bytes: int = 256 * 1024, *,
                 background: bool = True, cache_bytes: int = 8 << 20,
                 name: str | None = None,
                 perf: PerfCounters | None = None):
        os.makedirs(path, exist_ok=True)
        self._dir = path
        self._memtable_bytes = memtable_bytes
        self.background = bool(background)
        self.perf, self._owns_perf = resolve_kv_perf(name, perf)
        self._perf_name = f"kv.{name}" if name is not None else None
        self.cache = BlockCache(cache_bytes, self.perf)
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        self._mem: dict[bytes, tuple[int, bytes]] = {}  # ck->(tomb,val)
        self._mem_size = 0
        self._state = _State()
        #: sealed (memtable, wal_path) pairs OLDEST first — the flush
        #: thread's work queue; _state.imm mirrors it newest-first
        self._imm_meta: list[tuple[dict, str]] = []
        self._seq = 0
        self._wal_seq = 0
        self._manifest = os.path.join(path, "MANIFEST")
        #: manifest writes happen OUTSIDE the store lock (an fsync
        #: under the cv would stall every submitter behind the
        #: publish) — the pub_seq taken at the state swap orders them
        #: so a slower writer can never clobber a newer manifest
        self._manifest_mutex = threading.Lock()
        self._pub_seq = 0               # under the cv, at state swap
        self._manifest_written = 0      # under _manifest_mutex
        self._stopping = False
        #: set by close() AFTER the thread joins (even timed-out
        #: ones): a maintenance publish that lost the race must NOT
        #: rewrite the manifest from the emptied state — its outputs
        #: become orphans open-time GC removes, its WAL segment stays
        #: replayable
        self._closed = False
        #: maintenance passes in flight (flush/compact from pick to
        #: epilogue INCLUDING dead-file unlinks + counters) — a
        #: publish makes _pick return None before the epilogue runs,
        #: so wait_maintenance_idle needs this to not return early
        self._maint_busy = 0
        self._failed: BaseException | None = None
        self._compact_kick = False
        #: test seams: point-name -> callable, invoked at the same
        #: spots as CRASH_POINTS (wedge a flush to force stalls, etc.)
        self.test_hooks: dict = {}
        self._load_manifest()
        self._gc_stale_tmp()
        self._open_recover()
        self._flush_thread: threading.Thread | None = None
        self._compact_thread: threading.Thread | None = None
        if self.background:
            self._flush_thread = threading.Thread(
                target=self._flush_loop, daemon=True,
                name=f"kv-flush-{name or 'sst'}")
            self._compact_thread = threading.Thread(
                target=self._compact_loop, daemon=True,
                name=f"kv-compact-{name or 'sst'}")
            self._flush_thread.start()
            self._compact_thread.start()
        # a freshly-opened store may already be over a compaction
        # threshold (e.g. crash-recovery flushes piled L0 files)
        with self._cv:
            self._signal_compact_locked()

    # ------------------------------------------------------- manifest/wal
    def _load_manifest(self) -> None:
        if not os.path.exists(self._manifest):
            return
        with open(self._manifest, "rb") as f:
            d = Decoder(f.read())
        self._seq = d.u64()
        levels = []
        for _ in range(d.u32()):
            names = [d.string() for _ in range(d.u32())]
            levels.append(tuple(_Sst(os.path.join(self._dir, n))
                                for n in names))
        self._state = _State(levels=tuple(levels) or ((),))

    def _publish_state_locked(self) -> tuple:
        """Caller holds the cv and just swapped ``self._state``: take
        the (state, file_seq, pub_seq) snapshot ``_write_manifest``
        persists OUTSIDE the lock."""
        self._pub_seq += 1
        return self._state, self._seq, self._pub_seq

    def _write_manifest(self, state: _State, file_seq: int,
                        pub_seq: int) -> None:
        """Durably persist one published state (no store lock held —
        submitters never wait on this fsync).  Concurrent publishers
        serialize on the manifest mutex; a writer that lost the race
        to a NEWER publish skips (the newer manifest already covers
        its swap, so its own unlinks stay safe)."""
        with self._manifest_mutex:
            if pub_seq <= self._manifest_written:
                return
            e = Encoder()
            e.u64(file_seq)
            levels = state.levels
            e.u32(len(levels))
            for level in levels:
                e.u32(len(level))
                for sst in level:
                    e.string(os.path.basename(sst.path))
            tmp = self._manifest + ".tmp"
            with open(tmp, "wb") as f:
                f.write(e.tobytes())
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._manifest)
            self._manifest_written = pub_seq

    def _gc_stale_tmp(self) -> None:
        for fn in os.listdir(self._dir):
            if fn.endswith(".tmp"):
                try:
                    os.remove(os.path.join(self._dir, fn))
                except OSError:  # pragma: no cover
                    pass

    def _gc_orphans(self) -> None:
        """Remove sst files present on disk but absent from the
        manifest — the crash window between an sst write and its
        manifest (flush/compaction output) or between a manifest and
        its dead-file unlinks leaks files forever otherwise."""
        live = {os.path.basename(s.path)
                for lvl in self._state.levels for s in lvl}
        for fn in os.listdir(self._dir):
            if not (fn.startswith("sst_") and fn.endswith(".sst")):
                continue
            # keep the seq monotone past every file ever created, so a
            # new file can never collide with a just-GC'd orphan name
            try:
                self._seq = max(self._seq, int(fn[4:-4]))
            except ValueError:  # pragma: no cover - foreign file
                continue
            if fn not in live:
                try:
                    os.remove(os.path.join(self._dir, fn))
                except OSError:  # pragma: no cover
                    pass

    def _wal_segments(self) -> list[str]:
        """On-disk WAL segments, oldest first (the legacy single-file
        name sorts before the numbered segments it predates)."""
        segs = sorted(fn for fn in os.listdir(self._dir)
                      if fn.startswith("wal_") and fn.endswith(".log"))
        legacy = os.path.join(self._dir, "memtable.wal")
        out = [legacy] if os.path.exists(legacy) else []
        for fn in segs:
            try:
                self._wal_seq = max(self._wal_seq, int(fn[4:-4]))
            except ValueError:  # pragma: no cover
                continue
            out.append(os.path.join(self._dir, fn))
        return out

    def _open_recover(self) -> None:
        """Open-time recovery: replay every WAL segment (oldest first)
        into the memtable, GC orphaned ssts, then — if anything
        replayed — flush straight to L0 (the durability point for
        those keys moves into the manifest) and retire ALL segments,
        starting from a clean slate."""
        segs = self._wal_segments()
        for seg in segs:
            self._replay_segment(seg)
        self._gc_orphans()
        if self._mem:
            items = [(ck, t, v)
                     for ck, (t, v) in sorted(self._mem.items())]
            sst = _Sst.write(
                os.path.join(self._dir, self._next_name_locked()), items)
            lv0 = (sst,) + self._state.levels[0]
            self._state = _State(levels=(lv0,) + self._state.levels[1:])
            self._write_manifest(*self._publish_state_locked())
            self.perf.inc("kv_flush")
            self._mem = {}
            self._mem_size = 0
        for seg in segs:
            try:
                os.remove(seg)
            except OSError:  # pragma: no cover
                pass
        self._wal_seq += 1
        self._wal_path = os.path.join(self._dir,
                                      f"wal_{self._wal_seq:08d}.log")
        self._wal = open(self._wal_path, "ab")
        self._set_gauges_locked()

    def _replay_segment(self, path: str) -> None:
        with open(path, "rb") as f:
            raw = f.read()
        pos = 0
        while pos + 8 <= len(raw):
            length, crc = struct.unpack_from("<II", raw, pos)
            payload = raw[pos + 8: pos + 8 + length]
            if len(payload) < length or crc32c(payload) != crc:
                break  # torn tail
            d = Decoder(payload)
            if d.u8() == _REC_TX:
                for _ in range(d.u32()):
                    ck, tomb, val = d.blob(), d.u8(), d.blob()
                    self._mem_put(ck, tomb, val)
            pos += 8 + length
        if pos < len(raw):
            with open(path, "r+b") as f:
                f.truncate(pos)

    def _mem_put(self, ck: bytes, tomb: int, val: bytes) -> None:
        old = self._mem.get(ck)
        if old is not None:
            self._mem_size -= len(ck) + len(old[1])
        self._mem[ck] = (tomb, val)
        self._mem_size += len(ck) + len(val)

    def _crashpoint(self, point: str) -> None:
        hook = self.test_hooks.get(point)
        if hook is not None:
            hook()
        if point in self.CRASH_POINTS:
            os._exit(3)

    def _set_gauges_locked(self) -> None:
        self.perf.set("kv_imm_memtables", len(self._state.imm))
        self.perf.set("kv_l0_files", len(self._state.levels[0]))

    # ----------------------------------------------------------------- api
    def _check_failed_locked(self) -> None:
        if self._failed is not None:
            raise IOError(f"kv maintenance failed: {self._failed!r}")

    def _stall_locked(self) -> None:
        """Write-stall backpressure: pace (slowdown) then block (stop)
        the writer while background maintenance is behind.  Inline
        mode never stalls — maintenance runs in the write itself."""
        if not self.background:
            return
        st = self._state
        n_imm, n_l0 = len(st.imm), len(st.levels[0])
        if n_imm < self.STALL_IMM_SLOWDOWN \
                and n_l0 < self.STALL_L0_SLOWDOWN:
            return
        t0 = time.monotonic()
        if n_imm >= self.STALL_IMM_STOP or n_l0 >= self.STALL_L0_STOP:
            self.perf.inc("kv_stall_memtable"
                          if n_imm >= self.STALL_IMM_STOP
                          else "kv_stall_l0")
            while (self._failed is None and not self._stopping):
                st = self._state
                if len(st.imm) < self.STALL_IMM_STOP \
                        and len(st.levels[0]) < self.STALL_L0_STOP:
                    break
                self._cv.wait(0.5)
            self._check_failed_locked()
        else:
            self.perf.inc("kv_slowdown")
            self._cv.wait(self.SLOWDOWN_SLEEP_S)  # releases the lock
        self.perf.hinc("kv_stall_us", (time.monotonic() - t0) * 1e6)

    def submit(self, tx: KVTransaction, sync: bool = True) -> None:
        with self._cv:
            self._check_failed_locked()
            self._stall_locked()
            if self._stopping or self._wal is None:
                # a writer that was stalled when close() landed (or a
                # straggler submitting after it) must fail cleanly,
                # not dereference the torn-down WAL
                raise IOError("kv store is closing")
            flat: list[tuple[bytes, int, bytes]] = []
            for op, prefix, key, val in tx.ops:
                if op == "put":
                    flat.append((_ckey(prefix, key), 0, val))
                elif op == "rm":
                    flat.append((_ckey(prefix, key), _TOMB, b""))
                else:  # rm_prefix: tombstone every live key in range —
                    # including keys PUT earlier in this same tx
                    # (KVTransaction ops apply in order, as MemKV does).
                    # The doom scan bypasses the block cache: it reads
                    # blocks that are about to be tombstoned — they
                    # must not evict the hot read working set (same
                    # policy as compaction scans)
                    doomed = {_ckey(prefix, k)
                              for k, _v in self._iterate(prefix, "",
                                                         None)}
                    pfx = prefix.encode() + b"\x00"
                    doomed |= {ck for ck, t, _v in flat
                               if ck.startswith(pfx) and not t}
                    flat.extend((ck, _TOMB, b"") for ck in sorted(doomed))
            e = Encoder()
            e.u8(_REC_TX)
            e.u32(len(flat))
            for ck, tomb, val in flat:
                e.blob(ck)
                e.u8(tomb)
                e.blob(val)
            payload = e.tobytes()
            self._wal.write(struct.pack("<II", len(payload),
                                        crc32c(payload)) + payload)
            if sync:
                self._wal.flush()
                os.fsync(self._wal.fileno())
            # lock-free readers must never observe HALF a transaction
            # (e.g. a put already applied but the same tx's rm_prefix
            # tombstone not yet): collapse the tx to its final image
            # and apply it as ONE dict.update — a single C-level call
            # over bytes keys, so CPython readers see pre-tx or
            # post-tx, never in between
            patch: dict[bytes, tuple[int, bytes]] = {}
            for ck, tomb, val in flat:
                patch[ck] = (tomb, val)
            for ck, tv in patch.items():
                old = self._mem.get(ck)
                if old is not None:
                    self._mem_size -= len(ck) + len(old[1])
                self._mem_size += len(ck) + len(tv[1])
            self._mem.update(patch)
            if self._mem_size >= self._memtable_bytes:
                self._seal_locked()

    def sync(self) -> None:
        with self._lock:
            if self._wal is not None:
                self._wal.flush()
                os.fsync(self._wal.fileno())

    def get(self, prefix: str, key: str) -> bytes | None:
        ck = _ckey(prefix, key)
        # lock-free read: load the active memtable FIRST, the snapshot
        # second.  A seal publishes the new snapshot (sealed memtable
        # in imm) BEFORE swapping the active dict, so a reader that
        # sees the fresh (empty) active table is guaranteed to see the
        # sealed one in the snapshot it loads after.
        mem = self._mem
        state = self._state
        hit = mem.get(ck)
        if hit is not None:
            return None if hit[0] else hit[1]
        for imm in state.imm:                  # sealed, newest first
            hit = imm.get(ck)
            if hit is not None:
                return None if hit[0] else hit[1]
        for sst in state.levels[0]:            # L0 newest-first
            hit = sst.get(ck, self.cache)
            if hit is not None:
                return None if hit[0] else hit[1]
        for level in state.levels[1:]:         # non-overlapping
            for sst in level:
                if sst.first <= ck <= sst.last:
                    hit = sst.get(ck, self.cache)
                    if hit is not None:
                        return None if hit[0] else hit[1]
                    break
        return None

    def iterate(self, prefix: str, start: str = ""):
        """Merged newest-wins iteration over memtable + sealed
        memtables + every level — a streaming k-way heap merge over
        the read snapshot (lock held only to copy the active
        memtable's range; sst runs decode one block at a time)."""
        return self._iterate(prefix, start, self.cache)

    def _iterate(self, prefix: str, start: str,
                 cache: BlockCache | None):
        lo = _ckey(prefix, start)
        hi = prefix.encode() + b"\x01"  # end of the prefix's range
        with self._lock:
            mem_items = [(ck, tv[0], tv[1])
                         for ck, tv in sorted(self._mem.items())
                         if lo <= ck < hi]
            state = self._state
        sources = [iter(mem_items)]
        for imm in state.imm:  # frozen after seal: safe unlocked
            sources.append(iter(sorted(
                (ck, tv[0], tv[1]) for ck, tv in imm.items()
                if lo <= ck < hi)))
        for sst in state.levels[0]:
            sources.append(sst.scan(lo, hi, cache))
        for level in state.levels[1:]:
            run = [s for s in level
                   if not (s.last < lo or s.first >= hi)]
            if run:  # non-overlapping: chain is one sorted stream
                sources.append(itertools.chain.from_iterable(
                    s.scan(lo, hi, cache) for s in run))
        for ck, tomb, val in _merge_streams(sources):
            if not tomb:
                yield _split(ck)[1], val

    def wait_maintenance_idle(self, timeout: float = 30.0) -> bool:
        """Block until background maintenance has drained — no sealed
        memtable pending flush, no level over its compaction trigger,
        and no pass still in its post-publish epilogue (unlinks +
        counters land AFTER the manifest publish that flips _pick).
        A bench/test quiesce point (NOT part of the write path): the
        flush/compaction threads notify the cv on every transition."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while (self._imm_meta or self._maint_busy
                   or (self.background
                       and self._pick_compaction_locked() is not None)):
                if self._failed is not None or self._stopping:
                    return False
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(min(left, 0.05))
            return True

    def close(self) -> None:
        with self._cv:
            self._stopping = True
            self._cv.notify_all()
        for t in (self._flush_thread, self._compact_thread):
            if t is not None:
                t.join(timeout=30)
        with self._lock:
            # even after a TIMED-OUT join: a still-running maintenance
            # publish checks this under the lock and aborts instead of
            # rewriting the manifest from the emptied state below
            self._closed = True
            if self._wal:
                self._wal.close()
                self._wal = None
            # NOT s.close(): a lock-free reader may still be mid-pread
            # on a table (get()/iterate() take no lock by design) — the
            # same policy compaction applies to dead tables.  Dropping
            # the store's snapshot reference closes each fd when the
            # last reader's reference drops (_Sst.__del__); reads that
            # START after close see the empty snapshot.
            self._state = _State()
        if self._owns_perf and self._perf_name:
            global_perf().remove(self._perf_name)

    # ------------------------------------------------------ seal + flush
    def _next_name_locked(self) -> str:
        self._seq += 1
        return f"sst_{self._seq:08d}.sst"

    def _seal_locked(self) -> None:
        """Full memtable -> immutable memtable: fsync + close the
        active WAL segment (its bytes may be the ONLY copy of unsynced
        submits), hand the pair to the flush thread, and continue into
        a fresh memtable + fresh segment.  Inline mode flushes (and
        compacts) right here instead — the pre-background behavior."""
        if not self._mem:
            return
        self._wal.flush()
        os.fsync(self._wal.fileno())
        self._wal.close()
        sealed, sealed_path = self._mem, self._wal_path
        self._imm_meta.append((sealed, sealed_path))
        self._state = _State(imm=(sealed,) + self._state.imm,
                             levels=self._state.levels)
        self._mem = {}
        self._mem_size = 0
        self._wal_seq += 1
        self._wal_path = os.path.join(self._dir,
                                      f"wal_{self._wal_seq:08d}.log")
        self._wal = open(self._wal_path, "ab")
        self._set_gauges_locked()
        if self.background:
            self._cv.notify_all()  # wake the flush thread
            return
        # inline maintenance (background=off): the caller's thread
        # pays the flush — and any cascading compaction — right now,
        # booked as kv_*_inline (the cliff the background seam removes)
        self._flush_one(sealed, sealed_path)
        self.perf.inc("kv_flush_inline")
        while True:
            ln = self._pick_compaction_locked()
            if ln is None:
                break
            self._compact_level(ln)
            self.perf.inc("kv_compact_inline")

    def _flush_loop(self) -> None:
        while True:
            with self._cv:
                while not self._imm_meta and not self._stopping:
                    self._cv.wait()
                if self._closed or not self._imm_meta:
                    return  # store torn down / stopping and drained
                mem, wal_path = self._imm_meta[0]  # oldest; popped by
                #                                    _flush_one's publish
                self._maint_busy += 1
            try:
                self._flush_one(mem, wal_path)
            except BaseException as e:  # noqa: BLE001 - disk fail: the
                # store poisons (submit raises; the commit pipeline
                # poisons in turn) rather than silently losing the seal
                with self._cv:
                    self._failed = e
                    self._cv.notify_all()
                from ..utils.log import dout
                dout("kv", 0)("kv flush FAILED (store poisoned): %r", e)
                return
            finally:
                with self._cv:
                    self._maint_busy -= 1
                    self._cv.notify_all()

    def _flush_one(self, mem: dict, wal_path: str) -> None:
        """Write one sealed memtable to L0 (no lock: the memtable is
        frozen), publish manifest+levels under a short critical
        section, then retire the WAL segment (strictly after the
        manifest that makes its contents reachable is durable)."""
        t0 = time.monotonic()
        items = [(ck, t, v) for ck, (t, v) in sorted(mem.items())]
        with self._cv:
            name = self._next_name_locked()
        sst = _Sst.write(os.path.join(self._dir, name), items)
        self._crashpoint("flush.pre_manifest")
        with self._cv:
            if self._closed:
                # close() won the race (timed-out join): publishing
                # now would rewrite the manifest from the emptied
                # state and orphan every live sst.  The new file is
                # the orphan instead; the WAL segment stays for replay
                return
            lv0 = (sst,) + self._state.levels[0]
            imm = tuple(m for m in self._state.imm if m is not mem)
            self._state = _State(imm=imm,
                                 levels=(lv0,) + self._state.levels[1:])
            self._imm_meta = [p for p in self._imm_meta
                              if p[0] is not mem]
            pub = self._publish_state_locked()
            self._set_gauges_locked()
            self._signal_compact_locked()
            self._cv.notify_all()  # stalled writers re-check
        self._write_manifest(*pub)
        self._crashpoint("flush.pre_wal_unlink")
        try:
            os.remove(wal_path)
        except OSError:  # pragma: no cover
            pass
        self.perf.inc("kv_flush")
        self.perf.hinc("kv_flush_us", (time.monotonic() - t0) * 1e6)

    # ---------------------------------------------------------- compaction
    def _pick_compaction_locked(self):
        levels = self._state.levels
        if len(levels[0]) > self.L0_COMPACT_FILES:
            return 0
        limit = self.LEVEL_BASE_BYTES
        for ln in range(1, len(levels)):
            if sum(s.nbytes for s in levels[ln]) > limit:
                return ln
            limit *= self.LEVEL_FANOUT
        return None

    def _signal_compact_locked(self) -> None:
        if self.background and self._pick_compaction_locked() is not None:
            self._compact_kick = True
            self._cv.notify_all()

    def _compact_loop(self) -> None:
        while True:
            with self._cv:
                while not self._compact_kick and not self._stopping:
                    self._cv.wait()
                if self._stopping:
                    return
                self._compact_kick = False
            try:
                while not self._stopping and not self._closed:
                    with self._cv:
                        ln = self._pick_compaction_locked()
                        if ln is not None:
                            self._maint_busy += 1
                    if ln is None:
                        break
                    try:
                        self._compact_level(ln)
                    finally:
                        with self._cv:
                            self._maint_busy -= 1
                            self._cv.notify_all()
            except BaseException as e:  # noqa: BLE001
                with self._cv:
                    self._failed = e
                    self._cv.notify_all()
                from ..utils.log import dout
                dout("kv", 0)(
                    "kv compaction FAILED (store poisoned): %r", e)
                return

    def _compact_level(self, ln: int) -> None:
        """Merge level ln (+ the overlapping files of ln+1) into ln+1
        as a STREAMING k-way merge against an immutable snapshot of
        the level lists — O(one output chunk) resident, never the
        whole level.  Tombstones drop when the output is the
        bottom-most data.  The new manifest publishes under a short
        critical section; dead inputs unlink strictly after it (a
        crash in between leaks orphans that open-time GC removes).
        Scans bypass the block cache — a merge must not evict the
        read working set."""
        t0 = time.monotonic()
        state = self._state  # immutable input snapshot
        levels = state.levels
        while len(levels) <= ln + 1:
            levels = levels + ((),)
        upper = list(levels[ln])
        if not upper:
            return
        lo = min(s.first for s in upper)
        hi = max(s.last for s in upper)
        lower, keep = [], []
        for s in levels[ln + 1]:
            (lower if not (s.last < lo or s.first > hi)
             else keep).append(s)
        bottom = (ln + 2 >= len(levels)
                  or all(not lvl for lvl in levels[ln + 2:]))
        # newest-wins sources: L0 files are newest-first; the lower
        # level is older than everything above it and non-overlapping
        # (one chained sorted stream)
        sources = [s.scan() for s in upper]
        if lower:
            sources.append(itertools.chain.from_iterable(
                s.scan() for s in sorted(lower, key=lambda s: s.first)))
        new_ssts: list[_Sst] = []
        chunk: list[tuple[bytes, int, bytes]] = []
        size = 0
        for ck, tomb, val in _merge_streams(sources):
            if tomb and bottom:
                continue  # tombstone reached the bottom: drop for real
            chunk.append((ck, tomb, val))
            size += len(ck) + len(val)
            if size >= self.SST_SPLIT_BYTES:
                with self._cv:
                    name = self._next_name_locked()
                new_ssts.append(_Sst.write(
                    os.path.join(self._dir, name), chunk))
                chunk, size = [], 0
        if chunk:
            with self._cv:
                name = self._next_name_locked()
            new_ssts.append(_Sst.write(
                os.path.join(self._dir, name), chunk))
        self._crashpoint("compact.pre_manifest")
        dead = upper + lower
        with self._cv:
            if self._closed:
                return  # see _flush_one: a post-close publish would
                #         orphan every live sst; the merge outputs are
                #         the orphans instead (open-time GC)
            cur = list(self._state.levels)
            while len(cur) <= ln + 1:
                cur.append(())
            # L0 may have grown newer files while we merged: keep them
            upper_set = {s.uid for s in upper}
            cur[ln] = tuple(s for s in cur[ln]
                            if s.uid not in upper_set)
            cur[ln + 1] = tuple(sorted(keep + new_ssts,
                                       key=lambda s: s.first))
            self._state = _State(imm=self._state.imm,
                                 levels=tuple(cur))
            pub = self._publish_state_locked()
            self._set_gauges_locked()
            self._cv.notify_all()  # stalled writers re-check
        self._write_manifest(*pub)
        self._crashpoint("compact.pre_unlink")
        self.cache.invalidate_many(s.uid for s in dead)
        for s in dead:
            # NOT s.close(): a lock-free reader may still hold the old
            # snapshot — pin the fd then unlink (removing the name
            # only; preads keep working, the fd closes when the last
            # reference drops)
            s.pin_fd()
            try:
                os.remove(s.path)
            except OSError:  # pragma: no cover
                pass
        self.perf.inc("kv_compact")
        self.perf.hinc("kv_compact_us", (time.monotonic() - t0) * 1e6)

    # ------------------------------------------------------- observability
    def stats(self) -> dict:
        with self._lock:
            st = self._state
            return {"memtable_bytes": self._mem_size,
                    "imm_memtables": len(st.imm),
                    "levels": [len(lv) for lv in st.levels],
                    "files": sum(len(lv) for lv in st.levels),
                    "background": self.background,
                    "flushes": self.perf.get("kv_flush"),
                    "compactions": self.perf.get("kv_compact"),
                    "flushes_inline": self.perf.get("kv_flush_inline"),
                    "compactions_inline":
                        self.perf.get("kv_compact_inline"),
                    "stalls": (self.perf.get("kv_stall_memtable")
                               + self.perf.get("kv_stall_l0")),
                    "slowdowns": self.perf.get("kv_slowdown"),
                    "cache": self.cache.stats()}
