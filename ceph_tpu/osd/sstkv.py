"""SstKV: a leveled LSM / SSTable KeyValueDB backend.

The capability of the reference's RocksDBStore tier (src/kv/
RocksDBStore.cc over RocksDB's LSM): writes land in a WAL-backed
memtable; full memtables flush to immutable sorted-table files at L0;
L0 files (overlapping, newest-first) compact into non-overlapping
L1/L2/... runs; reads consult memtable -> L0 newest->oldest -> one
file per deeper level, each gated by a bloom filter and located via a
sparse block index; tombstones shadow older values and are dropped
when a compaction reaches the bottom level.

File format (sst_NNNNNNNN.sst):
    [records: u32 klen | key | u8 tomb | u32 vlen | value]*
    [bloom bits]
    [index: u32 n | (u32 koff_len | first_key | u64 file_off)*]
    [footer: u64 bloom_off | u64 index_off | u32 n_records |
             u32 crc32c(bloom..index) | magic "SSTB"]

The MANIFEST (levels layout + next file seq) rewrites atomically via
tmp+rename; the memtable WAL uses the store family's crc-framed
fsync'd record contract with torn-tail discard.  Composite keys are
``prefix \\x00 key`` so per-prefix iteration is a contiguous range.
"""

from __future__ import annotations

import hashlib
import os
import struct
import threading

from ..ops.native import crc32c
from ..utils.codec import Decoder, Encoder
from .kvstore import KeyValueDB, KVTransaction

_MAGIC = b"SSTB"
_TOMB = 1
_BLOCK = 4096              # sparse-index granularity (bytes of records)
_REC_TX = 1


def _ckey(prefix: str, key: str) -> bytes:
    return prefix.encode() + b"\x00" + key.encode()


def _split(ck: bytes) -> tuple[str, str]:
    p, _, k = ck.partition(b"\x00")
    return p.decode(), k.decode()


class _Bloom:
    """Fixed-k bloom filter (BloomFilterPolicy role): ~10 bits/key."""

    K = 3

    def __init__(self, bits: bytearray):
        self.bits = bits

    @classmethod
    def build(cls, keys: list[bytes]) -> "_Bloom":
        m = max(64, 10 * len(keys))
        bits = bytearray((m + 7) // 8)
        bloom = cls(bits)
        for k in keys:
            for h in bloom._hashes(k):
                bits[h >> 3] |= 1 << (h & 7)
        return bloom

    def _hashes(self, key: bytes):
        m = len(self.bits) * 8
        for i in range(self.K):
            d = hashlib.blake2b(key, digest_size=8,
                                salt=bytes([i]) * 16).digest()
            yield int.from_bytes(d, "little") % m

    def maybe(self, key: bytes) -> bool:
        return all(self.bits[h >> 3] & (1 << (h & 7))
                   for h in self._hashes(key))


class _Sst:
    """One immutable sorted table: bloom + sparse index resident, data
    read on demand."""

    def __init__(self, path: str):
        self.path = path
        with open(path, "rb") as f:
            f.seek(0, 2)
            end = f.tell()
            f.seek(end - 28)
            footer = f.read(28)
            bloom_off, index_off, self.n, crc = struct.unpack(
                "<QQII", footer[:-4])
            if footer[-4:] != _MAGIC:
                raise IOError(f"{path}: bad sst magic")
            f.seek(bloom_off)
            meta = f.read(end - 28 - bloom_off)
            if crc32c(meta) != crc:
                raise IOError(f"{path}: sst meta crc mismatch")
        self.bloom = _Bloom(bytearray(meta[:index_off - bloom_off]))
        d = Decoder(meta[index_off - bloom_off:])
        self.index: list[tuple[bytes, int]] = []
        for _ in range(d.u32()):
            first = d.blob()
            off = d.u64()
            self.index.append((first, off))
        self._data_end = bloom_off
        self.first = self.index[0][0] if self.index else b""
        self.last = self._last_key() if self.index else b""

    def _last_key(self) -> bytes:
        last = b""
        for ck, _tomb, _v in self.scan(self.index[-1][0]):
            last = ck
        return last

    @staticmethod
    def write(path: str, items: list[tuple[bytes, int, bytes]]) -> "_Sst":
        """items: sorted (composite_key, tomb, value)."""
        tmp = path + ".tmp"
        index: list[tuple[bytes, int]] = []
        with open(tmp, "wb") as f:
            block_start = 0
            for ck, tomb, val in items:
                off = f.tell()
                if off == 0 or off - block_start >= _BLOCK:
                    index.append((ck, off))
                    block_start = off
                f.write(struct.pack("<I", len(ck)))
                f.write(ck)
                f.write(struct.pack("<BI", tomb, len(val)))
                f.write(val)
            bloom_off = f.tell()
            bloom = _Bloom.build([ck for ck, _t, _v in items])
            e = Encoder()
            e.u32(len(index))
            for first, off in index:
                e.blob(first)
                e.u64(off)
            meta = bytes(bloom.bits) + e.tobytes()
            f.write(meta)
            f.write(struct.pack("<QQII", bloom_off,
                                bloom_off + len(bloom.bits),
                                len(items), crc32c(meta)))
            f.write(_MAGIC)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return _Sst(path)

    def scan(self, start_ck: bytes = b"", stop_ck: bytes | None = None):
        """Yield (ck, tomb, value) for start_ck <= ck < stop_ck.  The
        stop bound matters: a prefix range over a large file must not
        decode everything past it."""
        if not self.index:
            return
        # binary search the sparse index for the covering block
        lo, hi = 0, len(self.index) - 1
        pos = 0
        while lo <= hi:
            mid = (lo + hi) // 2
            if self.index[mid][0] <= start_ck:
                pos = self.index[mid][1]
                lo = mid + 1
            else:
                hi = mid - 1
        with open(self.path, "rb") as f:
            f.seek(pos)
            while f.tell() < self._data_end:
                (klen,) = struct.unpack("<I", f.read(4))
                ck = f.read(klen)
                tomb, vlen = struct.unpack("<BI", f.read(5))
                val = f.read(vlen)
                if stop_ck is not None and ck >= stop_ck:
                    return
                if ck >= start_ck:
                    yield ck, tomb, val

    def get(self, ck: bytes):
        """(tomb, value) or None."""
        if not (self.first <= ck <= self.last) or not self.bloom.maybe(ck):
            return None
        for k, tomb, val in self.scan(ck):
            if k == ck:
                return tomb, val
            if k > ck:
                return None
        return None


class SstKV(KeyValueDB):
    L0_COMPACT_FILES = 4
    LEVEL_BASE_BYTES = 1 << 20      # L1 target; 10x per deeper level
    LEVEL_FANOUT = 10
    SST_SPLIT_BYTES = 1 << 20       # split compaction output files

    def __init__(self, path: str, memtable_bytes: int = 256 * 1024):
        os.makedirs(path, exist_ok=True)
        self._dir = path
        self._memtable_bytes = memtable_bytes
        self._lock = threading.RLock()
        self._mem: dict[bytes, tuple[int, bytes]] = {}  # ck->(tomb,val)
        self._mem_size = 0
        self._levels: list[list[_Sst]] = []  # [0]=L0 newest-first
        self._seq = 0
        self._manifest = os.path.join(path, "MANIFEST")
        self._wal_path = os.path.join(path, "memtable.wal")
        self._load_manifest()
        self._replay_wal()
        self._wal = open(self._wal_path, "ab")

    # ------------------------------------------------------- manifest/wal
    def _load_manifest(self) -> None:
        if not os.path.exists(self._manifest):
            self._levels = [[]]
            return
        with open(self._manifest, "rb") as f:
            d = Decoder(f.read())
        self._seq = d.u64()
        self._levels = []
        for _ in range(d.u32()):
            names = [d.string() for _ in range(d.u32())]
            self._levels.append([_Sst(os.path.join(self._dir, n))
                                 for n in names])
        if not self._levels:
            self._levels = [[]]

    def _save_manifest(self) -> None:
        e = Encoder()
        e.u64(self._seq)
        e.u32(len(self._levels))
        for level in self._levels:
            e.u32(len(level))
            for sst in level:
                e.string(os.path.basename(sst.path))
        tmp = self._manifest + ".tmp"
        with open(tmp, "wb") as f:
            f.write(e.tobytes())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._manifest)

    def _replay_wal(self) -> None:
        if not os.path.exists(self._wal_path):
            return
        with open(self._wal_path, "rb") as f:
            raw = f.read()
        pos = 0
        while pos + 8 <= len(raw):
            length, crc = struct.unpack_from("<II", raw, pos)
            payload = raw[pos + 8: pos + 8 + length]
            if len(payload) < length or crc32c(payload) != crc:
                break  # torn tail
            d = Decoder(payload)
            if d.u8() == _REC_TX:
                for _ in range(d.u32()):
                    ck, tomb, val = d.blob(), d.u8(), d.blob()
                    self._mem_put(ck, tomb, val)
            pos += 8 + length
        if pos < len(raw):
            with open(self._wal_path, "r+b") as f:
                f.truncate(pos)

    def _mem_put(self, ck: bytes, tomb: int, val: bytes) -> None:
        old = self._mem.get(ck)
        if old is not None:
            self._mem_size -= len(ck) + len(old[1])
        self._mem[ck] = (tomb, val)
        self._mem_size += len(ck) + len(val)

    # ----------------------------------------------------------------- api
    def submit(self, tx: KVTransaction, sync: bool = True) -> None:
        with self._lock:
            flat: list[tuple[bytes, int, bytes]] = []
            for op, prefix, key, val in tx.ops:
                if op == "put":
                    flat.append((_ckey(prefix, key), 0, val))
                elif op == "rm":
                    flat.append((_ckey(prefix, key), _TOMB, b""))
                else:  # rm_prefix: tombstone every live key in range —
                    # including keys PUT earlier in this same tx
                    # (KVTransaction ops apply in order, as MemKV does)
                    doomed = {_ckey(prefix, k)
                              for k, _v in self.iterate(prefix)}
                    pfx = prefix.encode() + b"\x00"
                    doomed |= {ck for ck, t, _v in flat
                               if ck.startswith(pfx) and not t}
                    flat.extend((ck, _TOMB, b"") for ck in sorted(doomed))
            e = Encoder()
            e.u8(_REC_TX)
            e.u32(len(flat))
            for ck, tomb, val in flat:
                e.blob(ck)
                e.u8(tomb)
                e.blob(val)
            payload = e.tobytes()
            self._wal.write(struct.pack("<II", len(payload),
                                        crc32c(payload)) + payload)
            if sync:
                self._wal.flush()
                os.fsync(self._wal.fileno())
            for ck, tomb, val in flat:
                self._mem_put(ck, tomb, val)
            if self._mem_size >= self._memtable_bytes:
                self._flush_memtable()

    def sync(self) -> None:
        with self._lock:
            if self._wal is not None:
                self._wal.flush()
                os.fsync(self._wal.fileno())

    def get(self, prefix: str, key: str) -> bytes | None:
        ck = _ckey(prefix, key)
        with self._lock:
            hit = self._mem.get(ck)
            if hit is not None:
                return None if hit[0] else hit[1]
            for sst in self._levels[0]:            # L0 newest-first
                hit = sst.get(ck)
                if hit is not None:
                    return None if hit[0] else hit[1]
            for level in self._levels[1:]:         # non-overlapping
                for sst in level:
                    if sst.first <= ck <= sst.last:
                        hit = sst.get(ck)
                        if hit is not None:
                            return None if hit[0] else hit[1]
                        break
        return None

    def iterate(self, prefix: str, start: str = ""):
        """Merged newest-wins iteration over memtable + every level."""
        lo = _ckey(prefix, start)
        hi = prefix.encode() + b"\x01"  # end of the prefix's range
        with self._lock:
            sources: list[list[tuple[bytes, int, bytes]]] = []
            mem = [(ck, tv[0], tv[1])
                   for ck, tv in sorted(self._mem.items())
                   if lo <= ck < hi]
            sources.append(mem)
            for sst in self._levels[0]:
                sources.append(list(sst.scan(lo, hi)))
            for level in self._levels[1:]:
                run: list[tuple[bytes, int, bytes]] = []
                for sst in level:
                    if sst.last < lo or sst.first >= hi:
                        continue
                    run.extend(sst.scan(lo, hi))
                sources.append(run)
        # newest-wins merge: earlier sources shadow later ones
        seen: dict[bytes, tuple[int, bytes]] = {}
        for src in sources:
            for ck, tomb, val in src:
                if ck not in seen:
                    seen[ck] = (tomb, val)
        for ck in sorted(seen):
            tomb, val = seen[ck]
            if not tomb:
                yield _split(ck)[1], val

    def close(self) -> None:
        with self._lock:
            if self._wal:
                self._wal.close()
                self._wal = None

    # ------------------------------------------------------ flush/compact
    def _next_name(self) -> str:
        self._seq += 1
        return f"sst_{self._seq:08d}.sst"

    def _flush_memtable(self) -> None:
        """Memtable -> new L0 file; WAL truncates after the flush is
        durable (the flush IS the durability point for these keys)."""
        if not self._mem:
            return
        items = [(ck, t, v) for ck, (t, v) in sorted(self._mem.items())]
        sst = _Sst.write(os.path.join(self._dir, self._next_name()),
                         items)
        self._levels[0].insert(0, sst)  # newest first
        self._save_manifest()
        self._mem.clear()
        self._mem_size = 0
        self._wal.close()
        self._wal = open(self._wal_path, "wb")
        self._maybe_compact()

    def _maybe_compact(self) -> None:
        if len(self._levels[0]) > self.L0_COMPACT_FILES:
            self._compact_level(0)
        limit = self.LEVEL_BASE_BYTES
        for ln in range(1, len(self._levels)):
            size = sum(os.path.getsize(s.path)
                       for s in self._levels[ln])
            if size > limit:
                self._compact_level(ln)
            limit *= self.LEVEL_FANOUT

    def _compact_level(self, ln: int) -> None:
        """Merge level ln (+ the overlapping files of ln+1) into ln+1.
        Tombstones drop when the output is the bottom-most data."""
        while len(self._levels) <= ln + 1:
            self._levels.append([])
        upper = list(self._levels[ln])
        if not upper:
            return
        lo = min(s.first for s in upper)
        hi = max(s.last for s in upper)
        lower, keep = [], []
        for s in self._levels[ln + 1]:
            (lower if not (s.last < lo or s.first > hi)
             else keep).append(s)
        # newest-wins merge: L0 files are newest-first; the lower level
        # is older than everything above it
        merged: dict[bytes, tuple[int, bytes]] = {}
        for s in list(upper) + lower:
            for ck, tomb, val in s.scan():
                if ck not in merged:
                    merged[ck] = (tomb, val)
        bottom = (ln + 2 >= len(self._levels)
                  or all(not lvl for lvl in self._levels[ln + 2:]))
        out_items: list[tuple[bytes, int, bytes]] = []
        for ck in sorted(merged):
            tomb, val = merged[ck]
            if tomb and bottom:
                continue  # tombstone reached the bottom: drop for real
            out_items.append((ck, tomb, val))
        new_ssts: list[_Sst] = []
        chunk: list[tuple[bytes, int, bytes]] = []
        size = 0
        for item in out_items:
            chunk.append(item)
            size += len(item[0]) + len(item[2])
            if size >= self.SST_SPLIT_BYTES:
                new_ssts.append(_Sst.write(
                    os.path.join(self._dir, self._next_name()), chunk))
                chunk, size = [], 0
        if chunk or not new_ssts:
            new_ssts.append(_Sst.write(
                os.path.join(self._dir, self._next_name()), chunk))
        dead = upper + lower
        self._levels[ln] = [] if ln > 0 else \
            [s for s in self._levels[0] if s not in upper]
        self._levels[ln + 1] = sorted(keep + new_ssts,
                                      key=lambda s: s.first)
        self._save_manifest()
        for s in dead:
            try:
                os.remove(s.path)
            except OSError:
                pass

    # ------------------------------------------------------- observability
    def stats(self) -> dict:
        with self._lock:
            return {"memtable_bytes": self._mem_size,
                    "levels": [len(lv) for lv in self._levels],
                    "files": sum(len(lv) for lv in self._levels)}
