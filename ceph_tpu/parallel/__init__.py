"""Parallelism layer: device meshes, shardings, and the distributed EC
write/recovery steps (the TPU mapping of SURVEY.md §2.8's strategies —
stripe batch = data parallel, shard axis = tensor parallel, collectives
over ICI instead of the reference's messenger fan-out)."""

from .mesh import init_multihost, make_flat_mesh, make_host_mesh, make_mesh
from .distributed import DistributedStripeEC, make_folded_matmul

__all__ = ["make_mesh", "make_flat_mesh", "make_host_mesh",
           "init_multihost", "DistributedStripeEC", "make_folded_matmul"]
