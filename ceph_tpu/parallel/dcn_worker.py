"""Multi-process DCN worker: one PROCESS of a jax.distributed cluster.

The reference scales across hosts with its cluster messenger over the
network (src/ceph_osd.cc:550-630 boot joining the cluster fabric); the
TPU build's DCN fabric is jax.distributed + XLA collectives.  Every
prior round exercised the ("host","dp","shard") mesh inside ONE
process over virtual devices; this worker is the leg that crosses a
REAL process boundary: N processes (each with its own CPU devices)
join through the gRPC coordination service, build the host mesh whose
"host" axis follows jax.process_index(), and run the full distributed
EC write + recovery step with every verification computed INSIDE the
SPMD program (replicated scalars out — no host-side gathering of
cross-process shards needed).

Launched by tests/test_multiprocess_dcn.py and by
__graft_entry__.dryrun_multichip's multi-process leg:

    python -m ceph_tpu.parallel.dcn_worker \
        --coordinator 127.0.0.1:PORT --num-processes 2 --process-id I
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--coordinator", required=True)
    p.add_argument("--num-processes", type=int, required=True)
    p.add_argument("--process-id", type=int, required=True)
    p.add_argument("--devices-per-host", type=int, default=4)
    args = p.parse_args()

    # hermetic CPU backend BEFORE any backend init (the axon wedge —
    # see utils/jaxenv).  The flag is forced here even over an
    # inherited XLA_FLAGS: each WORKER process must get exactly
    # devices_per_host devices regardless of the parent's setting.
    flag = "--xla_force_host_platform_device_count"
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if not f.startswith(flag)]
    os.environ["XLA_FLAGS"] = " ".join(
        flags + [f"{flag}={args.devices_per_host}"])
    from ceph_tpu.utils.jaxenv import force_cpu
    force_cpu()
    import jax

    from ceph_tpu.parallel.mesh import init_multihost
    joined = init_multihost(args.coordinator, args.num_processes,
                            args.process_id)
    assert joined, "init_multihost declined a multi-process config"
    assert jax.process_count() == args.num_processes, \
        jax.process_count()

    import jax.numpy as jnp
    import numpy as np

    from ceph_tpu.models.stripe_codec import StripeCodec
    from ceph_tpu.parallel import DistributedStripeEC, make_host_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_host_mesh()  # host axis == the process boundary
    assert mesh.shape["host"] == args.num_processes
    codec = StripeCodec(k=8, m=3)
    dec = DistributedStripeEC(codec, mesh, batch_axes=("host", "dp"))

    B = 2 * dec.n_dp
    L = 256 * dec.n_shard
    # every process derives the same GLOBAL payload, then materializes
    # only its addressable shards of the distributed array
    data_np = np.random.default_rng(42).integers(
        0, 256, (B, 8, L), dtype=np.uint8)
    sharding = NamedSharding(mesh, P(("host", "dp"), None, None))
    data = jax.make_array_from_callback(
        data_np.shape, sharding, lambda idx: data_np[idx])

    stack, digest = dec.write_step(data)
    # verifications stay inside SPMD; only replicated scalars come out
    sys_err = int(jax.jit(
        lambda s, d: jnp.sum(jnp.bitwise_xor(
            s[:, :8, :], d), dtype=jnp.uint32))(stack, data))
    available = [0, 2, 3, 5, 6, 7, 8, 10]  # lose chunks 1, 4, 9
    rec = dec.recovery_step(available)(stack)
    rec_err = int(jax.jit(
        lambda r, d: jnp.sum(jnp.bitwise_xor(r, d),
                             dtype=jnp.uint32))(rec, data))
    stats = jax.jit(dec.make_stats_step())(stack)
    stats_sum = int(jax.jit(
        lambda s: jnp.sum(s, dtype=jnp.uint64))(stats))

    print(json.dumps({
        "process_id": args.process_id,
        "process_count": jax.process_count(),
        "devices_total": len(jax.devices()),
        "devices_local": len(jax.local_devices()),
        "mesh": dict(mesh.shape),
        "digest": int(np.asarray(digest)),
        "systematic_err": sys_err,
        "recovery_err": rec_err,
        "stats_sum": stats_sum,
    }))
    return 0 if sys_err == 0 and rec_err == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
