"""Distributed stripe EC over a device mesh — the ICI-native "cluster".

The TPU mapping of the reference's distributed write path (SURVEY.md §3.1
EC variant: ECBackend.submit_transaction -> per-shard MOSDECSubOpWrite
fan-out over the cluster messenger) re-designed for SPMD over a
("dp", "shard") mesh:

- encode runs **column-sharded** ("sp": each device holds a slice of every
  chunk's columns — the striping/sequence-parallel analogue, SURVEY.md §5),
  so parity is computed with zero communication;
- chunk *placement* is one `all_to_all` that re-lays the stripe from
  column-sharded to row-sharded ownership (each shard device ends up
  owning whole chunks — the acting-set fan-out, but as a single ICI
  collective instead of k+m messenger sends);
- rebalance/backfill movement is a `ppermute` of chunk rows around the
  shard ring (the chunk_mapping/pg-remap analogue, ECUtil.h:477-517);
- degraded reads `all_gather` the surviving rows and decode locally (the
  ReadPipeline fan-in, ECCommon.h:352-420);
- cluster-wide stats (bytes/digest) reduce with `psum` (the PGStats ->
  mgr report analogue).
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from ..models.stripe_codec import StripeCodec


def stage_folded(rows: np.ndarray, mesh: Mesh, axis: str = "shard"):
    """Stage a host fold DIRECTLY into its mesh sharding: ``device_put``
    with the folded launch's NamedSharding moves one column slice per
    device, instead of landing the whole tensor on device 0 and paying
    an on-mesh reshard when the jitted shard_map consumes it — the
    sharded half of the device-resident stripe plane's single-h2d
    contract.  The copy is metered on the ``ec_stage_h2d_*`` staging
    counters (one copy event: the slices leave the host together).
    Device-resident inputs pass through untouched — the jit reshards
    them on-device."""
    if not isinstance(rows, np.ndarray):
        return rows
    import time

    from jax.sharding import NamedSharding

    from ..utils import staging
    t0 = time.perf_counter()
    dev = jax.device_put(rows, NamedSharding(mesh, P(None, axis)))
    # latency only on the synchronous CPU backend — an async device_put
    # returns at dispatch and would book dispatch time as the copy
    staging.note_h2d(rows.nbytes,
                     time.perf_counter() - t0
                     if staging.backend_is_cpu() else None)
    return dev


def make_folded_matmul(M: np.ndarray, mesh: Mesh, axis: str = "shard",
                       kernel: str = "xla"):
    """Mesh-sharded folded region multiply: fn(rows (c, N) uint8) ->
    (r, N) uint8 computing M @ rows over GF(2^8) with the LENGTH axis
    sharded over `axis` — the multi-chip fan-out for the ECBatcher's
    folded (k, sum L) launches (and any other caller already holding
    many stripes as one wide tensor).

    Columns of a region matmul are independent, so the shard_map body
    is the plain encode/decode graph and NO collective runs: an n-device
    mesh encodes an n-writer burst in ~one chip-time.  Callers pad N to
    a multiple of n_devices * 4 (uint32 lanes per shard); zero columns
    encode to zero under a linear code, so padding slices away exact.

    ``kernel`` selects the graph realization the body embeds
    (ops/ec_kernels.gf_region_graph: xla bit-terms / bitxor scheduled
    planes / mxu bit-matrix dot) — how a sharded pool rides the
    auto-tuner's per-signature winner.
    """
    from ..ops.ec_kernels import gf_region_graph
    g = gf_region_graph(np.ascontiguousarray(M, dtype=np.uint8), kernel)
    return shard_map(g, mesh=mesh, in_specs=P(None, axis),
                     out_specs=P(None, axis))


def make_folded_csum(k: int, m: int, M: np.ndarray, chunk_bytes: int,
                     mesh: Mesh, axis: str = "shard",
                     kernel: str = "xla"):
    """Mesh-sharded fused encode+CRC32C: fn(data (k, N) uint8, N =
    batch*chunk_bytes) -> (parity (m, N), csums (k+m, batch) uint32)
    with the length axis sharded over `axis` — the multi-chip fan-out
    for the ECBatcher's CHECKSUMMED folded launches.

    The CRC tree reduction (ops/checksum.CrcPlan) is per chunk, and a
    flushed batch pads its stripe count to a multiple of the fan-out,
    so every device owns whole chunks: the shard_map body is the plain
    single-device encode+csum graph and NO collective runs — a
    checksummed burst on a sharded pool keeps its fan-out instead of
    falling through to the CPU CRC sweep.  Callers guarantee N splits
    into whole per-device chunks (chunk_bytes % 4 == 0 and the chunk
    count divisible by the mesh size); digests are byte-identical to
    the native sweep (the affine constants are shape-independent)."""
    codec = StripeCodec.__new__(StripeCodec)
    codec.k, codec.m = k, m
    codec.matrix = np.ascontiguousarray(M, dtype=np.uint8)
    fn = codec.encode_csum_graph(chunk_bytes, kernel=kernel)
    return shard_map(fn, mesh=mesh, in_specs=P(None, axis),
                     out_specs=(P(None, axis), P(None, axis)))


class DistributedStripeEC:
    """Distributed EC pipeline for a StripeCodec over a ("dp","shard") mesh.

    Data model: a batch of stripes (B, k, L) uint8.  B is sharded over
    "dp"; L over "shard" during compute; after placement each shard device
    owns S/n_shard whole chunk rows, where S pads k+m up to a multiple of
    the shard axis (spare rows are zero — "spare OSD" slots).
    """

    def __init__(self, codec: StripeCodec, mesh: Mesh,
                 batch_axes: Sequence[str] = ("dp",)):
        self.codec = codec
        self.mesh = mesh
        self.n_shard = mesh.shape["shard"]
        # the batch dimension may shard over several mesh axes — on a
        # multi-host mesh it is ("host", "dp"): the slow DCN hop only
        # ever carries batch-parallel work, while the chatty "shard"
        # collectives (all_to_all / ppermute) stay inside one host's
        # ICI domain (the scaling-book layout rule; SURVEY.md §2.3
        # TPU-equivalent row — DCN via jax.distributed)
        self.batch_axes = tuple(batch_axes)
        self.n_dp = 1
        for a in self.batch_axes:
            self.n_dp *= mesh.shape[a]
        km = codec.k + codec.m
        self.S = -(-km // self.n_shard) * self.n_shard
        self.spare_rows = self.S - km

    # ---------------- write ----------------
    def make_write_step(self):
        """jit-able fn(data (B, k, L)) -> (stack (B, S, L), digest scalar).

        Output sharding: stack rows over "shard" (chunk ownership), batch
        over "dp"; digest is a psum-reduced uint32 scrub digest.
        """
        k, m, S = self.codec.k, self.codec.m, self.S
        enc = self.codec.encode_graph()

        def local(d):  # (b, k, Lloc) on one device
            b, _, Ll = d.shape
            folded = d.transpose(1, 0, 2).reshape(k, b * Ll)
            par = enc(folded).reshape(m, b, Ll).transpose(1, 0, 2)
            zeros = jnp.zeros((b, S - k - m, Ll), jnp.uint8)
            stack = jnp.concatenate([d, par, zeros], axis=1)  # (b, S, Ll)
            # placement: column-sharded -> row-sharded chunk ownership
            stack = jax.lax.all_to_all(stack, "shard", split_axis=1,
                                       concat_axis=2, tiled=True)
            # scrub digest: cluster-wide reduction of encoded bytes
            digest = jax.lax.psum(
                jnp.sum(par.astype(jnp.uint32)),
                (*self.batch_axes, "shard"))
            return stack, digest

        B = self.batch_axes
        return shard_map(
            local, mesh=self.mesh,
            in_specs=P(B, None, "shard"),
            out_specs=(P(B, "shard", None), P()),
        )

    # ---------------- rebalance / backfill ----------------
    def make_rebalance_step(self, rotate: int = 1):
        """jit-able fn(stack (B, S, L)) -> stack with chunk-row ownership
        rotated `rotate` positions around the shard ring (ppermute) — the
        movement primitive behind pg-remap/backfill."""
        n = self.n_shard

        def local(stack_local):
            perm = [(i, (i + rotate) % n) for i in range(n)]
            return jax.lax.ppermute(stack_local, "shard", perm)

        B = self.batch_axes
        return shard_map(
            local, mesh=self.mesh,
            in_specs=P(B, "shard", None),
            out_specs=P(B, "shard", None),
        )

    # ---------------- degraded read / recovery ----------------
    def make_recovery_step(self, available: Sequence[int]):
        """jit-able fn(stack (B, S, L)) -> data (B, k, L) decoding from the
        static erasure signature `available` (>= k surviving chunk ids).

        all_gathers surviving rows over the shard axis (the fan-in read),
        decodes locally with the inverted matrix, returns column-sharded
        data (ready for re-encode or client return).
        """
        k = self.codec.k
        use = list(available)[:k]
        dec = self.codec.decode_graph(use)

        def local(stack_local):  # (b, S/n, L) — whole rows owned locally
            b = stack_local.shape[0]
            # inverse of the write placement: row-sharded -> column-sharded
            # (each device sends every peer only the column slice it will
            # decode — less ICI traffic than a full-row all_gather, and no
            # decode work is discarded)
            full = jax.lax.all_to_all(stack_local, "shard", split_axis=2,
                                      concat_axis=1, tiled=True)  # (b,S,L/n)
            Ll = full.shape[2]
            surv = full[:, jnp.asarray(use), :]  # (b, k, L/n) static gather
            folded = surv.transpose(1, 0, 2).reshape(k, b * Ll)
            return dec(folded).reshape(k, b, Ll).transpose(1, 0, 2)

        B = self.batch_axes
        return shard_map(
            local, mesh=self.mesh,
            in_specs=P(B, "shard", None),
            out_specs=P(B, None, "shard"),
        )

    # ---------------- partial write: parity delta ----------------
    def make_delta_step(self):
        """jit-able fn(stack (B,S,L) row-sharded, delta (B,k,L)
        column-sharded) -> updated stack.

        The parity-delta partial write (ECUtil encode_parity_delta,
        ECUtil.cc:519-566): GF(2^8) addition is XOR, so
        parity' = parity ^ encode(delta) and data' = data ^ delta —
        the stripe updates without re-reading any other row.  The
        delta encodes column-sharded (zero communication), then one
        all_to_all re-lays it to chunk ownership and the XOR folds in
        locally — same collective budget as a full write, a fraction
        of the FLOPs."""
        k, m, S = self.codec.k, self.codec.m, self.S
        enc = self.codec.encode_graph()

        def local(stack_local, d):  # d: (b, k, Lloc) column-sharded
            b, _, Ll = d.shape
            folded = d.transpose(1, 0, 2).reshape(k, b * Ll)
            par = enc(folded).reshape(m, b, Ll).transpose(1, 0, 2)
            zeros = jnp.zeros((b, S - k - m, Ll), jnp.uint8)
            upd = jnp.concatenate([d, par, zeros], axis=1)
            upd = jax.lax.all_to_all(upd, "shard", split_axis=1,
                                     concat_axis=2, tiled=True)
            return jnp.bitwise_xor(stack_local, upd)

        B = self.batch_axes
        return shard_map(
            local, mesh=self.mesh,
            in_specs=(P(B, "shard", None), P(B, None, "shard")),
            out_specs=P(B, "shard", None),
        )

    # ---------------- per-shard stats: dp-axis reduction ----------------
    def make_stats_step(self):
        """jit-able fn(stack (B,S,L)) -> (S,) uint32 per-chunk-row byte
        totals, reduced over the BATCH axes only (each shard position
        aggregates its own rows across every batch — the per-OSD stats
        report, MPGStats -> mgr aggregation).  On a multi-host mesh this
        is the reduction that rides DCN."""
        def local(stack_local):
            tot = jnp.sum(stack_local.astype(jnp.uint32), axis=(0, 2))
            return jax.lax.psum(tot, self.batch_axes)

        B = self.batch_axes
        return shard_map(
            local, mesh=self.mesh,
            in_specs=P(B, "shard", None),
            out_specs=P("shard"),
        )

    # ---------------- convenience: jitted end-to-end step ----------------
    @functools.cached_property
    def write_step(self):
        return jax.jit(self.make_write_step())

    def recovery_step(self, available: Sequence[int]):
        """Jitted recovery step, cached per erasure signature (the decode
        table cache of the reference, ErasureCodeIsa.cc:513-563)."""
        key = tuple(available)
        cache = self.__dict__.setdefault("_recovery_cache", {})
        fn = cache.get(key)
        if fn is None:
            fn = jax.jit(self.make_recovery_step(available))
            if len(cache) > 128:
                cache.pop(next(iter(cache)))
            cache[key] = fn
        return fn
