"""Device-mesh helpers.

The cluster topology layer: where the reference wires messengers between
OSD processes (src/ceph_osd.cc:550-630), the TPU build arranges devices in
a jax.sharding.Mesh whose axes carry the parallelism strategies — "dp"
(stripe batches, the PG-parallel analogue) x "shard" (chunk shards of one
stripe, the acting-set analogue).  Collectives over these axes ride ICI.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(n_devices: int | None = None,
              shard_axis: int | None = None,
              devices=None) -> Mesh:
    """Build a ("dp", "shard") mesh over the first n devices.

    shard_axis defaults to the largest power-of-two divisor of n that is
    <= 4 when n is small (keeping a nontrivial dp axis), so an 8-device CI
    mesh becomes (dp=2, shard=4).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = n_devices or len(devices)
    if n > len(devices):
        raise ValueError(f"requested {n} devices, have {len(devices)}")
    devices = devices[:n]
    if shard_axis is None:
        shard_axis = 1
        while shard_axis * 2 <= min(4, n) and n % (shard_axis * 2) == 0:
            shard_axis *= 2
    if n % shard_axis:
        raise ValueError(f"{n} devices not divisible by shard={shard_axis}")
    arr = np.array(devices).reshape(n // shard_axis, shard_axis)
    return Mesh(arr, ("dp", "shard"))
