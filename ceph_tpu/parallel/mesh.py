"""Device-mesh helpers.

The cluster topology layer: where the reference wires messengers between
OSD processes (src/ceph_osd.cc:550-630), the TPU build arranges devices in
a jax.sharding.Mesh whose axes carry the parallelism strategies — "dp"
(stripe batches, the PG-parallel analogue) x "shard" (chunk shards of one
stripe, the acting-set analogue).  Collectives over these axes ride ICI.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(n_devices: int | None = None,
              shard_axis: int | None = None,
              devices=None) -> Mesh:
    """Build a ("dp", "shard") mesh over the first n devices.

    shard_axis defaults to the largest power-of-two divisor of n that is
    <= 4 when n is small (keeping a nontrivial dp axis), so an 8-device CI
    mesh becomes (dp=2, shard=4).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = n_devices or len(devices)
    if n > len(devices):
        raise ValueError(f"requested {n} devices, have {len(devices)}")
    devices = devices[:n]
    if shard_axis is None:
        shard_axis = 1
        while shard_axis * 2 <= min(4, n) and n % (shard_axis * 2) == 0:
            shard_axis *= 2
    if n % shard_axis:
        raise ValueError(f"{n} devices not divisible by shard={shard_axis}")
    arr = np.array(devices).reshape(n // shard_axis, shard_axis)
    return Mesh(arr, ("dp", "shard"))


def make_flat_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """A 1-axis ("shard",) mesh over the first n devices — the fan-out
    topology for FOLDED launches (the ECBatcher's (k, sum L) tensors),
    where the only parallel dimension is the length axis and no
    collective ever runs: columns of a GF(2^8) region matmul are
    independent, so each device computes its column slice and the
    result is purely the concatenation."""
    devices = list(devices if devices is not None else jax.devices())
    n = n_devices or len(devices)
    if n > len(devices):
        raise ValueError(f"requested {n} devices, have {len(devices)}")
    return Mesh(np.array(devices[:n]), ("shard",))


def init_multihost(coordinator_address: str | None = None,
                   num_processes: int | None = None,
                   process_id: int | None = None,
                   local_device_ids=None) -> bool:
    """Join a multi-host JAX cluster (the DCN fabric — the role the
    reference's cluster messenger network plays across hosts,
    src/ceph_osd.cc:550-630, re-based on jax.distributed + its gRPC
    coordination service).  No-op (returns False) single-process, so
    callers can share one code path.  Env fallbacks: JAX_COORDINATOR /
    JAX_NUM_PROCESSES / JAX_PROCESS_ID."""
    import os

    coordinator_address = coordinator_address or \
        os.environ.get("JAX_COORDINATOR")
    if num_processes is None:
        num_processes = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    if num_processes <= 1 or not coordinator_address:
        return False
    if process_id is None:
        process_id = int(os.environ.get("JAX_PROCESS_ID", "0"))
    jax.distributed.initialize(coordinator_address, num_processes,
                               process_id,
                               local_device_ids=local_device_ids)
    return True


def make_host_mesh(n_hosts: int | None = None,
                   shard_axis: int | None = None,
                   devices=None) -> Mesh:
    """("host", "dp", "shard") mesh: the OUTER host axis maps the DCN
    hop (one slice per process), the inner axes the ICI domain.  Layout
    rule (scaling-book): chatty per-stripe collectives stay on "shard"
    (ICI); only batch-parallel reductions cross "host".

    Under a real multi-host runtime the host axis follows
    jax.process_index(); single-process (CI / the driver's virtual CPU
    mesh) an n_hosts axis is synthesized by slicing the flat device
    list — the same program compiles either way (SPMD is oblivious)."""
    devices = list(devices if devices is not None else jax.devices())
    if n_hosts is None:
        n_hosts = max(1, jax.process_count())
    if len(devices) % n_hosts:
        raise ValueError(
            f"{len(devices)} devices not divisible by {n_hosts} hosts")
    per_host = len(devices) // n_hosts
    # keep each host's devices contiguous (process-local under a real
    # multi-host runtime, so "shard"/"dp" collectives never cross DCN)
    devices = sorted(devices,
                     key=lambda d: (getattr(d, "process_index", 0),
                                    d.id))
    if shard_axis is None:
        shard_axis = 1
        while shard_axis * 2 <= min(4, per_host) and \
                per_host % (shard_axis * 2) == 0:
            shard_axis *= 2
    if per_host % shard_axis:
        raise ValueError(
            f"{per_host} per-host devices not divisible by "
            f"shard={shard_axis}")
    arr = np.array(devices).reshape(n_hosts, per_host // shard_axis,
                                    shard_axis)
    return Mesh(arr, ("host", "dp", "shard"))
