"""Deterministic data placement — the CRUSH-equivalent.

The capability of the reference's CRUSH layer (src/crush/mapper.c
crush_do_rule + straw2 buckets; OSDMap::_pg_to_raw_osds
src/osd/OSDMap.cc:2779): a pure function from (map, pg) to an ordered
device list that every client and server computes identically — no lookup
service.  This implementation is straw2-*style* (max of weight-scaled
log-uniform draws, which gives weight-proportional selection and minimal
movement on weight changes) over a two-level tree (root -> failure domains
-> devices), with retry-based collision avoidance.  The hash is splitmix64,
not rjenkins; layouts are NOT wire-compatible with Ceph, deliberately.

Object -> PG uses stable-mod semantics (ceph_stable_mod,
src/include/types.h) so pg_num changes split PGs predictably.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

_M = (1 << 64) - 1


def _splitmix64(x: int) -> int:
    x = (x + 0x9E3779B97F4A7C15) & _M
    z = x
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _M
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _M
    return z ^ (z >> 31)


def hash_combine(*parts) -> int:
    h = 0x243F6A8885A308D3
    for p in parts:
        if isinstance(p, str):
            p = int.from_bytes(p.encode("utf-8").ljust(8, b"\0")[:8],
                               "little") ^ (len(p) << 56)
        h = _splitmix64((h ^ p) & _M)
    return h


def stable_mod(x: int, b: int, bmask: int) -> int:
    """ceph_stable_mod semantics: nearest power-of-two split behaviour."""
    return (x & bmask) if (x & bmask) < b else (x & (bmask >> 1))


def pg_of_object(name: str, pg_num: int) -> int:
    """Object name -> pg seed (the ceph_str_hash + stable_mod step)."""
    bmask = (1 << max(pg_num - 1, 1).bit_length()) - 1
    return stable_mod(hash_combine("oid", name) & 0xFFFFFFFF, pg_num, bmask)


@dataclass
class Device:
    id: int
    weight: float = 1.0
    host: str = "host0"


@dataclass
class PlacementMap:
    """Two-level tree: failure domains (hosts) -> devices."""

    devices: dict[int, Device] = field(default_factory=dict)

    def add_device(self, dev_id: int, weight: float = 1.0,
                   host: str | None = None) -> None:
        self.devices[dev_id] = Device(dev_id, weight,
                                      host or f"host{dev_id}")

    def remove_device(self, dev_id: int) -> None:
        self.devices.pop(dev_id, None)

    def hosts(self) -> dict[str, list[Device]]:
        out: dict[str, list[Device]] = {}
        for d in self.devices.values():
            out.setdefault(d.host, []).append(d)
        return out

    # -- straw2-style draws ------------------------------------------------
    @staticmethod
    def _draw(key: int, item: int | str, trial: int, weight: float) -> float:
        if weight <= 0:
            return -math.inf
        u = (hash_combine("straw", key, item, trial) & 0xFFFFFFFF) / 2**32
        u = max(u, 1e-12)
        return math.log(u) / weight  # max over items ~ weighted choice

    def _choose_one(self, key: int, trial: int, items: list,
                    weights: list[float], exclude: set) -> int | str | None:
        best, best_draw = None, -math.inf
        for it, w in zip(items, weights):
            if it in exclude:
                continue
            d = self._draw(key, it, trial, w)
            if d > best_draw:
                best, best_draw = it, d
        return best

    def select(self, key: int, n: int, domain: str = "host",
               reject=None) -> list[int]:
        """Choose n devices for placement key, at most one per failure
        domain when domain='host' (fewer domains than n fall back to
        device-level spreading for the remainder).  `reject(dev_id)` marks
        devices unusable (out); collisions retry with fresh trials, so
        survivors keep their positions when others are rejected."""
        reject = reject or (lambda d: False)
        hosts = self.hosts()
        host_names = sorted(hosts)
        host_w = [sum(d.weight for d in hosts[h]) for h in host_names]
        out: list[int] = []
        used_hosts: set = set()
        used_devs: set = set()
        trial = 0
        max_trials = 50 * max(n, 1)
        while len(out) < n and trial < max_trials:
            if domain == "host" and len(used_hosts) < len(host_names):
                h = self._choose_one(key, trial, host_names, host_w,
                                     used_hosts)
                trial += 1
                if h is None:
                    break
                devs = hosts[h]
                d = self._choose_one(
                    hash_combine(key, h), trial, [x.id for x in devs],
                    [x.weight for x in devs], used_devs)
                if d is None or reject(d):
                    # host exhausted/unusable for this slot; try others
                    used_hosts.add(h)
                    continue
                used_hosts.add(h)
                used_devs.add(d)
                out.append(d)
            else:
                ids = sorted(self.devices)
                d = self._choose_one(key, trial, ids,
                                     [self.devices[i].weight for i in ids],
                                     used_devs)
                trial += 1
                if d is None:
                    break
                used_devs.add(d)
                if not reject(d):
                    out.append(d)
        return out
