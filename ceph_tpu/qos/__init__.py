"""Multi-tenant QoS control plane (the dmclock + mclock-profiles role).

Three coupled layers (see README.md in this package):

- ``dmclock.py``  — client-side tag state: a per-tenant ServiceTracker
  stamps outgoing ops with (delta, rho) dmclock tags learned from the
  phase field on op replies, so every OSD can compute correct
  multi-server mclock tags without a global clock;
- ``profiles.py`` — named tenant profiles (reservation/weight/limit
  IOPS) distributed cluster-wide via the OSDMap like pool options;
- ``controller.py`` — the adaptive reservation controller: AIMD with
  hysteresis over observed client p99 queue-wait vs recovery backlog,
  retuning ``osd_mclock_recovery_{res,lim}`` live via ``reset_mclock``.
"""

from .dmclock import (PHASE_NONE, PHASE_RESERVATION, PHASE_WEIGHT,
                      ServiceTracker)
from .profiles import (DEFAULT_TENANT, TenantProfile, params_from_map,
                       parse_profile, profiles_from_map)

__all__ = [
    "PHASE_NONE", "PHASE_RESERVATION", "PHASE_WEIGHT", "ServiceTracker",
    "DEFAULT_TENANT", "TenantProfile", "parse_profile",
    "params_from_map", "profiles_from_map",
]
