r"""Adaptive recovery-reservation controller (AIMD with hysteresis).

Closes the loop the ROADMAP's telemetry follow-on (a) and saturation
follow-on (d) described: PR 7 proved the recovery reservation/limit
knob moves recovery rate and client p99 in opposite directions, PR 9
gave us windowed ``mclock_qwait_us_client`` p99 via ``metrics_query``
— this controller reads the observed tails and turns the knob itself,
retuning ``osd_mclock_recovery_{res,lim}`` live through the existing
``reset_mclock`` verb.

State machine (one ``observe()`` call per mgr tick)::

          p99 > high for `hold` ticks
    STEADY ------------------------------>  BACKOFF  (res *= backoff)
      ^  \                                      |
      |   \  backlog & p99 < low for `hold`     |  cooldown ticks
      |    ------------------------------> GROW |
      |          (res += step)                  |
      +-----------------------------------------+

- **Additive increase**: recovery has backlog and clients are
  comfortably under the low watermark -> raise the reservation one
  ``step`` (recovery drains faster while there is headroom).
- **Multiplicative decrease**: client p99 queue-wait crosses the high
  watermark -> cut the reservation by ``backoff`` (clients win ties).
- **Hysteresis**: a condition must hold ``hold`` consecutive ticks
  before acting, and every apply starts a ``cooldown`` during which no
  further move happens — one noisy window cannot saw the knob.
- **Clamps**: res stays inside [res_min, res_max] (the hand-tuned
  sweep's endpoints); the limit follows as ``res * lim_factor``.

The class is pure decision logic — no cluster access — so the AIMD
steps, hysteresis and clamps unit-test deterministically; the mgr
``qos`` module (mon/mgr.py) owns the sensing (metrics_query windows,
recovery backlog) and the actuation (config set + reset_mclock +
``qos`` cluster-log events).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class ControllerKnobs:
    """Tuning surface (seeded from qos_controller_* config options)."""

    res_min: float = 4.0        # the hand-tuned sweep's low endpoint
    res_max: float = 128.0      # ... and its high endpoint
    step: float = 8.0           # additive increase (ops/s)
    backoff: float = 0.5        # multiplicative decrease factor
    p99_low_us: float = 20_000.0   # grow only below this client p99
    p99_high_us: float = 100_000.0  # back off above this client p99
    hold: int = 2               # consecutive ticks before acting
    cooldown: int = 2           # ticks of silence after an apply
    lim_factor: float = 2.0     # limit = res * lim_factor (0 = no lim)
    burn_high: float = 2.0      # slo sense: back off above this burn
    burn_low: float = 0.5       # slo sense: grow only below this burn


@dataclass
class Retune:
    """One applied move (the journal row of a ``qos`` cluster event)."""

    tick: int
    res: float
    lim: float
    reason: str                  # "grow" | "backoff"
    p99_us: float | None = None
    backlog: int = 0
    burn: float | None = None    # slo sense: fast-window burn at retune


class ReservationController:
    """AIMD recovery-reservation controller; call ``observe`` once per
    tick with the sensed cluster state, apply the returned (res, lim)
    when non-None."""

    def __init__(self, knobs: ControllerKnobs | None = None,
                 res0: float | None = None):
        self.knobs = knobs or ControllerKnobs()
        k = self.knobs
        self.res = min(k.res_max,
                       max(k.res_min, res0 if res0 is not None
                           else k.res_min))
        self._tick = 0
        self._hot = 0            # consecutive over-high ticks
        self._cold = 0           # consecutive grow-eligible ticks
        self._cooldown = 0
        self.history: list[Retune] = []

    # ------------------------------------------------------------ stepping
    def limit(self, res: float | None = None) -> float:
        k = self.knobs
        r = self.res if res is None else res
        return r * k.lim_factor if k.lim_factor > 0 else 0.0

    def observe(self, p99_us: float | None, backlog: int,
                recovery_active: bool) -> tuple[float, float] | None:
        """One tick.  ``p99_us``: worst client queue-wait p99 across
        daemons over the sensing window (None = no samples yet);
        ``backlog``: queued recovery items cluster-wide;
        ``recovery_active``: a storm is live (progress items open).
        Returns (res, lim) when a retune should be applied."""
        k = self.knobs
        hot = p99_us is not None and p99_us > k.p99_high_us
        cold = ((p99_us is None or p99_us < k.p99_low_us)
                and (recovery_active or backlog > 0))
        return self._step(hot, cold, p99_us, backlog, None)

    def observe_burn(self, burn_fast: float | None, backlog: int,
                     recovery_active: bool,
                     p99_us: float | None = None
                     ) -> tuple[float, float] | None:
        """One tick sensing on SLO burn instead of raw p99: back off
        when the fast-window error-budget burn exceeds ``burn_high``
        (the budget is being eaten faster than the objective allows),
        grow only when burn is comfortably under ``burn_low`` and
        recovery wants headroom.  ``burn_fast`` None = SLO module has
        no samples yet -> treated like quiet (grow-eligible when
        backlog exists), matching ``observe``'s no-samples stance."""
        k = self.knobs
        hot = burn_fast is not None and burn_fast > k.burn_high
        cold = ((burn_fast is None or burn_fast < k.burn_low)
                and (recovery_active or backlog > 0))
        return self._step(hot, cold, p99_us, backlog, burn_fast)

    def _step(self, hot: bool, cold: bool, p99_us, backlog,
              burn) -> tuple[float, float] | None:
        k = self.knobs
        self._tick += 1
        # hysteresis counters advance even through cooldown, so a
        # persistent condition acts the instant the cooldown lifts
        if hot:
            self._hot += 1
            self._cold = 0
        elif cold:
            self._cold += 1
            self._hot = 0
        else:
            self._hot = self._cold = 0
        if self._cooldown > 0:
            self._cooldown -= 1
            return None
        if self._hot >= k.hold and self.res > k.res_min:
            self.res = max(k.res_min, self.res * k.backoff)
            return self._applied("backoff", p99_us, backlog, burn)
        if self._cold >= k.hold and self.res < k.res_max:
            self.res = min(k.res_max, self.res + k.step)
            return self._applied("grow", p99_us, backlog, burn)
        return None

    def _applied(self, reason: str, p99_us, backlog, burn=None
                 ) -> tuple[float, float]:
        lim = self.limit()
        self.history.append(Retune(self._tick, self.res, lim, reason,
                                   p99_us, int(backlog),
                                   None if burn is None else float(burn)))
        self._cooldown = self.knobs.cooldown
        self._hot = self._cold = 0
        return self.res, lim

    # -------------------------------------------------------- introspection
    def retunes(self) -> int:
        return len(self.history)

    def convergence_error(self) -> float:
        """Relative size of the last move — the bench row's
        "controller convergence error" (0.0 until two retunes exist)."""
        if len(self.history) < 2:
            return 0.0
        a, b = self.history[-2].res, self.history[-1].res
        return abs(b - a) / max(b, 1e-9)

    def converged_between(self, lo: float | None = None,
                          hi: float | None = None) -> bool:
        """The tenant-suite gate: the controller MOVED (>= 1 retune)
        and landed strictly above the low hand-tuned sweep point and
        at-or-under the high one."""
        k = self.knobs
        lo = k.res_min if lo is None else lo
        hi = k.res_max if hi is None else hi
        return bool(self.history) and lo < self.res <= hi

    def status(self) -> dict:
        return {
            "res": self.res, "lim": self.limit(),
            "tick": self._tick, "retunes": self.retunes(),
            "cooldown": self._cooldown,
            "convergence_error": round(self.convergence_error(), 4),
            "history": [
                {"tick": r.tick, "res": r.res, "lim": r.lim,
                 "reason": r.reason, "p99_us": r.p99_us,
                 "backlog": r.backlog,
                 **({"burn": r.burn} if r.burn is not None else {})}
                for r in self.history],
        }
