"""Client-side dmclock: per-tenant (delta, rho) tag bookkeeping.

The capability of the reference's vendored dmclock ServiceTracker
(src/dmclock/src/dmclock_client.h): a client talking to MANY servers
must tell each server how much service it received *elsewhere* since
its last request to that server, or every server would grant the
tenant its full reservation independently and the cluster-wide floor
would multiply by the server count.  The protocol needs no global
clock — only two monotone counters per client:

- ``delta``: responses received from ANY server since the last request
  to this server (+1 for the request being tagged) — advances the
  server's proportional (weight) tag by ``delta / W``;
- ``rho``: the subset of those responses served in the RESERVATION
  phase (+1) — advances the server's reservation tag by ``rho / R``.

Servers learn the phase they served each op under from the trailing
``qphase`` field on op replies; the tracker folds those back in via
``note_reply``.  Per-server state resets on reconnect (``forget``) and
decays when a server goes idle-cold (``idle_age_s``) — a tenant that
stopped talking to an OSD for minutes must restart from (1, 1), not
replay an ancient backlog of foreign service into its first tag.
"""

from __future__ import annotations

import threading
import time

#: service-phase codes carried in MOSDOpReply.qphase (0 = scheduler
#: off / untagged: the op never crossed a dmclock queue)
PHASE_NONE = 0
PHASE_RESERVATION = 1
PHASE_WEIGHT = 2

#: sanity clamp on wire-carried tags: a buggy or hostile client must
#: not be able to fast-forward a server's clocks arbitrarily far
TAG_CAP = 10_000


class ServiceTracker:
    """One tenant's view of its own cluster-wide service.

    One instance per client (the client IS one tenant); thread-safe —
    the aio pool sends ops from several threads at once.
    """

    def __init__(self, idle_age_s: float = 300.0,
                 clock=time.monotonic):
        self.idle_age_s = float(idle_age_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._delta_total = 0   # responses received, any phase
        self._rho_total = 0     # responses served by reservation
        # server -> {"delta_seen", "rho_seen", "last"}: the totals at
        # the moment of the last request to that server
        self._servers: dict[str, dict] = {}
        self._last_sweep = clock()

    # ------------------------------------------------------------ feedback
    def note_reply(self, server: str, phase: int) -> None:
        """Fold one op reply's phase into the totals (any server)."""
        with self._lock:
            self._delta_total += 1
            if phase == PHASE_RESERVATION:
                self._rho_total += 1

    # ------------------------------------------------------------- tagging
    def tags_for(self, server: str) -> tuple[int, int]:
        """(delta, rho) for the next request to ``server``; both count
        the request itself, so the floor is (1, 1) — a client's very
        first op, or its first after a reset, carries the neutral tag."""
        now = self._clock()
        with self._lock:
            if now - self._last_sweep > self.idle_age_s:
                self._sweep_locked(now)
            st = self._servers.get(server)
            if st is None:
                st = self._servers[server] = {
                    "delta_seen": self._delta_total,
                    "rho_seen": self._rho_total, "last": now}
                return 1, 1
            if now - st["last"] > self.idle_age_s:
                # idle decay: restart the pair, don't replay history
                st["delta_seen"] = self._delta_total
                st["rho_seen"] = self._rho_total
                st["last"] = now
                return 1, 1
            delta = min(TAG_CAP,
                        self._delta_total - st["delta_seen"] + 1)
            rho = min(TAG_CAP, self._rho_total - st["rho_seen"] + 1)
            st["delta_seen"] = self._delta_total
            st["rho_seen"] = self._rho_total
            st["last"] = now
            return delta, rho

    def forget(self, server: str) -> None:
        """Reconnect reset: the server's dmclock state died with its
        old process (or our connection), so the pair restarts at the
        neutral (1, 1) on the next request."""
        with self._lock:
            self._servers.pop(server, None)

    def _sweep_locked(self, now: float) -> None:
        self._last_sweep = now
        for s, st in list(self._servers.items()):
            if now - st["last"] > self.idle_age_s:
                del self._servers[s]

    # -------------------------------------------------------- introspection
    def totals(self) -> tuple[int, int]:
        """(delta_total, rho_total) — test/diagnostic surface."""
        with self._lock:
            return self._delta_total, self._rho_total

    def tracked_servers(self) -> list[str]:
        with self._lock:
            return sorted(self._servers)
