"""Named tenant QoS profiles, distributed via the OSDMap.

The mclock-profiles role at tenant granularity: a profile names a
tenant and gives it (reservation, weight, limit) IOPS — the same
triple the per-class scheduler knobs use, but owned by the operator
per tenant and shipped cluster-wide inside the OSDMap (the pg_pool_t
options pattern: commit once on the mon, every daemon converges on the
next map push, no per-OSD config fan-out).

Profile grammar (the `osd qos set-profile` verb and the map wire
form)::

    name:  [a-z0-9_-]{1,32}            # also the exporter label stem
    res:   float >= 0   ops/s guaranteed floor (0 = none)
    wgt:   float >  0   proportional share past the floor
    lim:   float >= 0   ops/s hard ceiling (0 = unlimited)

The string form ``res=50,wgt=4,lim=200`` round-trips through
``parse_profile`` / ``TenantProfile.spec``.  A tenant no profile
names falls into the DEFAULT profile — isolated in its own dynamic
sub-queue, but with the neutral (0, 1, 0) parameters.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

#: the tenant every untagged / unknown-tenant op accounts to
DEFAULT_TENANT = "default"

_NAME_RE = re.compile(r"^[a-z0-9_-]{1,32}$")


@dataclass(frozen=True)
class TenantProfile:
    """One tenant's (R, W, L) in ops/s."""

    name: str
    reservation: float = 0.0
    weight: float = 1.0
    limit: float = 0.0

    def __post_init__(self):
        validate_name(self.name)
        if self.reservation < 0 or self.limit < 0:
            raise ValueError(
                f"profile {self.name!r}: res/lim must be >= 0")
        if self.weight <= 0:
            raise ValueError(f"profile {self.name!r}: wgt must be > 0")

    def spec(self) -> str:
        return (f"res={self.reservation:g},wgt={self.weight:g},"
                f"lim={self.limit:g}")

    def to_dict(self) -> dict:
        """The OSDMap / mon-command wire form."""
        return {"res": float(self.reservation),
                "wgt": float(self.weight), "lim": float(self.limit)}

    @classmethod
    def from_dict(cls, name: str, d: dict) -> "TenantProfile":
        return cls(name, reservation=float(d.get("res", 0.0)),
                   weight=float(d.get("wgt", 1.0)),
                   limit=float(d.get("lim", 0.0)))


def validate_name(name: str) -> str:
    if not _NAME_RE.match(name or ""):
        raise ValueError(
            f"bad tenant name {name!r} (want [a-z0-9_-]{{1,32}})")
    return name


DEFAULT_PROFILE = TenantProfile(DEFAULT_TENANT, 0.0, 1.0, 0.0)


def parse_profile(name: str, spec: str) -> TenantProfile:
    """``res=50,wgt=4,lim=200`` -> TenantProfile (missing keys keep
    their defaults; unknown keys are an error, not silence)."""
    kw = {"res": 0.0, "wgt": 1.0, "lim": 0.0}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        k, _, v = part.partition("=")
        k = k.strip()
        if k not in kw:
            raise ValueError(f"unknown profile key {k!r} "
                             f"(want res/wgt/lim)")
        try:
            kw[k] = float(v)
        except ValueError:
            raise ValueError(f"profile key {k}={v!r} is not a number") \
                from None
    return TenantProfile(name, reservation=kw["res"], weight=kw["wgt"],
                         limit=kw["lim"])


def profiles_from_map(qos_profiles: dict) -> dict[str, TenantProfile]:
    """OSDMap.qos_profiles ({name: {res, wgt, lim}}) -> profile
    objects; malformed entries degrade to the default parameters
    instead of raising (a bad map entry must not take a daemon down)."""
    out: dict[str, TenantProfile] = {}
    for name, d in (qos_profiles or {}).items():
        try:
            out[name] = TenantProfile.from_dict(name, dict(d))
        except (TypeError, ValueError):
            try:
                out[name] = TenantProfile(name)
            except ValueError:
                continue  # unusable name: skip entirely
    return out


def params_from_map(qos_profiles: dict) -> dict:
    """OSDMap.qos_profiles -> {tenant: ClassParams} for the scheduler
    (the import lives here so the scheduler module stays the only
    importer of ClassParams in this direction)."""
    from ..osd.scheduler import ClassParams
    return {name: ClassParams(p.reservation, p.weight, p.limit)
            for name, p in profiles_from_map(qos_profiles).items()}
