"""Service layers over RADOS (the reference's librbd/rgw/cephfs tier).

First slice: `rbd` — block images striped over objects with COW
snapshots (SURVEY.md §2.7 librbd row).
"""
