"""fs-lite: a POSIX-ish file layer over RADOS.

The capability slice of CephFS's data path (src/mds metadata +
src/client Client.cc -> Striper -> RADOS): directories are omap-backed
metadata objects (the dentry table of a dir frag), file data stripes
over RADOS objects via the same file_layout_t algebra the reference's
Striper uses, and path resolution walks the directory chain.

What the reference's MDS adds beyond this slice — distributed cache
with capabilities (leases), journaling via MDLog, multi-active subtree
partitioning and rebalancing, snapshots — is the planned widening;
this layer gives the POSIX surface (mkdir/readdir/create/read/write/
truncate/unlink/rename/stat) with single-writer semantics.
"""

from __future__ import annotations

import posixpath
import time
import uuid

from ..client.rados import RadosClient, RadosError
from ..client.striper import FileLayout, StripedObject
from ..msg.wire import pack_value, unpack_value

_DIR_OID = "fs_dir.{path}"
_DATA_PREFIX = "fs_data.{ino}"


class FsError(Exception):
    def __init__(self, code: int, what: str):
        super().__init__(what)
        self.code = code


def _norm(path: str) -> str:
    # POSIX quirk: normpath("//x") keeps the double slash; strip leading
    # slashes before re-rooting
    return posixpath.normpath("/" + path.strip().lstrip("/"))


class FsClient:
    """One mounted filesystem view (libcephfs Client shape)."""

    def __init__(self, client: RadosClient, pool: str,
                 layout: FileLayout | None = None):
        self.client = client
        self.pool = pool
        self.layout = layout or FileLayout(stripe_unit=65536,
                                           stripe_count=4,
                                           object_size=1 << 22)
        # ensure the root exists
        try:
            self.client.omap_get(self.pool, _DIR_OID.format(path="/"))
        except RadosError:
            self.client.omap_set(self.pool, _DIR_OID.format(path="/"),
                                 {})

    # ------------------------------------------------------------ helpers
    def _dir_oid(self, path: str) -> str:
        return _DIR_OID.format(path=_norm(path))

    def _entries(self, dirpath: str) -> dict:
        try:
            raw = self.client.omap_get(self.pool, self._dir_oid(dirpath))
        except RadosError:
            raise FsError(-2, f"no such directory {dirpath!r}") from None
        return {k: unpack_value(v) for k, v in raw.items()}

    def _lookup(self, path: str) -> dict:
        path = _norm(path)
        if path == "/":
            return {"type": "dir"}
        parent, name = posixpath.split(path)
        ent = self._entries(parent).get(name)
        if ent is None:
            raise FsError(-2, f"no such entry {path!r}")
        return ent

    def _set_entry(self, path: str, ent: dict) -> None:
        parent, name = posixpath.split(_norm(path))
        self.client.omap_set(self.pool, self._dir_oid(parent),
                             {name: pack_value(ent)})

    def _rm_entry(self, path: str) -> None:
        parent, name = posixpath.split(_norm(path))
        self.client.omap_rm(self.pool, self._dir_oid(parent), [name])

    def _data(self, ino: str) -> StripedObject:
        return StripedObject(self.client, self.pool,
                             _DATA_PREFIX.format(ino=ino), self.layout)

    # ---------------------------------------------------------- directory
    def mkdir(self, path: str) -> None:
        path = _norm(path)
        parent, name = posixpath.split(path)
        ents = self._entries(parent)  # raises if parent missing
        if name in ents:
            raise FsError(-17, f"{path!r} exists")
        self.client.omap_set(self.pool, self._dir_oid(path), {})
        self._set_entry(path, {"type": "dir", "mtime": time.time()})

    def listdir(self, path: str) -> list[str]:
        self._assert_dir(path)
        return sorted(self._entries(path))

    def rmdir(self, path: str) -> None:
        path = _norm(path)
        if path == "/":
            raise FsError(-22, "cannot remove the root")
        self._assert_dir(path)
        if self._entries(path):
            raise FsError(-39, f"{path!r} not empty")
        self.client.remove(self.pool, self._dir_oid(path))
        self._rm_entry(path)

    def _assert_dir(self, path: str) -> None:
        ent = self._lookup(path)
        if ent["type"] != "dir":
            raise FsError(-20, f"{path!r} is not a directory")

    # --------------------------------------------------------------- files
    def create(self, path: str) -> None:
        path = _norm(path)
        parent, name = posixpath.split(path)
        ents = self._entries(parent)
        if name in ents:
            raise FsError(-17, f"{path!r} exists")
        self._set_entry(path, {"type": "file", "size": 0,
                               "ino": uuid.uuid4().hex,
                               "mtime": time.time()})

    def write_file(self, path: str, data: bytes, offset: int = 0) -> None:
        ent = self._lookup(path)
        if ent["type"] != "file":
            raise FsError(-21, f"{path!r} is a directory")
        self._data(ent["ino"]).write(offset, data)
        ent["size"] = max(ent["size"], offset + len(data))
        ent["mtime"] = time.time()
        self._set_entry(path, ent)

    def read_file(self, path: str, offset: int = 0,
                  length: int | None = None) -> bytes:
        ent = self._lookup(path)
        if ent["type"] != "file":
            raise FsError(-21, f"{path!r} is a directory")
        if length is None:
            length = max(0, ent["size"] - offset)
        length = max(0, min(length, ent["size"] - offset))
        return self._data(ent["ino"]).read(offset, length)

    def truncate(self, path: str, size: int) -> None:
        ent = self._lookup(path)
        if ent["type"] != "file":
            raise FsError(-21, f"{path!r} is a directory")
        if size > ent["size"]:
            self._data(ent["ino"]).write(
                ent["size"], b"\0" * (size - ent["size"]))
        elif size < ent["size"]:
            # zero the cut region so a later grow reads POSIX holes,
            # not resurrected pre-truncate bytes
            self._data(ent["ino"]).write(
                size, b"\0" * (ent["size"] - size))
        ent["size"] = size
        ent["mtime"] = time.time()
        self._set_entry(path, ent)

    def unlink(self, path: str) -> None:
        ent = self._lookup(path)
        if ent["type"] != "file":
            raise FsError(-21, f"{path!r} is a directory (use rmdir)")
        self._data(ent["ino"]).remove()
        self._rm_entry(path)

    def stat(self, path: str) -> dict:
        ent = dict(self._lookup(path))
        ent.setdefault("size", 0)
        return ent

    def rename(self, src: str, dst: str) -> None:
        """Same-type rename; directories move their SUBTREE by renaming
        the dir object path keys (the subtree-migration slice of the
        MDS, minus the distributed locking)."""
        src, dst = _norm(src), _norm(dst)
        if dst == src or dst.startswith(src + "/"):
            raise FsError(-22,
                          f"cannot move {src!r} into itself ({dst!r})")
        ent = self._lookup(src)
        parent, name = posixpath.split(dst)
        dents = self._entries(parent)
        if name in dents:
            raise FsError(-17, f"{dst!r} exists")
        if ent["type"] == "dir":
            self._rename_dir_tree(src, dst)
        self._set_entry(dst, ent)
        self._rm_entry(src)

    def _rename_dir_tree(self, src: str, dst: str) -> None:
        ents = self._entries(src)
        self.client.omap_set(self.pool, self._dir_oid(dst),
                             {k: pack_value(v) for k, v in ents.items()})
        for name, ent in ents.items():
            if ent["type"] == "dir":
                self._rename_dir_tree(posixpath.join(src, name),
                                      posixpath.join(dst, name))
        self.client.remove(self.pool, self._dir_oid(src))
