"""fs-lite: a POSIX-ish file layer over RADOS, metadata via mds-lite.

The capability slice of CephFS (src/mds + src/client/Client.cc):
metadata ops route through an MdsDaemon whose mutations are MDLog-
journaled (services/mds.py) — directories are omap-backed dentry
tables, and an MDS crash/restart replays the journal.  File DATA never
touches the MDS: it stripes over RADOS objects via the same
file_layout_t algebra the reference's Striper uses (Client ->
Objecter -> OSD).

Capabilities: `open()` returns an FsFile holding caps from the MDS —
"r" caches the file content client-side (cached reads, Fc/Fr role),
"w" buffers writes locally (Fb/Fw role) until flush/close/revoke.  A
conflicting open on another mount revokes first: the holder flushes
synchronously, so readers-after-writers always see flushed bytes.

Multi-active subtree partitioning is the next widening; snapshots are
not implemented.
"""

from __future__ import annotations

import posixpath
import threading
import time
import uuid

from ..client.rados import RadosClient, RadosError
from ..client.striper import FileLayout, StripedObject
from .mds import FsError, MdsDaemon, _norm

_DATA_PREFIX = "fs_data.{ino}"

__all__ = ["FsClient", "FsError", "FsFile", "MdsDaemon"]


class FsFile:
    """An open file handle under caps (the Fh + cap-ref role)."""

    def __init__(self, fs: "FsClient", path: str, ent: dict, caps: str):
        self._fs = fs
        self.path = path
        self.ino = ent["ino"]
        self.caps = caps
        self._size = ent["size"]
        self._cache: bytes | None = None       # "r": whole-file cache
        self._buffered: list[tuple[int, bytes]] = []  # "w": write-back
        self._lock = threading.RLock()
        self.cache_reads = 0    # served from cache (observability/tests)
        self.closed = False

    # ---------------------------------------------------------------- io
    # Lock order is ALWAYS mds._lock -> handle._lock (both RLocks): the
    # revoke path (MdsDaemon.open holds mds._lock, calls _on_revoke
    # which takes h._lock) and the io paths below (which call back into
    # the MDS) would otherwise ABBA-deadlock across two mounts.
    def read(self, offset: int = 0, length: int | None = None) -> bytes:
        with self._fs.mds._lock, self._lock:
            self._assert_open()
            if "r" not in self.caps and not self._buffered:
                # no lease: the size may have moved under us — re-stat
                # from the MDS (the uncapped read goes to authority)
                self._size = int(self._fs.mds.lookup(self.path)
                                 .get("size", 0))
            if length is None:
                length = max(0, self._size - offset)
            length = max(0, min(length, self._size - offset))
            if self._buffered:
                # read-your-writes through the buffer: flatten first
                self._flush_locked()
            if "r" in self.caps:
                if self._cache is None:
                    self._cache = self._fs._data(self.ino).read(
                        0, self._size)
                else:
                    self.cache_reads += 1
                return self._cache[offset:offset + length]
            return self._fs._data(self.ino).read(offset, length)

    def write(self, data: bytes, offset: int | None = None) -> None:
        with self._fs.mds._lock, self._lock:
            self._assert_open()
            if "w" not in self.caps:
                raise FsError(-9, f"{self.path!r} not open for write")
            if offset is None:
                offset = self._size
            self._buffered.append((offset, bytes(data)))
            self._size = max(self._size, offset + len(data))
            self._cache = None  # cache no longer covers the new extent

    def flush(self) -> None:
        with self._fs.mds._lock, self._lock:
            self._assert_open()
            self._flush_locked()

    def _flush_locked(self) -> None:
        if not self._buffered:
            return
        self._fs._snapc_sync()  # buffered data lands under the live snapc
        so = self._fs._data(self.ino)
        for offset, data in self._buffered:
            so.write(offset, data)
        self._buffered.clear()
        ent = self._fs.mds.lookup(self.path)
        if self._size != ent.get("size"):
            ent["size"] = max(int(ent.get("size", 0)), self._size)
            ent["mtime"] = time.time()
            self._fs.mds.set_entry(self.path, ent,
                                   client_id=self._fs.client_id)
        self._size = ent["size"]

    def close(self) -> None:
        with self._fs.mds._lock, self._lock:
            if self.closed:
                return
            self._flush_locked()
            self.closed = True
        self._fs._close_handle(self)

    def _assert_open(self) -> None:
        if self.closed:
            raise FsError(-9, f"{self.path!r} is closed")

    def __enter__(self) -> "FsFile":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class FsClient:
    """One mounted filesystem view (libcephfs Client shape).  Shares an
    MdsDaemon with other mounts; creates a private one when none is
    given (still journaled — single-mount callers get crash safety for
    free)."""

    def __init__(self, client: RadosClient, pool: str,
                 layout: FileLayout | None = None,
                 mds: MdsDaemon | None = None,
                 client_id: str | None = None):
        self.client = client
        self.pool = pool
        self.layout = layout or FileLayout(stripe_unit=65536,
                                           stripe_count=4,
                                           object_size=1 << 22)
        self.mds = mds or MdsDaemon(client, pool)
        self.client_id = client_id or f"fsclient-{uuid.uuid4().hex[:8]}"
        self._handles: dict[str, list[FsFile]] = {}
        self._hlock = threading.Lock()
        self.mds.register_session(
            self.client_id, self._on_revoke,
            ticket=client.service_ticket("mds"),
            ticket_provider=lambda: client.service_ticket("mds"))

    def unmount(self) -> None:
        with self._hlock:
            handles = [h for hs in self._handles.values() for h in hs]
        for h in handles:
            h.close()
        self.mds.unregister_session(self.client_id)

    # ------------------------------------------------------------ helpers
    def _data(self, ino: str) -> StripedObject:
        return StripedObject(self.client, self.pool,
                             _DATA_PREFIX.format(ino=ino), self.layout)

    def _on_revoke(self, path: str) -> None:
        """MDS cap revoke: flush buffered writes, drop caches (the
        client-side CHECK_CAPS/flush path)."""
        with self._hlock:
            handles = list(self._handles.get(path, []))
        for h in handles:
            with h._lock:
                if not h.closed:
                    h._flush_locked()
                h._cache = None
                h.caps = ""

    def _close_handle(self, h: FsFile) -> None:
        with self._hlock:
            hs = self._handles.get(h.path, [])
            if h in hs:
                hs.remove(h)
            if not hs:
                self._handles.pop(h.path, None)
        self.mds.release(self.client_id, h.path)

    # ------------------------------------------------------ open/caps API
    def open(self, path: str, mode: str = "r") -> FsFile:
        """Open with caps: "r" (cached reads), "w"/"rw" (buffered
        writes); "w" creates if missing."""
        path = _norm(path)
        if "w" in mode:
            try:
                self.mds.lookup(path)
            except FsError:
                self.create(path)
        grant = self.mds.open(self.client_id, path, mode)
        h = FsFile(self, path, grant["ent"], grant["caps"])
        with self._hlock:
            self._handles.setdefault(path, []).append(h)
        return h

    # ----------------------------------------------------- .snap routing
    def _split_snap(self, path: str):
        """CephFS-style snapshot paths: <realm>/.snap/<name>[/<rest>].
        Returns (snapid, snap_root, resolved_path) or None for live
        paths; resolution walks up to the covering realm (snaprealm
        semantics)."""
        parts = _norm(path).split("/")
        if ".snap" not in parts:
            return None
        i = parts.index(".snap")
        realm = _norm("/".join(parts[:i])) or "/"
        if i + 1 >= len(parts):
            raise FsError(-21, ".snap itself is not a file")
        name = parts[i + 1]
        rest = "/".join(parts[i + 2:])
        root, sid = self.mds.snap_covering(realm, name)
        resolved = _norm(posixpath.join(realm, rest)) if rest \
            else _norm(realm)
        return sid, root, resolved

    def _snapc_sync(self) -> None:
        """Attach the filesystem's live SnapContext before data writes
        so the OSDs clone-on-first-write-after-snap (the snaprealm
        get_snap_context -> ioctx write-ctx path)."""
        seq, snaps = self.mds.snap_context()
        self.client.set_snap_context(self.pool, seq, snaps)

    def _read_snap_data(self, ent: dict, snapid: int, offset: int,
                        length: int) -> bytes:
        out = bytearray(length)
        pos = 0
        prefix = _DATA_PREFIX.format(ino=ent["ino"])
        for objno, obj_off, take in self.layout.file_to_extents(
                offset, length):
            try:
                piece = self.client.read(
                    self.pool, f"{prefix}.{objno:016x}",
                    offset=obj_off, length=take, snapid=snapid)
            except RadosError:
                piece = b""
            out[pos:pos + len(piece)] = piece
            pos += take
        return bytes(out)

    # ---------------------------------------------------- snapshot verbs
    def snap_create(self, path: str, name: str) -> int:
        # flush THIS mount's buffers under the realm before freezing
        for p, hs in list(self._handles.items()):
            if p == _norm(path) or p.startswith(_norm(path) + "/"):
                for h in list(hs):
                    if not h.closed:
                        h.flush()
        return self.mds.snap_create(path, name, client_id=self.client_id)

    def snap_list(self, path: str) -> dict:
        return self.mds.snaps_of(path)

    def snap_remove(self, path: str, name: str) -> None:
        self.mds.snap_remove(path, name, client_id=self.client_id)

    def snap_rollback(self, path: str, name: str) -> None:
        """Restore the subtree to the snapshot: journaled metadata
        rollback at the MDS, then per-piece data rollback driven here
        (idempotent — re-run after any crash)."""
        path = _norm(path)
        sid = self.mds.snaps_of(path).get(name)
        if sid is None:
            raise FsError(-2, f"no snapshot {name!r} on {path!r}")
        # capture the LIVE tree before metadata rollback: files that
        # grew after the snapshot need their beyond-snap pieces rolled
        # (the OSD removes pre-birth pieces), and files born after the
        # snapshot lose their dentries — their data is purged here
        live = {}
        self._collect_files(path, live)
        self.mds.snap_rollback(path, name, client_id=self.client_id)
        survivors: dict[str, int] = {}
        self._rollback_data(path, sid, live, survivors)
        for ino, size in live.items():
            if ino not in survivors:
                # born after the snapshot: dentry gone, purge the data
                prefix = _DATA_PREFIX.format(ino=ino)
                for objno in ({o for o, _x, _t in
                               self.layout.file_to_extents(0, size)}
                              if size else set()):
                    try:
                        self.client.remove(self.pool,
                                           f"{prefix}.{objno:016x}")
                    except RadosError:
                        pass

    def _collect_files(self, dirpath: str, out: dict) -> None:
        try:
            ents = self.mds.entries(dirpath)
        except FsError:
            return
        for nm, ent in ents.items():
            sub = posixpath.join(dirpath, nm)
            if ent["type"] == "dir":
                self._collect_files(sub, out)
            else:
                out[ent["ino"]] = int(ent.get("size", 0))

    def _rollback_data(self, dirpath: str, snapid: int,
                       live: dict, survivors: dict) -> None:
        for nm, ent in self.mds.entries(dirpath).items():
            sub = posixpath.join(dirpath, nm)
            if ent["type"] == "dir":
                self._rollback_data(sub, snapid, live, survivors)
                continue
            size = int(ent.get("size", 0))
            survivors[ent["ino"]] = size
            # roll the pieces covering the LARGER of snap-time and live
            # size: the OSD restores in-snap pieces and REMOVES pieces
            # born after the snap (pre-birth rollback semantics)
            span = max(size, live.get(ent["ino"], 0))
            prefix = _DATA_PREFIX.format(ino=ent["ino"])
            pieces = {objno for objno, _o, _t
                      in self.layout.file_to_extents(0, span)} if span \
                else set()
            for objno in pieces:
                try:
                    self.client.snap_rollback(
                        self.pool, f"{prefix}.{objno:016x}", snapid)
                except RadosError:
                    pass  # piece did not exist at snap time

    # ---------------------------------------------------------- directory
    def mkdir(self, path: str) -> None:
        if self._split_snap(path) is not None:
            raise FsError(-30, "snapshots are read-only")
        self.mds.mkdir(path, client_id=self.client_id)

    def listdir(self, path: str) -> list[str]:
        parts = _norm(path).split("/")
        if parts[-1] == ".snap":
            realm = _norm("/".join(parts[:-1])) or "/"
            self._assert_dir(realm)
            return sorted(self.mds.snaps_of(realm))
        snap = self._split_snap(path)
        if snap is not None:
            sid, _root, resolved = snap
            return sorted(self.mds.snap_entries(sid, resolved))
        self._assert_dir(path)
        return sorted(self.mds.entries(_norm(path)))

    def rmdir(self, path: str) -> None:
        self.mds.rmdir(path, client_id=self.client_id)

    def _assert_dir(self, path: str) -> None:
        ent = self.mds.lookup(path)
        if ent["type"] != "dir":
            raise FsError(-20, f"{path!r} is not a directory")

    # --------------------------------------------------------------- files
    def create(self, path: str) -> None:
        if self._split_snap(path) is not None:
            raise FsError(-30, "snapshots are read-only")
        self.mds.create(path, client_id=self.client_id)

    def write_file(self, path: str, data: bytes, offset: int = 0) -> None:
        if self._split_snap(path) is not None:
            raise FsError(-30, "snapshots are read-only")
        # caps BEFORE the data mutation: a path-denied caller must not
        # touch the file bytes and only then fail on the dentry update
        self.mds.check_caps(self.client_id, "w", path)
        self._snapc_sync()
        ent = self.mds.lookup(path)
        if ent["type"] != "file":
            raise FsError(-21, f"{path!r} is a directory")
        # cap-less mutation: revoke holders first (their caches/buffers
        # must not survive a write they never saw)
        self.mds.invalidate(path)
        self._data(ent["ino"]).write(offset, data)
        ent["size"] = max(ent["size"], offset + len(data))
        ent["mtime"] = time.time()
        self.mds.set_entry(path, ent, client_id=self.client_id)

    def read_file(self, path: str, offset: int = 0,
                  length: int | None = None) -> bytes:
        snap = self._split_snap(path)
        if snap is not None:
            sid, root, resolved = snap
            ent = self.mds.snap_lookup(sid, root, resolved)
            if ent["type"] != "file":
                raise FsError(-21, f"{path!r} is a directory")
            size = int(ent.get("size", 0))
            if length is None:
                length = max(0, size - offset)
            length = max(0, min(length, size - offset))
            return self._read_snap_data(ent, sid, offset, length)
        ent = self.mds.lookup(path)
        if ent["type"] != "file":
            raise FsError(-21, f"{path!r} is a directory")
        if length is None:
            length = max(0, ent["size"] - offset)
        length = max(0, min(length, ent["size"] - offset))
        return self._data(ent["ino"]).read(offset, length)

    def truncate(self, path: str, size: int) -> None:
        self.mds.check_caps(self.client_id, "w", path)
        ent = self.mds.lookup(path)
        if ent["type"] != "file":
            raise FsError(-21, f"{path!r} is a directory")
        self.mds.invalidate(path)
        ent = self.mds.lookup(path)  # revokes may have flushed size
        if size > ent["size"]:
            self._data(ent["ino"]).write(
                ent["size"], b"\0" * (size - ent["size"]))
        elif size < ent["size"]:
            # zero the cut region so a later grow reads POSIX holes,
            # not resurrected pre-truncate bytes
            self._data(ent["ino"]).write(
                size, b"\0" * (ent["size"] - size))
        ent["size"] = size
        ent["mtime"] = time.time()
        self.mds.set_entry(path, ent, client_id=self.client_id)

    def unlink(self, path: str) -> None:
        # caps BEFORE the data purge: rm_entry's own gate fires only
        # after the object data would already be gone
        self.mds.check_caps(self.client_id, "w", path)
        ent = self.mds.lookup(path)
        if ent["type"] != "file":
            raise FsError(-21, f"{path!r} is a directory (use rmdir)")
        self.mds.invalidate(path)
        self._data(ent["ino"]).remove()
        self.mds.rm_entry(path, client_id=self.client_id)

    def stat(self, path: str) -> dict:
        snap = self._split_snap(path)
        if snap is not None:
            sid, root, resolved = snap
            ent = dict(self.mds.snap_lookup(sid, root, resolved))
            ent.setdefault("size", 0)
            return ent
        ent = dict(self.mds.lookup(path))
        ent.setdefault("size", 0)
        return ent

    def rename(self, src: str, dst: str) -> None:
        """Same-type rename; directory renames move the SUBTREE (the
        single-rank slice of the MDS rename machinery)."""
        self.mds.rename(src, dst, client_id=self.client_id)
