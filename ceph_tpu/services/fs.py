"""fs-lite: a POSIX-ish file layer over RADOS, metadata via mds-lite.

The capability slice of CephFS (src/mds + src/client/Client.cc):
metadata ops route through an MdsDaemon whose mutations are MDLog-
journaled (services/mds.py) — directories are omap-backed dentry
tables, and an MDS crash/restart replays the journal.  File DATA never
touches the MDS: it stripes over RADOS objects via the same
file_layout_t algebra the reference's Striper uses (Client ->
Objecter -> OSD).

Capabilities: `open()` returns an FsFile holding caps from the MDS —
"r" caches the file content client-side (cached reads, Fc/Fr role),
"w" buffers writes locally (Fb/Fw role) until flush/close/revoke.  A
conflicting open on another mount revokes first: the holder flushes
synchronously, so readers-after-writers always see flushed bytes.

Multi-active subtree partitioning is the next widening; snapshots are
not implemented.
"""

from __future__ import annotations

import posixpath
import threading
import time
import uuid

from ..client.rados import RadosClient, RadosError
from ..client.striper import FileLayout, StripedObject
from .mds import FsError, MdsDaemon, _norm

_DATA_PREFIX = "fs_data.{ino}"

__all__ = ["FsClient", "FsError", "FsFile", "MdsDaemon"]


class FsFile:
    """An open file handle under caps (the Fh + cap-ref role)."""

    def __init__(self, fs: "FsClient", path: str, ent: dict, caps: str):
        self._fs = fs
        self.path = path
        self.ino = ent["ino"]
        self.caps = caps
        self._size = ent["size"]
        self._cache: bytes | None = None       # "r": whole-file cache
        self._buffered: list[tuple[int, bytes]] = []  # "w": write-back
        self._lock = threading.RLock()
        self.cache_reads = 0    # served from cache (observability/tests)
        self.closed = False

    # ---------------------------------------------------------------- io
    # Lock order is ALWAYS mds._lock -> handle._lock (both RLocks): the
    # revoke path (MdsDaemon.open holds mds._lock, calls _on_revoke
    # which takes h._lock) and the io paths below (which call back into
    # the MDS) would otherwise ABBA-deadlock across two mounts.
    def read(self, offset: int = 0, length: int | None = None) -> bytes:
        with self._fs.mds._lock, self._lock:
            self._assert_open()
            if "r" not in self.caps and not self._buffered:
                # no lease: the size may have moved under us — re-stat
                # from the MDS (the uncapped read goes to authority)
                self._size = int(self._fs.mds.lookup(self.path)
                                 .get("size", 0))
            if length is None:
                length = max(0, self._size - offset)
            length = max(0, min(length, self._size - offset))
            if self._buffered:
                # read-your-writes through the buffer: flatten first
                self._flush_locked()
            if "r" in self.caps:
                if self._cache is None:
                    self._cache = self._fs._data(self.ino).read(
                        0, self._size)
                else:
                    self.cache_reads += 1
                return self._cache[offset:offset + length]
            return self._fs._data(self.ino).read(offset, length)

    def write(self, data: bytes, offset: int | None = None) -> None:
        with self._fs.mds._lock, self._lock:
            self._assert_open()
            if "w" not in self.caps:
                raise FsError(-9, f"{self.path!r} not open for write")
            if offset is None:
                offset = self._size
            self._buffered.append((offset, bytes(data)))
            self._size = max(self._size, offset + len(data))
            self._cache = None  # cache no longer covers the new extent

    def flush(self) -> None:
        with self._fs.mds._lock, self._lock:
            self._assert_open()
            self._flush_locked()

    def _flush_locked(self) -> None:
        if not self._buffered:
            return
        so = self._fs._data(self.ino)
        for offset, data in self._buffered:
            so.write(offset, data)
        self._buffered.clear()
        ent = self._fs.mds.lookup(self.path)
        if self._size != ent.get("size"):
            ent["size"] = max(int(ent.get("size", 0)), self._size)
            ent["mtime"] = time.time()
            self._fs.mds.set_entry(self.path, ent)
        self._size = ent["size"]

    def close(self) -> None:
        with self._fs.mds._lock, self._lock:
            if self.closed:
                return
            self._flush_locked()
            self.closed = True
        self._fs._close_handle(self)

    def _assert_open(self) -> None:
        if self.closed:
            raise FsError(-9, f"{self.path!r} is closed")

    def __enter__(self) -> "FsFile":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class FsClient:
    """One mounted filesystem view (libcephfs Client shape).  Shares an
    MdsDaemon with other mounts; creates a private one when none is
    given (still journaled — single-mount callers get crash safety for
    free)."""

    def __init__(self, client: RadosClient, pool: str,
                 layout: FileLayout | None = None,
                 mds: MdsDaemon | None = None,
                 client_id: str | None = None):
        self.client = client
        self.pool = pool
        self.layout = layout or FileLayout(stripe_unit=65536,
                                           stripe_count=4,
                                           object_size=1 << 22)
        self.mds = mds or MdsDaemon(client, pool)
        self.client_id = client_id or f"fsclient-{uuid.uuid4().hex[:8]}"
        self._handles: dict[str, list[FsFile]] = {}
        self._hlock = threading.Lock()
        self.mds.register_session(self.client_id, self._on_revoke)

    def unmount(self) -> None:
        with self._hlock:
            handles = [h for hs in self._handles.values() for h in hs]
        for h in handles:
            h.close()
        self.mds.unregister_session(self.client_id)

    # ------------------------------------------------------------ helpers
    def _data(self, ino: str) -> StripedObject:
        return StripedObject(self.client, self.pool,
                             _DATA_PREFIX.format(ino=ino), self.layout)

    def _on_revoke(self, path: str) -> None:
        """MDS cap revoke: flush buffered writes, drop caches (the
        client-side CHECK_CAPS/flush path)."""
        with self._hlock:
            handles = list(self._handles.get(path, []))
        for h in handles:
            with h._lock:
                if not h.closed:
                    h._flush_locked()
                h._cache = None
                h.caps = ""

    def _close_handle(self, h: FsFile) -> None:
        with self._hlock:
            hs = self._handles.get(h.path, [])
            if h in hs:
                hs.remove(h)
            if not hs:
                self._handles.pop(h.path, None)
        self.mds.release(self.client_id, h.path)

    # ------------------------------------------------------ open/caps API
    def open(self, path: str, mode: str = "r") -> FsFile:
        """Open with caps: "r" (cached reads), "w"/"rw" (buffered
        writes); "w" creates if missing."""
        path = _norm(path)
        if "w" in mode:
            try:
                self.mds.lookup(path)
            except FsError:
                self.create(path)
        grant = self.mds.open(self.client_id, path, mode)
        h = FsFile(self, path, grant["ent"], grant["caps"])
        with self._hlock:
            self._handles.setdefault(path, []).append(h)
        return h

    # ---------------------------------------------------------- directory
    def mkdir(self, path: str) -> None:
        self.mds.mkdir(path)

    def listdir(self, path: str) -> list[str]:
        self._assert_dir(path)
        return sorted(self.mds.entries(_norm(path)))

    def rmdir(self, path: str) -> None:
        self.mds.rmdir(path)

    def _assert_dir(self, path: str) -> None:
        ent = self.mds.lookup(path)
        if ent["type"] != "dir":
            raise FsError(-20, f"{path!r} is not a directory")

    # --------------------------------------------------------------- files
    def create(self, path: str) -> None:
        self.mds.create(path)

    def write_file(self, path: str, data: bytes, offset: int = 0) -> None:
        ent = self.mds.lookup(path)
        if ent["type"] != "file":
            raise FsError(-21, f"{path!r} is a directory")
        # cap-less mutation: revoke holders first (their caches/buffers
        # must not survive a write they never saw)
        self.mds.invalidate(path)
        self._data(ent["ino"]).write(offset, data)
        ent["size"] = max(ent["size"], offset + len(data))
        ent["mtime"] = time.time()
        self.mds.set_entry(path, ent)

    def read_file(self, path: str, offset: int = 0,
                  length: int | None = None) -> bytes:
        ent = self.mds.lookup(path)
        if ent["type"] != "file":
            raise FsError(-21, f"{path!r} is a directory")
        if length is None:
            length = max(0, ent["size"] - offset)
        length = max(0, min(length, ent["size"] - offset))
        return self._data(ent["ino"]).read(offset, length)

    def truncate(self, path: str, size: int) -> None:
        ent = self.mds.lookup(path)
        if ent["type"] != "file":
            raise FsError(-21, f"{path!r} is a directory")
        self.mds.invalidate(path)
        ent = self.mds.lookup(path)  # revokes may have flushed size
        if size > ent["size"]:
            self._data(ent["ino"]).write(
                ent["size"], b"\0" * (size - ent["size"]))
        elif size < ent["size"]:
            # zero the cut region so a later grow reads POSIX holes,
            # not resurrected pre-truncate bytes
            self._data(ent["ino"]).write(
                size, b"\0" * (ent["size"] - size))
        ent["size"] = size
        ent["mtime"] = time.time()
        self.mds.set_entry(path, ent)

    def unlink(self, path: str) -> None:
        ent = self.mds.lookup(path)
        if ent["type"] != "file":
            raise FsError(-21, f"{path!r} is a directory (use rmdir)")
        self.mds.invalidate(path)
        self._data(ent["ino"]).remove()
        self.mds.rm_entry(path)

    def stat(self, path: str) -> dict:
        ent = dict(self.mds.lookup(path))
        ent.setdefault("size", 0)
        return ent

    def rename(self, src: str, dst: str) -> None:
        """Same-type rename; directory renames move the SUBTREE (the
        single-rank slice of the MDS rename machinery)."""
        self.mds.rename(src, dst)
