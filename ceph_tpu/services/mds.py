"""mds-lite: journaled metadata server with capability leases.

The capability slice of the reference MDS core (src/mds/):

- **MDLog / EMetaBlob** (MDLog.cc, journal/EMetaBlob.cc): every
  metadata mutation is appended to a durable RADOS-backed journal
  BEFORE it touches the dentry tables; a crash between journal append
  and apply replays on the next start (Journaler replay role), so the
  namespace can never lose an acked mutation.  Applied entries are
  expired and trimmed in segments (LogSegment trim role).
- **Capabilities** (Capability.h, Locker.cc): a client opens a file and
  is granted caps — "r" lets it cache reads, "w" lets it buffer
  writes.  Conflicting opens REVOKE outstanding caps first: the holder
  flushes its buffered state synchronously before the new grant is
  issued (the cap revoke round-trip).  Grants carry a lease TTL; an
  expired lease is reclaimable without a round-trip (session-death
  safety).

Multi-active: `MdsCluster` runs N ranks over the same pool with
DIRECTORY-SUBTREE authority partitioning (MDCache subtree map +
Migrator roles): every op routes to the rank owning the dentry's
parent directory (longest-prefix match over a durable subtree map),
`export_subtree` hands a subtree to another rank (caps on the moved
subtree are revoked; the map update is durable), and `balance()`
re-exports the hottest subtree off the busiest rank (MDBalancer).
Because dentry tables live in shared RADOS omap objects, migration is
an authority transfer — no cache or journal copying, the part of the
reference's Migrator that exists only because its metadata is cached
per-MDS.  Cross-rank renames take both ranks' locks in rank order and
journal in both (apply is idempotent, so dual-journal replay is safe).

The daemons are in-process objects shared by FsClient mounts (the
fs.py data path stays client->RADOS, exactly the reference's split:
metadata through the MDS, file bytes never touch it).  MdsCluster
exposes the same surface as MdsDaemon, so FsClient mounts either.
"""

from __future__ import annotations

import posixpath
import threading
import time
import uuid

from ..client.rados import RadosClient, RadosError
from ..msg.wire import pack_value, unpack_value

_DIR_OID = "fs_dir.{path}"
_SNAPDIR_OID = "fs_snapdir.{snapid:x}.{path}"
_SNAPTABLE_OID = "fs_snaptable"
_JOURNAL_OID = "mds_journal.{rank}"
_APPLIED_KEY = "_applied"          # journal omap: high-water of applied seqs
_TRIM_EVERY = 64                   # expire applied entries in batches


class FsError(Exception):
    def __init__(self, code: int, what: str):
        super().__init__(what)
        self.code = code


def _norm(path: str) -> str:
    return posixpath.normpath("/" + path.strip().lstrip("/"))


class _Session:
    def __init__(self, client_id: str, revoke_cb, vt=None,
                 ticket_provider=None):
        self.client_id = client_id
        self.revoke_cb = revoke_cb   # revoke_cb(path) -> None (flush+drop)
        self.vt = vt                 # VerifiedTicket (auth clusters)
        # () -> fresh ticket blob; lets a long-lived mount re-present a
        # renewed mds ticket instead of bricking at TTL expiry
        self.ticket_provider = ticket_provider


class MdsDaemon:
    LEASE_TTL = 30.0  # seconds; mirrors mds_session_cap lease behavior

    def __init__(self, client: RadosClient, pool: str, rank: int = 0,
                 auth=None, standby: bool = False):
        self.client = client
        self.pool = pool
        self.rank = rank
        # cephx gate (Server::handle_client_session + MDSAuthCaps
        # enforcement role): sessions must present an "mds" service
        # ticket; namespace mutations and file opens check the
        # session's caps with path restrictions.  The data plane is
        # additionally bound by the entity's pool caps at the OSDs.
        self.auth = auth
        # per-top-level-prefix op accounting (MDBalancer pop counters)
        self.dir_ops: dict[str, int] = {}
        self._lock = threading.RLock()
        self._sessions: dict[str, _Session] = {}
        # path -> {client_id: (caps "r"/"rw", expires_at)}
        self._caps: dict[str, dict[str, tuple[str, float]]] = {}
        self._journal_oid = _JOURNAL_OID.format(rank=rank)
        self._seq = 0
        self._applied = 0
        self._ensure_root()
        if not standby:
            self.replay()
        # standby-replay construction defers the journal apply to
        # promotion: a hot spare must never APPLY while the active
        # serves (re-applying an old entry behind the active's newer
        # write would clobber shared dentry tables) — it only keeps
        # its journal view warm (StandbyReplayMds below).

    # ------------------------------------------------------------- journal
    def _ensure_root(self) -> None:
        try:
            self.client.omap_get(self.pool, _DIR_OID.format(path="/"))
        except RadosError:
            self.client.omap_set(self.pool, _DIR_OID.format(path="/"), {})

    def _journal_entries(self) -> dict:
        try:
            return self.client.omap_get(self.pool, self._journal_oid)
        except RadosError:
            return {}

    def replay(self) -> int:
        """Re-apply journal entries past the applied high-water (MDLog
        replay on MDS start).  Apply is idempotent, so a crash anywhere
        in journal->apply->mark is safe.  Returns entries replayed."""
        raw = self._journal_entries()
        self._applied = int(raw.get(_APPLIED_KEY, b"0") or 0)
        seqs = sorted(int(k, 16) for k in raw if k != _APPLIED_KEY)
        self._seq = max(seqs) if seqs else self._applied
        replayed = 0
        for seq in seqs:
            if seq <= self._applied:
                continue
            self._apply(unpack_value(raw[f"{seq:016x}"]))
            self._mark_applied(seq)
            replayed += 1
        return replayed

    def _account(self, path: str) -> None:
        top = "/" + _norm(path).split("/", 2)[1] if _norm(path) != "/" \
            else "/"
        self.dir_ops[top] = self.dir_ops.get(top, 0) + 1

    def submit(self, op: dict) -> None:
        """Journal, then apply, then advance the applied mark — the
        EMetaBlob submit_entry/flush contract (durability before ack)."""
        self._account(op.get("path") or op.get("src") or "/")
        with self._lock:
            self._seq += 1
            seq = self._seq
            self.client.omap_set(self.pool, self._journal_oid,
                                 {f"{seq:016x}": pack_value(op)})
            self._apply(op)
            self._mark_applied(seq)

    def _mark_applied(self, seq: int) -> None:
        self._applied = max(self._applied, seq)
        self.client.omap_set(self.pool, self._journal_oid,
                             {_APPLIED_KEY: str(self._applied).encode()})
        if seq % _TRIM_EVERY == 0:
            self._trim()

    def _trim(self) -> None:
        """Expire applied entries (LogSegment trim): the journal stays
        bounded; only the unapplied tail matters for recovery."""
        raw = self._journal_entries()
        dead = [k for k in raw
                if k != _APPLIED_KEY and int(k, 16) <= self._applied]
        if dead:
            self.client.omap_rm(self.pool, self._journal_oid, dead)

    # -------------------------------------------------- dentry-table apply
    # All mutations are expressed as idempotent journal ops: re-applying
    # any prefix/suffix after a crash converges to the same state.
    def _dir_oid(self, path: str) -> str:
        return _DIR_OID.format(path=_norm(path))

    def _raw_entries(self, dirpath: str) -> dict | None:
        try:
            return self.client.omap_get(self.pool, self._dir_oid(dirpath))
        except RadosError:
            return None

    def _apply(self, op: dict) -> None:
        kind = op["op"]
        if kind == "mkdir":
            path = op["path"]
            self.client.omap_set(self.pool, self._dir_oid(path), {})
            self._apply_set_entry(path, op["ent"])
        elif kind == "set_entry":
            self._apply_set_entry(op["path"], op["ent"])
        elif kind == "rm_entry":
            parent, name = posixpath.split(_norm(op["path"]))
            self.client.omap_rm(self.pool, self._dir_oid(parent), [name])
        elif kind == "rmdir":
            path = op["path"]
            try:
                self.client.remove(self.pool, self._dir_oid(path))
            except RadosError:
                pass  # replay after the remove landed
            parent, name = posixpath.split(_norm(path))
            self.client.omap_rm(self.pool, self._dir_oid(parent), [name])
        elif kind == "rename":
            self._apply_rename(op["src"], op["dst"], op["ent"])
        elif kind == "mksnap":
            self._apply_mksnap(op["path"], op["name"], op["snapid"])
        elif kind == "rmsnap":
            self._apply_rmsnap(op["path"], op["name"], op["snapid"])
        elif kind == "rollback_snap":
            self._apply_rollback_snap(op["path"], op["snapid"])
        else:  # pragma: no cover - forward-compat guard
            raise FsError(-22, f"unknown journal op {kind!r}")

    # ------------------------------------------------- snapshots (realm)
    # The SnapServer + SnapRealm capability (src/mds/SnapServer.h:32,
    # src/mds/SnapRealm.h) re-shaped: snapids come from the mon's
    # self-managed pool snaps (data-object COW happens in the OSDs via
    # the SnapContext clients attach), the snap TABLE is a journaled
    # omap object shared by every rank, and the directory-tree METADATA
    # at snap time is frozen by copying the subtree's dentry omaps to
    # per-snapid objects.  Simplification vs the reference: the write
    # SnapContext is filesystem-global, not per-realm — a post-snapshot
    # write anywhere clones the touched objects (extra clones, same
    # correctness; trim removes them).
    def _snapdir_oid(self, snapid: int, path: str) -> str:
        return _SNAPDIR_OID.format(snapid=snapid, path=_norm(path))

    def snap_table(self) -> dict[tuple[str, str], int]:
        """{(dirpath, snapname): snapid} — the live snap table."""
        try:
            omap = self.client.omap_get(self.pool, _SNAPTABLE_OID)
        except RadosError:
            return {}
        out = {}
        for k, v in omap.items():
            rec = unpack_value(bytes(v))
            out[(rec["path"], rec["name"])] = int(rec["snapid"])
        return out

    def snap_context(self) -> tuple[int, list]:
        """(seq, snapids newest-first) for the data-pool write
        SnapContext — what clients attach so the OSDs clone-on-write
        (the snaprealm get_snap_context role).  Cached (the reference
        caches it with client caps): the per-WRITE cost must not be a
        snap-table omap scan; mksnap/rmsnap invalidate."""
        cached = getattr(self, "_snapc_cache", None)
        if cached is not None:
            return cached
        ids = sorted(self.snap_table().values(), reverse=True)
        self._snapc_cache = (ids[0] if ids else 0, ids)
        return self._snapc_cache

    def _snapc_invalidate(self) -> None:
        self._snapc_cache = None

    def snaps_of(self, dirpath: str) -> dict[str, int]:
        dirpath = _norm(dirpath)
        return {name: sid for (p, name), sid in
                self.snap_table().items() if p == dirpath}

    def snap_covering(self, path: str, name: str) -> tuple[str, int]:
        """Closest ancestor dir with a snapshot `name` (snaprealm
        resolution: a realm covers its whole subtree)."""
        path = _norm(path)
        table = self.snap_table()
        probe = path
        while True:
            sid = table.get((probe, name))
            if sid is not None:
                return probe, sid
            if probe == "/":
                raise FsError(-2, f"no snapshot {name!r} over {path!r}")
            probe = posixpath.split(probe)[0]

    def snap_create(self, dirpath: str, name: str,
                    client_id=None) -> int:
        self._check(client_id, "w", dirpath)
        dirpath = _norm(dirpath)
        if self.lookup(dirpath)["type"] != "dir":
            raise FsError(-20, f"{dirpath!r} is not a directory")
        if name in self.snaps_of(dirpath):
            raise FsError(-17, f"snapshot {name!r} exists")
        # flush every client's buffered data under the realm first: the
        # pool snapshot freezes what the OSDs HOLD, not client caches
        self._revoke_subtree(dirpath, exclude=None)
        self.invalidate(dirpath)
        snapid = self.client.selfmanaged_snap_create(self.pool)
        self.submit({"op": "mksnap", "path": dirpath, "name": name,
                     "snapid": snapid})
        return snapid

    def _apply_mksnap(self, dirpath, name, snapid) -> None:
        self._snapc_invalidate()
        self._freeze_tree(dirpath, snapid)
        self.client.omap_set(self.pool, _SNAPTABLE_OID, {
            f"{snapid:016x}": pack_value({"path": _norm(dirpath),
                                          "name": name,
                                          "snapid": snapid})})

    def _freeze_tree(self, dirpath: str, snapid: int) -> None:
        """Copy the subtree's dentry omaps to the snapshot objects
        (idempotent; replay-safe)."""
        ents = self._raw_entries(dirpath)
        if ents is None:
            return
        self.client.omap_set(self.pool,
                             self._snapdir_oid(snapid, dirpath),
                             dict(ents) or {"_": b""})
        for nm, raw in ents.items():
            if unpack_value(raw)["type"] == "dir":
                self._freeze_tree(posixpath.join(_norm(dirpath), nm),
                                  snapid)

    def snap_remove(self, dirpath: str, name: str,
                    client_id=None) -> None:
        self._check(client_id, "w", dirpath)
        dirpath = _norm(dirpath)
        sid = self.snaps_of(dirpath).get(name)
        if sid is None:
            raise FsError(-2, f"no snapshot {name!r} on {dirpath!r}")
        self.submit({"op": "rmsnap", "path": dirpath, "name": name,
                     "snapid": sid})
        # retire the pool snap: OSDs trim the data clones
        self.client.selfmanaged_snap_remove(self.pool, sid)

    def _apply_rmsnap(self, dirpath, name, snapid) -> None:
        self._snapc_invalidate()
        self._thaw_tree(dirpath, snapid)
        self.client.omap_rm(self.pool, _SNAPTABLE_OID,
                            [f"{snapid:016x}"])

    def _thaw_tree(self, dirpath: str, snapid: int) -> None:
        ents = self.snap_entries_raw(snapid, dirpath)
        for nm, raw in (ents or {}).items():
            if nm != "_" and unpack_value(raw)["type"] == "dir":
                self._thaw_tree(posixpath.join(_norm(dirpath), nm),
                                snapid)
        try:
            self.client.remove(self.pool,
                               self._snapdir_oid(snapid, dirpath))
        except RadosError:
            pass

    # -- snapshot READ surface (the .snap view) -------------------------
    def snap_entries_raw(self, snapid: int, dirpath: str) -> dict | None:
        try:
            omap = self.client.omap_get(
                self.pool, self._snapdir_oid(snapid, dirpath))
        except RadosError:
            return None
        omap.pop("_", None)
        return omap

    def snap_entries(self, snapid: int, dirpath: str) -> dict:
        raw = self.snap_entries_raw(snapid, dirpath)
        if raw is None:
            raise FsError(-2, f"no such directory in snapshot")
        return {k: unpack_value(v) for k, v in raw.items()}

    def snap_lookup(self, snapid: int, snap_root: str,
                    path: str) -> dict:
        path = _norm(path)
        if path == _norm(snap_root):
            return {"type": "dir"}
        parent, nm = posixpath.split(path)
        ent = self.snap_entries(snapid, parent).get(nm)
        if ent is None:
            raise FsError(-2, f"no entry {path!r} in snapshot")
        return ent

    # -- rollback --------------------------------------------------------
    def snap_rollback(self, dirpath: str, name: str,
                      client_id=None) -> None:
        self._check(client_id, "w", dirpath)
        """Restore the subtree (metadata + file data) to its state at
        the snapshot; survives failover because the op is journaled and
        apply is idempotent."""
        dirpath = _norm(dirpath)
        sid = self.snaps_of(dirpath).get(name)
        if sid is None:
            raise FsError(-2, f"no snapshot {name!r} on {dirpath!r}")
        self._revoke_subtree(dirpath, exclude=None)
        self.invalidate(dirpath)
        self.submit({"op": "rollback_snap", "path": dirpath,
                     "snapid": sid})

    def _apply_rollback_snap(self, dirpath: str, snapid: int) -> None:
        self._rollback_tree(dirpath, snapid)

    def _rollback_tree(self, dirpath: str, snapid: int) -> None:
        dirpath = _norm(dirpath)
        frozen = self.snap_entries_raw(snapid, dirpath)
        if frozen is None:
            return
        live = self._raw_entries(dirpath) or {}
        # drop entries born after the snapshot
        dead = [nm for nm in live if nm not in frozen]
        if dead:
            for nm in dead:
                ent = unpack_value(live[nm])
                if ent["type"] == "dir":
                    self._drop_dir_tree(posixpath.join(dirpath, nm))
            self.client.omap_rm(self.pool, self._dir_oid(dirpath), dead)
        # restore the frozen entries (sizes/inos) + recurse; file DATA
        # rolls back via the rados per-object snap_rollback op
        self.client.omap_set(self.pool, self._dir_oid(dirpath),
                             dict(frozen))
        for nm, raw in frozen.items():
            ent = unpack_value(raw)
            sub = posixpath.join(dirpath, nm)
            if ent["type"] == "dir":
                try:
                    self.client.omap_get(self.pool, self._dir_oid(sub))
                except RadosError:
                    self.client.omap_set(self.pool,
                                         self._dir_oid(sub), {})
                self._rollback_tree(sub, snapid)
        # file DATA rolls back client-side (FsClient.snap_rollback):
        # the layout lives with the mount, and the rados per-piece
        # snap_rollback op is idempotent — a crashed roller re-runs

    def _apply_set_entry(self, path: str, ent: dict) -> None:
        parent, name = posixpath.split(_norm(path))
        self.client.omap_set(self.pool, self._dir_oid(parent),
                             {name: pack_value(ent)})

    def _apply_rename(self, src: str, dst: str, ent: dict) -> None:
        if ent["type"] == "dir":
            self._copy_dir_tree(src, dst)
        self._apply_set_entry(dst, ent)
        parent, name = posixpath.split(_norm(src))
        self.client.omap_rm(self.pool, self._dir_oid(parent), [name])
        if ent["type"] == "dir":
            self._drop_dir_tree(src)

    def _copy_dir_tree(self, src: str, dst: str) -> None:
        ents = self._raw_entries(src)
        if ents is None:
            return  # replay: source tree already moved
        self.client.omap_set(self.pool, self._dir_oid(dst), dict(ents))
        for name, raw in ents.items():
            if unpack_value(raw)["type"] == "dir":
                self._copy_dir_tree(posixpath.join(src, name),
                                    posixpath.join(dst, name))

    def _drop_dir_tree(self, src: str) -> None:
        ents = self._raw_entries(src)
        for name, raw in (ents or {}).items():
            if unpack_value(raw)["type"] == "dir":
                self._drop_dir_tree(posixpath.join(src, name))
        try:
            self.client.remove(self.pool, self._dir_oid(src))
        except RadosError:
            pass

    # --------------------------------------------------- metadata service
    def entries(self, dirpath: str) -> dict:
        raw = self._raw_entries(dirpath)
        if raw is None:
            raise FsError(-2, f"no such directory {dirpath!r}")
        return {k: unpack_value(v) for k, v in raw.items()}

    def lookup(self, path: str) -> dict:
        path = _norm(path)
        if path == "/":
            return {"type": "dir"}
        parent, name = posixpath.split(path)
        ent = self.entries(parent).get(name)
        if ent is None:
            raise FsError(-2, f"no such entry {path!r}")
        return ent

    def mkdir(self, path: str, client_id=None) -> None:
        self._check(client_id, "w", path)
        with self._lock:
            path = _norm(path)
            parent, name = posixpath.split(path)
            if name in self.entries(parent):
                raise FsError(-17, f"{path!r} exists")
            self.submit({"op": "mkdir", "path": path,
                         "ent": {"type": "dir", "mtime": time.time()}})

    def rmdir(self, path: str, client_id=None) -> None:
        self._check(client_id, "w", path)
        with self._lock:
            path = _norm(path)
            if path == "/":
                raise FsError(-22, "cannot remove the root")
            ent = self.lookup(path)
            if ent["type"] != "dir":
                raise FsError(-20, f"{path!r} is not a directory")
            if self.entries(path):
                raise FsError(-39, f"{path!r} not empty")
            self.submit({"op": "rmdir", "path": path})

    def create(self, path: str, client_id=None) -> dict:
        self._check(client_id, "w", path)
        with self._lock:
            path = _norm(path)
            parent, name = posixpath.split(path)
            if name in self.entries(parent):
                raise FsError(-17, f"{path!r} exists")
            ent = {"type": "file", "size": 0, "ino": uuid.uuid4().hex,
                   "mtime": time.time()}
            self.submit({"op": "set_entry", "path": path, "ent": ent})
            return ent

    def set_entry(self, path: str, ent: dict, client_id=None) -> None:
        self._check(client_id, "w", path)
        with self._lock:
            self.submit({"op": "set_entry", "path": _norm(path),
                         "ent": ent})

    def rm_entry(self, path: str, client_id=None) -> None:
        self._check(client_id, "w", path)
        with self._lock:
            self.submit({"op": "rm_entry", "path": _norm(path)})

    def rename(self, src: str, dst: str, client_id=None) -> None:
        self._check(client_id, "w", src)
        self._check(client_id, "w", dst)
        with self._lock:
            src, dst = _norm(src), _norm(dst)
            if dst == src or dst.startswith(src + "/"):
                raise FsError(-22,
                              f"cannot move {src!r} into itself "
                              f"({dst!r})")
            ent = self.lookup(src)
            parent, name = posixpath.split(dst)
            if name in self.entries(parent):
                raise FsError(-17, f"{dst!r} exists")
            self._revoke_subtree(src, exclude=None)
            self.submit({"op": "rename", "src": src, "dst": dst,
                         "ent": ent})

    # ------------------------------------------------------- capabilities
    def _check(self, client_id, need: str, path: str) -> None:
        """Session caps gate (MDSAuthCaps::is_capable role): verify the
        caller's session ticket is live and its caps cover `need` at
        `path`.  An expired ticket renews through the session's
        provider (the client re-presents a fresh mon-issued ticket)
        before failing.  No-op on auth-free clusters."""
        if self.auth is None:
            return
        sess = self._sessions.get(client_id)
        if sess is None or sess.vt is None:
            raise FsError(-13, f"no authenticated session {client_id!r}")
        if time.time() > sess.vt.valid_until:
            vt = (self.auth.verify(sess.ticket_provider())
                  if sess.ticket_provider is not None else None)
            if vt is None:
                raise FsError(-13,
                              f"mds ticket expired for {client_id!r}")
            sess.vt = vt
        if not sess.vt.caps.allows(need, path=_norm(path)):
            raise FsError(-13, f"{sess.vt.entity}: mds caps deny "
                               f"{need!r} at {path!r}")

    def check_caps(self, client_id, need: str, path: str) -> None:
        """Public pre-flight gate: callers that mutate DATA before
        metadata (unlink, write) must check caps first, or a denied
        caller destroys file contents the path restriction protects."""
        self._check(client_id, need, path)

    def register_session(self, client_id: str, revoke_cb,
                         ticket: bytes = b"",
                         ticket_provider=None) -> None:
        vt = None
        if self.auth is not None:
            vt = self.auth.verify(ticket)
            if vt is None:
                raise FsError(-13, "mount refused: no/invalid/expired "
                                   "mds ticket")
        with self._lock:
            self._sessions[client_id] = _Session(client_id, revoke_cb,
                                                 vt, ticket_provider)

    def unregister_session(self, client_id: str) -> None:
        with self._lock:
            self._sessions.pop(client_id, None)
            for holders in self._caps.values():
                holders.pop(client_id, None)

    def open(self, client_id: str, path: str, mode: str) -> dict:
        """Grant caps on a file (Locker issue path): mode "r" wants
        cached reads, "w"/"rw" buffered writes.  Conflicting holders
        are revoked (synchronously flushed) first.  Returns the entry
        plus the granted caps + lease expiry."""
        path = _norm(path)
        want_w = "w" in mode
        self._check(client_id, "rw" if want_w else "r", path)
        with self._lock:
            ent = self.lookup(path)
            if ent["type"] != "file":
                raise FsError(-21, f"{path!r} is a directory")
            now = time.time()
            holders = self._caps.setdefault(path, {})
            # expired leases are reclaimable without a round-trip
            for cid, (_c, exp) in list(holders.items()):
                if exp < now or cid not in self._sessions:
                    del holders[cid]
            revoke = []
            for cid, (caps, _exp) in holders.items():
                if cid == client_id:
                    continue
                if want_w or "w" in caps:
                    # multiple readers share; any writer is exclusive
                    revoke.append(cid)
            for cid in revoke:
                self._revoke_one(path, cid)
            ent = self.lookup(path)  # revokes may have flushed size
            caps = "rw" if want_w else "r"
            expires = now + self.LEASE_TTL
            holders[client_id] = (caps, expires)
            return {"ent": ent, "caps": caps, "expires": expires}

    def release(self, client_id: str, path: str) -> None:
        with self._lock:
            holders = self._caps.get(_norm(path), {})
            holders.pop(client_id, None)

    def _revoke_one(self, path: str, client_id: str) -> None:
        """Call the holder's revoke callback (it flushes buffered writes
        through the normal data+set_entry path), then drop the cap.
        RLock: the flush may re-enter set_entry on this thread."""
        sess = self._sessions.get(client_id)
        if sess is not None:
            try:
                sess.revoke_cb(path)
            except Exception:  # noqa: BLE001 - a dead client must not wedge opens
                pass
        self._caps.get(path, {}).pop(client_id, None)

    def _revoke_subtree(self, path: str, exclude: str | None) -> None:
        """Renames invalidate cached paths under the moved subtree."""
        for cap_path in list(self._caps):
            if cap_path == path or cap_path.startswith(path + "/"):
                for cid in list(self._caps[cap_path]):
                    if cid != exclude:
                        self._revoke_one(cap_path, cid)

    def invalidate(self, path: str, exclude: str | None = None) -> None:
        """Revoke every cap on `path` (used by cap-less mutation APIs —
        write_file/truncate/unlink — so 'r' holders cannot keep serving
        pre-mutation cache)."""
        path = _norm(path)
        with self._lock:
            for cid in list(self._caps.get(path, {})):
                if cid != exclude:
                    self._revoke_one(path, cid)

    def caps_held(self, path: str) -> dict:
        with self._lock:
            return {cid: caps for cid, (caps, _e)
                    in self._caps.get(_norm(path), {}).items()}


_SUBTREE_OID = "mds_subtreemap"


class _OrderedLocks:
    """Acquire a fixed list of locks in order; release in reverse."""

    def __init__(self, locks):
        self._locks = locks

    def __enter__(self):
        for lk in self._locks:
            lk.acquire()
        return self

    def __exit__(self, *exc):
        for lk in reversed(self._locks):
            lk.release()


class StandbyReplayMds:
    """Hot spare for one rank (the mds standby-replay role,
    src/mds/MDSRank standby_replay): tails the active's journal
    continuously so promotion costs only the UNAPPLIED delta, not a
    cold construction + full journal scan.

    Shape difference from the reference, deliberate: our dentry tables
    live in shared RADOS omap (not per-MDS memory), so the standby must
    never APPLY while the active serves — re-applying an old entry
    behind the active's newer write would clobber shared state.  It
    polls the journal to keep its view (and the object's read path)
    warm and tracks the lag; promote() performs one replay() that
    applies exactly the entries the dead active journaled but never
    marked applied (the crash window), then the daemon serves."""

    def __init__(self, client: RadosClient, pool: str, rank: int = 0,
                 auth=None, poll: float = 0.05):
        self.mds = MdsDaemon(client, pool, rank=rank, auth=auth,
                             standby=True)
        self.lag = 0          # journaled-but-unapplied entries seen
        self._promoted = False
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._tail, args=(poll,),
            name=f"mds-standby-{rank}", daemon=True)
        self._thread.start()

    def _tail(self, poll: float) -> None:
        while not self._stop.wait(poll):
            try:
                raw = self.mds._journal_entries()
                applied = int(raw.get(_APPLIED_KEY, b"0") or 0)
                self.lag = sum(1 for k in raw
                               if k != _APPLIED_KEY
                               and int(k, 16) > applied)
            except Exception:  # noqa: BLE001 - cluster hiccup; re-poll
                pass

    def promote(self) -> tuple[MdsDaemon, int]:
        """Take over the rank: stop tailing, apply the unapplied tail
        (the dead active's crash window), return the live daemon and
        how many entries the takeover had to replay."""
        self._stop.set()
        self._thread.join(timeout=5)
        replayed = self.mds.replay()
        self._promoted = True
        return self.mds, replayed

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)


class MdsCluster:
    """N active ranks with subtree authority partitioning (see module
    docstring).  Drop-in for MdsDaemon in FsClient."""

    def __init__(self, client: RadosClient, pool: str, n_ranks: int = 2,
                 auth=None):
        self.client = client
        self.pool = pool
        self.ranks = [MdsDaemon(client, pool, rank=i, auth=auth)
                      for i in range(n_ranks)]
        self._maplock = threading.RLock()
        try:
            raw = client.omap_get(pool, _SUBTREE_OID)
            self._map = {k: int(unpack_value(v)) for k, v in raw.items()}
        except RadosError:
            self._map = {}
        if "/" not in self._map:
            self._map["/"] = 0
            self._save_map()

    def _save_map(self) -> None:
        self.client.omap_set(self.pool, _SUBTREE_OID,
                             {k: pack_value(v)
                              for k, v in self._map.items()})

    # ------------------------------------------------------- authority map
    def authority_rank(self, dirpath: str) -> int:
        """Longest-prefix subtree match for a DIRECTORY path (the
        MDCache subtree map get_subtree_root walk)."""
        dirpath = _norm(dirpath)
        with self._maplock:
            best, best_len = 0, -1
            for root, rank in self._map.items():
                if (dirpath == root or root == "/"
                        or dirpath.startswith(root + "/")):
                    if len(root) > best_len:
                        best, best_len = rank, len(root)
            return best

    def _dir_auth(self, dirpath: str) -> MdsDaemon:
        return self.ranks[self.authority_rank(dirpath)]

    def _entry_auth(self, path: str) -> MdsDaemon:
        """Ops on a dentry route to its PARENT directory's authority
        (the dentry lives in the parent's table, as in the MDS)."""
        path = _norm(path)
        if path == "/":
            return self.ranks[0]
        return self._dir_auth(posixpath.split(path)[0])

    # ------------------------------------------- export/import + balancer
    def export_subtree(self, path: str, to_rank: int) -> None:
        """Hand authority for `path` (a directory) to another rank
        (Migrator export_dir): revoke caps under the subtree at the old
        authority, then commit the durable map update."""
        path = _norm(path)
        if not 0 <= to_rank < len(self.ranks):
            raise FsError(-22, f"no such rank {to_rank}")
        old = self._dir_auth(path)
        if old.lookup(path)["type"] != "dir":
            raise FsError(-20, f"{path!r} is not a directory")
        old._revoke_subtree(path, exclude=None)
        with self._maplock:
            self._map[path] = to_rank
            self._save_map()
        # the moved subtree's heat moves WITH it: stale counters on the
        # old rank would keep it looking busy forever
        top = "/" + path.split("/", 2)[1] if path != "/" else "/"
        heat = old.dir_ops.pop(top, 0)
        dst = self.ranks[to_rank]
        dst.dir_ops[top] = dst.dir_ops.get(top, 0) + heat

    def balance(self) -> dict | None:
        """One MDBalancer pass: move the hottest exportable subtree off
        the busiest rank to the least busy.  Returns the move made.
        Counters HALVE each pass (the balancer's decaying load
        average), so one historical burst cannot drive moves forever."""
        for r in self.ranks:
            for k in list(r.dir_ops):
                r.dir_ops[k] //= 2
                if not r.dir_ops[k]:
                    del r.dir_ops[k]
        loads = [sum(r.dir_ops.values()) for r in self.ranks]
        src = max(range(len(self.ranks)), key=lambda i: loads[i])
        dst = min(range(len(self.ranks)), key=lambda i: loads[i])
        if src == dst or loads[src] == 0:
            return None
        candidates = sorted(self.ranks[src].dir_ops.items(),
                            key=lambda kv: -kv[1])
        for top, _heat in candidates:
            if top != "/" and self.authority_rank(top) == src:
                try:
                    self.export_subtree(top, dst)
                except FsError:
                    continue
                return {"subtree": top, "from": src, "to": dst}
        return None

    # ------------------------------------------------ snapshot routing
    def snap_create(self, dirpath: str, name: str,
                    client_id=None) -> int:
        a = self._entry_auth(dirpath)
        for r in self.ranks:          # flush EVERY rank's caps under it
            r._revoke_subtree(_norm(dirpath), exclude=None)
        sid = a.snap_create(dirpath, name, client_id=client_id)
        for r in self.ranks:
            r._snapc_invalidate()
        return sid

    def snap_remove(self, dirpath: str, name: str,
                    client_id=None) -> None:
        self._entry_auth(dirpath).snap_remove(dirpath, name,
                                              client_id=client_id)
        for r in self.ranks:
            r._snapc_invalidate()

    def snap_rollback(self, dirpath: str, name: str,
                      client_id=None) -> None:
        a = self._entry_auth(dirpath)
        for r in self.ranks:
            r._revoke_subtree(_norm(dirpath), exclude=None)
        a.snap_rollback(dirpath, name, client_id=client_id)

    def snaps_of(self, dirpath: str):
        return self.ranks[0].snaps_of(dirpath)

    def snap_context(self):
        return self.ranks[0].snap_context()

    def snap_covering(self, path: str, name: str):
        return self.ranks[0].snap_covering(path, name)

    def snap_entries(self, snapid: int, dirpath: str):
        return self.ranks[0].snap_entries(snapid, dirpath)

    def snap_lookup(self, snapid: int, snap_root: str, path: str):
        return self.ranks[0].snap_lookup(snapid, snap_root, path)

    # --------------------------------------- MdsDaemon-compatible surface
    def register_session(self, client_id: str, revoke_cb,
                         ticket: bytes = b"",
                         ticket_provider=None) -> None:
        for r in self.ranks:
            r.register_session(client_id, revoke_cb, ticket,
                               ticket_provider)

    def check_caps(self, client_id, need: str, path: str) -> None:
        # sessions are registered on every rank: rank 0 sees the same
        # ticket state as the authoritative rank
        self.ranks[0].check_caps(client_id, need, path)

    def unregister_session(self, client_id: str) -> None:
        for r in self.ranks:
            r.unregister_session(client_id)

    def lookup(self, path: str) -> dict:
        return self._entry_auth(path).lookup(path)

    def entries(self, dirpath: str) -> dict:
        return self._dir_auth(dirpath).entries(dirpath)

    def mkdir(self, path: str, client_id=None) -> None:
        self._entry_auth(path).mkdir(path, client_id=client_id)

    def rmdir(self, path: str, client_id=None) -> None:
        self._entry_auth(path).rmdir(path, client_id=client_id)

    def create(self, path: str, client_id=None) -> dict:
        return self._entry_auth(path).create(path, client_id=client_id)

    def set_entry(self, path: str, ent: dict, client_id=None) -> None:
        self._entry_auth(path).set_entry(path, ent, client_id=client_id)

    def rm_entry(self, path: str, client_id=None) -> None:
        self._entry_auth(path).rm_entry(path, client_id=client_id)

    def open(self, client_id: str, path: str, mode: str) -> dict:
        auth = self._entry_auth(path)
        auth._account(path)
        return auth.open(client_id, path, mode)

    def release(self, client_id: str, path: str) -> None:
        self._entry_auth(path).release(client_id, path)

    def invalidate(self, path: str, exclude: str | None = None) -> None:
        self._entry_auth(path).invalidate(path, exclude)

    # FsFile io paths take mds._lock BEFORE handle._lock (fs.py lock
    # order).  For a cluster that must mean the RANK locks — in rank
    # order — so the global order (rank0 < rank1 < ... < handles) holds
    # for every path: rank.open takes (one rank, handle), flushes take
    # (all ranks in order, handle), cross-rank rename takes (two ranks
    # in order, handles via revoke).  No cycle exists.
    @property
    def _lock(self):
        return _OrderedLocks([r._lock for r in self.ranks])

    def _rename_subtree_map(self, src: str, dst: str) -> None:
        """Rewrite durable subtree-map keys at/under src to dst paths —
        a moved subtree keeps its authority assignment (Migrator keeps
        subtree bounds across rename); without this the moved tree
        silently reverts to rank 0 and a later mkdir at the old path
        inherits a stale rank."""
        with self._maplock:
            moved, old_keys = {}, []
            for root in list(self._map):
                if root == src or root.startswith(src + "/"):
                    moved[dst + root[len(src):]] = self._map.pop(root)
                    old_keys.append(root)
            if moved:
                self._map.update(moved)
                # write the merged map FIRST: a crash between the two
                # ops then leaves a transient stale old-path key, never
                # a lost assignment.  Remove exactly the keys we moved —
                # another live MdsCluster on the same pool may have
                # persisted keys this instance hasn't loaded, and they
                # must survive.
                self._save_map()
                self.client.omap_rm(self.pool, _SUBTREE_OID, old_keys)

    def rename(self, src: str, dst: str, client_id=None) -> None:
        """Renames take ALL rank locks in RANK ORDER (no ABBA between
        two renames) because the moved subtree may contain interior
        subtree roots whose caps live at ranks other than the two
        parents' — those must be revoked too, and the authority-map
        rewrite must be atomic with the namespace change (a lookup
        between them would route to a stale rank).  The op is journaled
        at both parents' ranks — apply is idempotent, so each rank's
        replay converges (the slave-request rename role)."""
        src, dst = _norm(src), _norm(dst)
        # sessions are registered on every rank; rank 0 carries the
        # same ticket state as the authoritative ranks
        self.ranks[0]._check(client_id, "w", src)
        self.ranks[0]._check(client_id, "w", dst)
        if dst == src or dst.startswith(src + "/"):
            raise FsError(-22,
                          f"cannot move {src!r} into itself ({dst!r})")

        def _involved():
            a, b = self._entry_auth(src), self._entry_auth(dst)
            # lock only the ranks the rename can touch: the two
            # parents' plus any rank holding authority INSIDE the moved
            # subtree (interior subtree roots — their cached caps must
            # be revoked too).  The common same-rank,
            # no-interior-subtree rename stays cheap instead of
            # barriering the whole cluster.
            with self._maplock:
                interior = {rank for root, rank in self._map.items()
                            if root == src
                            or root.startswith(src + "/")}
            return a, b, sorted({a.rank, b.rank} | interior)

        a, b, involved = _involved()
        while True:
            with _OrderedLocks([self.ranks[i]._lock
                                for i in involved]):
                # a concurrent export_subtree()/balance() may have moved
                # authority into the subtree between the snapshot and
                # the lock grab — that rank would be neither locked nor
                # revoked, and its buffered client data would survive
                # the rename.  Re-derive under the locks; widen+retry
                # until the set is stable — then hold _maplock through
                # the body so no export can move authority mid-rename
                # (export takes rank locks before _maplock, same order
                # as here, so this cannot deadlock).
                with self._maplock:
                    a, b, needed = _involved()
                    if needed == involved:
                        self._locked_rename(a, b, involved, src, dst)
                        return
                involved = needed

    def _locked_rename(self, a, b, involved, src: str, dst: str) -> None:
        """The rename body; caller holds every involved rank lock."""
        ent = a.lookup(src)
        parent, name = posixpath.split(dst)
        if name in b.entries(parent):
            raise FsError(-17, f"{dst!r} exists")
        for i in involved:
            self.ranks[i]._revoke_subtree(src, exclude=None)
        op = {"op": "rename", "src": src, "dst": dst, "ent": ent}
        a.submit(op)
        if b is not a:
            b.submit(op)  # idempotent re-apply; journals both
        self._rename_subtree_map(src, dst)
        # heat follows ONLY when the top-level entry itself moved
        # (export_subtree's pattern); renaming one deep entry must
        # not drain its old top-level dir's counters
        if src != "/" and src == "/" + src.split("/", 2)[1]:
            new_top = "/" + dst.split("/", 2)[1]
            if new_top != src:
                for i in involved:
                    r = self.ranks[i]
                    heat = r.dir_ops.pop(src, 0)
                    if heat:
                        r.dir_ops[new_top] = (
                            r.dir_ops.get(new_top, 0) + heat)
