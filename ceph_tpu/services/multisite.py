"""Multisite async replication: zone-to-zone rgw sync.

The capability of the reference's RGW multisite machinery (src/rgw/
rgw_sync.cc metadata sync + rgw_data_sync.cc / RGWDataSyncCR: each zone
tails the peer zones' bucket-index logs over HTTP and applies changes
locally, with per-shard sync markers persisted so a restart resumes
where it left off; active-active loops are broken by skipping log
entries the applying zone itself originated).

ZoneSyncAgent runs INSIDE the destination zone: it polls the source
gateway's /admin/bilog endpoint, fetches changed objects with plain S3
GETs, and applies them through the destination gateway's store API
stamped with the ORIGIN zone (so the destination's own bilog entry for
the applied change is not replicated back — the no-ping-pong rule).
Conflicts resolve last-writer-wins by mtime, the reference's object-
mtime squash for non-versioned buckets.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
import urllib.parse

from ..msg.wire import pack_value, unpack_value
from ..utils.log import dout
from .rgw import RgwGateway

_MARKERS_OID = "rgw_sync_markers.{src_zone}"


class ZoneSyncAgent:
    def __init__(self, src_host: str, src_port: int, dst: RgwGateway,
                 src_zone: str, interval: float = 0.2,
                 creds: tuple[str, str] | None = None):
        self.src_host = src_host
        self.src_port = src_port
        self.dst = dst
        self.src_zone = src_zone
        self.interval = interval
        self.creds = creds  # (access_key, secret_key) for SigV4 zones
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.cycles = 0
        self.applied = 0

    # ------------------------------------------------------------ control
    def start(self) -> "ZoneSyncAgent":
        self._thread = threading.Thread(
            target=self._run, name=f"rgw-sync-{self.src_zone}",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)

    # ----------------------------------------------------- http plumbing
    def _request(self, method: str, path_qs: str,
                 body: bytes = b"") -> tuple[int, bytes]:
        headers = {}
        if self.creds is not None:
            from . import s3auth
            path, _, query = path_qs.partition("?")
            headers = s3auth.sign(
                method, f"{self.src_host}:{self.src_port}", path, query,
                body, self.creds[0], self.creds[1])
        conn = http.client.HTTPConnection(self.src_host, self.src_port,
                                          timeout=10)
        try:
            conn.request(method, path_qs, body=body or None,
                         headers=headers)
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    # ------------------------------------------------------- sync markers
    # Persisted in the DESTINATION zone (RGWDataSyncMarker role): a
    # restarted agent resumes from the durable position.
    def _markers_oid(self) -> str:
        return _MARKERS_OID.format(src_zone=self.src_zone)

    def _marker(self, bucket: str) -> int:
        try:
            raw = self.dst.client.omap_get(self.dst.pool,
                                           self._markers_oid())
        except Exception:  # noqa: BLE001 - no markers yet
            return 0
        return int(unpack_value(raw[bucket])) if bucket in raw else 0

    def _set_marker(self, bucket: str, seq: int) -> None:
        self.dst.client.omap_set(self.dst.pool, self._markers_oid(),
                                 {bucket: pack_value(int(seq))})

    # ------------------------------------------------------------ the loop
    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.sync_once()
            except Exception as e:  # noqa: BLE001 - peer down: retry later
                dout("rgw", 5)("sync from %s: %r", self.src_zone, e)

    def _src_buckets(self) -> list[str]:
        st, body = self._request("GET", "/")
        if st != 200:
            return []
        out = []
        for part in body.decode().split("<Name>")[1:]:
            out.append(part.split("</Name>")[0])
        return out

    def sync_once(self) -> int:
        """One full pass over the source's buckets; returns entries
        applied."""
        self.cycles += 1
        applied = 0
        for bucket in self._src_buckets():
            try:
                self.dst.check_bucket(bucket)
            except KeyError:
                self.dst.create_bucket(bucket)  # metadata sync slice
            marker = self._marker(bucket)
            st, body = self._request(
                "GET", f"/admin/bilog?bucket={bucket}&marker={marker}")
            if st != 200:
                continue
            for entry in json.loads(body):
                if entry["zone"] == self.dst.zone:
                    # our own change echoed back: never re-apply
                    self._set_marker(bucket, entry["seq"])
                    continue
                verdict = self._apply(bucket, entry)
                if verdict == "retry":
                    # transient source failure: the marker must NOT
                    # advance past an unapplied entry (silent loss) —
                    # stop this bucket, the next cycle retries
                    break
                if verdict == "applied":
                    applied += 1
                self._set_marker(bucket, entry["seq"])
        self.applied += applied
        return applied

    def _apply(self, bucket: str, entry: dict) -> str:
        """-> "applied" | "skip" (superseded/duplicate) | "retry"."""
        key = entry["key"]
        vid = entry.get("version_id") or None
        op = entry["op"]
        # versioned ops replicate EXACT generations (the bilog carries
        # version ids — rgw data-sync versioned-epoch role); duplicates
        # are detected per-version, not by head mtime
        if (op in ("delete_marker", "delete_version")
                or (op == "put" and vid)) and \
                not self.dst.versioning_enabled(bucket):
            # versioned entries imply the source bucket is versioned:
            # mirror the flag before applying, or the unversioned paths
            # would DROP retained generations instead of keeping them
            self.dst.set_versioning(bucket, True)
        if op == "delete_marker":
            if any(m.get("delete_marker")
                   and m.get("version_id") == vid
                   for m in self.dst.versions_of(bucket, key)):
                return "skip"
            try:
                # the marker replicates with the ORIGIN's id, so the
                # generation graph stays identical across zones (a
                # later delete_version of the marker then applies)
                self.dst.delete_object(bucket, key,
                                       origin=entry["zone"],
                                       mtime=entry["mtime"],
                                       marker_version_id=vid)
            except KeyError:
                pass
            return "applied"
        if op == "delete_version":
            try:
                self.dst.delete_object(bucket, key, version_id=vid,
                                       origin=entry["zone"])
            except KeyError:
                pass  # that generation never made it here / gone
            return "applied"
        if op == "put" and vid:
            if any(m.get("version_id") == vid
                   for m in self.dst.versions_of(bucket, key)):
                return "skip"  # this exact generation already landed
            quoted = urllib.parse.quote(key)
            st, body = self._request(
                "GET", f"/{bucket}/{quoted}?versionId={vid}")
            if st == 404:
                return "skip"  # generation purged at source meanwhile
            if st != 200:
                return "retry"
            self.dst.put_object(bucket, key, body,
                                origin=entry["zone"],
                                mtime=entry["mtime"], version_id=vid)
            return "applied"
        # last-writer-wins by mtime: a newer local change outranks the
        # replicated one (non-versioned-bucket mtime squash)
        try:
            local = self.dst.head_object(bucket, key)
        except KeyError:
            local = None
        if local is not None and local["mtime"] > entry["mtime"]:
            return "skip"
        if op == "delete":
            try:
                self.dst.delete_object(bucket, key,
                                       origin=entry["zone"])
            except KeyError:
                pass  # already gone
            return "applied"
        if local is not None and local["etag"] == entry["etag"]:
            return "skip"  # content already present (e.g. via resync)
        quoted = urllib.parse.quote(key)
        st, body = self._request("GET", f"/{bucket}/{quoted}")
        if st == 404:
            return "skip"  # deleted again at source; its entry follows
        if st != 200:
            return "retry"  # transient source error: do not lose this
        self.dst.put_object(bucket, key, body, origin=entry["zone"],
                            mtime=entry["mtime"])
        return "applied"
