"""rbd-nbd: export RBD images over the NBD protocol.

The capability of the reference's rbd-nbd (src/tools/rbd_nbd/ — expose
an rbd image as a network block device so any NBD client, the Linux
kernel's included, can mount it): a fixed-newstyle NBD server over the
Image read/write/flush surface.

Protocol (the published NBD spec, fixed-newstyle negotiation):
  S: NBDMAGIC IHAVEOPT <u16 handshake flags: FIXED_NEWSTYLE>
  C: <u32 client flags>
  C: IHAVEOPT <u32 option> <u32 len> <data>     (loop)
     NBD_OPT_EXPORT_NAME -> S: <u64 size> <u16 transmission flags>
                               + 124 zero pad; enter transmission
  transmission: C: <magic 0x25609513> <u16 flags> <u16 type>
                   <u64 handle> <u64 offset> <u32 length> [data]
                S: <magic 0x67446698> <u32 error> <u64 handle> [data]

Writes honor the image's exclusive-lock/journaling features (they go
through Image.write); one connection serves one export at a time, the
server hosts many connections.
"""

from __future__ import annotations

import socket
import struct
import threading

from ..msg.tcp import _recv_exact
from .rbd import RBD, RbdError

NBDMAGIC = 0x4E42444D41474943          # "NBDMAGIC"
IHAVEOPT = 0x49484156454F5054          # "IHAVEOPT"
FLAG_FIXED_NEWSTYLE = 1 << 0
CFLAG_FIXED_NEWSTYLE = 1 << 0

OPT_EXPORT_NAME = 1
OPT_ABORT = 2
OPT_LIST = 3

REP_MAGIC = 0x3E889045565A9
REP_ACK = 1
REP_SERVER = 2
REP_ERR_UNSUP = (1 << 31) | 1

REQ_MAGIC = 0x25609513
REPLY_MAGIC = 0x67446698

CMD_READ = 0
CMD_WRITE = 1
CMD_DISC = 2
CMD_FLUSH = 3
CMD_TRIM = 4

TFLAG_HAS_FLAGS = 1 << 0
TFLAG_SEND_FLUSH = 1 << 2
TFLAG_SEND_TRIM = 1 << 5

EIO = 5
EINVAL = 22
ENOSPC = 28

MAX_REQUEST = 32 << 20  # spec-suggested sanity cap per request
CFLAG_NO_ZEROES = 1 << 1


class NbdServer:
    """Serve every image of one pool as NBD exports (rbd-nbd role)."""

    def __init__(self, client, pool: str, host: str = "127.0.0.1",
                 port: int = 0):
        self.client = client
        self.pool = pool
        self.rbd = RBD(client)
        self._ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._ls.bind((host, port))
        self._ls.listen(16)
        self.port = self._ls.getsockname()[1]
        self._stopping = False
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="nbd-accept", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stopping = True
        try:
            self._ls.close()
        except OSError:
            pass

    # ------------------------------------------------------------ accept
    def _accept_loop(self) -> None:
        while not self._stopping:
            try:
                sock, _addr = self._ls.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(sock,),
                             name="nbd-conn", daemon=True).start()

    # ------------------------------------------------------- negotiation
    def _serve_conn(self, sock: socket.socket) -> None:
        try:
            sock.sendall(struct.pack(">QQH", NBDMAGIC, IHAVEOPT,
                                     FLAG_FIXED_NEWSTYLE))
            raw = _recv_exact(sock, 4)
            if raw is None:
                return
            (cflags,) = struct.unpack(">I", raw)
            img = self._negotiate(sock, cflags)
            if img is not None:
                self._transmission(sock, img)
        except OSError:
            pass
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _negotiate(self, sock: socket.socket, cflags: int):
        while True:
            hdr = _recv_exact(sock, 16)
            if hdr is None:
                return None
            magic, opt, ln = struct.unpack(">QII", hdr)
            data = _recv_exact(sock, ln) if ln else b""
            if magic != IHAVEOPT or data is None:
                return None
            if opt == OPT_EXPORT_NAME:
                name = data.decode("utf-8", "replace")
                try:
                    img = self.rbd.open(self.pool, name)
                except RbdError:
                    return None  # spec: option has no error reply path
                tflags = (TFLAG_HAS_FLAGS | TFLAG_SEND_FLUSH
                          | TFLAG_SEND_TRIM)
                # NO_ZEROES clients (the Linux kernel's nbd-client
                # negotiates it) must NOT receive the 124-byte pad, or
                # transmission desynchronises by exactly that much
                pad = b"" if cflags & CFLAG_NO_ZEROES else b"\0" * 124
                sock.sendall(struct.pack(">QH", img.size(), tflags)
                             + pad)
                return img
            if opt == OPT_LIST:
                for name in self.rbd.list(self.pool):
                    payload = struct.pack(">I", len(name.encode())) \
                        + name.encode()
                    sock.sendall(struct.pack(
                        ">QIII", REP_MAGIC, opt, REP_SERVER,
                        len(payload)) + payload)
                sock.sendall(struct.pack(">QIII", REP_MAGIC, opt,
                                         REP_ACK, 0))
            elif opt == OPT_ABORT:
                sock.sendall(struct.pack(">QIII", REP_MAGIC, opt,
                                         REP_ACK, 0))
                return None
            else:
                sock.sendall(struct.pack(">QIII", REP_MAGIC, opt,
                                         REP_ERR_UNSUP, 0))

    # ------------------------------------------------------ transmission
    def _transmission(self, sock: socket.socket, img) -> None:
        try:
            while True:
                hdr = _recv_exact(sock, 28)
                if hdr is None:
                    return
                magic, _flags, cmd, handle, offset, length = \
                    struct.unpack(">IHHQQI", hdr)
                if magic != REQ_MAGIC:
                    return
                if length > MAX_REQUEST and cmd != CMD_DISC:
                    # a u32 straight off the wire must not size an
                    # allocation (4 GiB trim/read would OOM the host)
                    if cmd == CMD_WRITE:
                        return  # can't resync past an unread payload
                    self._reply(sock, EINVAL, handle)
                    continue
                if cmd == CMD_READ:
                    try:
                        data = img.read(offset, length)
                        data += b"\0" * (length - len(data))
                        self._reply(sock, 0, handle, data)
                    except RbdError:
                        self._reply(sock, EINVAL, handle)
                elif cmd == CMD_WRITE:
                    payload = _recv_exact(sock, length)
                    if payload is None:
                        return
                    try:
                        img.write(offset, payload)
                        self._reply(sock, 0, handle)
                    except RbdError:
                        self._reply(sock, ENOSPC, handle)
                elif cmd == CMD_FLUSH:
                    # Image.write is synchronous through librados-style
                    # all-ack commits: nothing is buffered server-side
                    self._reply(sock, 0, handle)
                elif cmd == CMD_TRIM:
                    try:
                        img.write(offset, b"\0" * length)
                        self._reply(sock, 0, handle)
                    except RbdError:
                        self._reply(sock, EIO, handle)
                elif cmd == CMD_DISC:
                    return
                else:
                    self._reply(sock, EINVAL, handle)
        finally:
            img.close()

    @staticmethod
    def _reply(sock: socket.socket, error: int, handle: int,
               data: bytes = b"") -> None:
        sock.sendall(struct.pack(">IIQ", REPLY_MAGIC, error, handle)
                     + data)


class NbdClient:
    """Minimal fixed-newstyle NBD client (the kernel's wire dialect) —
    the other half of the gateway, used by smoke/tests and usable as a
    library client."""

    def __init__(self, port):
        self.sock = socket.create_connection(("127.0.0.1", port),
                                             timeout=10)
        magic, opt, flags = struct.unpack(
            ">QQH", _recv_exact(self.sock, 18))
        assert magic == NBDMAGIC and opt == IHAVEOPT
        self.sock.sendall(struct.pack(">I", 1))  # fixed-newstyle
        self._handle = 0

    def list_exports(self):
        self.sock.sendall(struct.pack(">QII", IHAVEOPT, OPT_LIST, 0))
        names = []
        while True:
            magic, opt, rep, ln = struct.unpack(
                ">QIII", _recv_exact(self.sock, 20))
            payload = _recv_exact(self.sock, ln) if ln else b""
            if rep == REP_ACK:
                return names
            assert rep == REP_SERVER
            (nlen,) = struct.unpack(">I", payload[:4])
            names.append(payload[4:4 + nlen].decode())

    def go(self, name):
        data = name.encode()
        self.sock.sendall(struct.pack(">QII", IHAVEOPT,
                                      OPT_EXPORT_NAME, len(data))
                          + data)
        size, tflags = struct.unpack(">QH",
                                     _recv_exact(self.sock, 10))
        _recv_exact(self.sock, 124)
        return size, tflags

    def _cmd(self, cmd, offset=0, length=0, data=b""):
        self._handle += 1
        self.sock.sendall(struct.pack(
            ">IHHQQI", REQ_MAGIC, 0, cmd, self._handle, offset,
            length) + data)
        if cmd == CMD_DISC:
            return 0, b""
        magic, err, handle = struct.unpack(
            ">IIQ", _recv_exact(self.sock, 16))
        assert magic == REPLY_MAGIC and handle == self._handle
        body = _recv_exact(self.sock, length) \
            if cmd == CMD_READ and err == 0 else b""
        return err, body

    def read(self, offset, length):
        err, data = self._cmd(CMD_READ, offset, length)
        assert err == 0, err
        return data

    def write(self, offset, data):
        err, _ = self._cmd(CMD_WRITE, offset, len(data), data)
        return err

    def flush(self):
        return self._cmd(CMD_FLUSH)[0]

    def trim(self, offset, length):
        return self._cmd(CMD_TRIM, offset, length)[0]

    def close(self):
        try:
            self._cmd(CMD_DISC)
        finally:
            self.sock.close()
