"""NVMe-oF gateway: an NVMe/TCP target exporting rbd images.

The capability slice of the reference's NVMe-oF gateway
(/root/reference/src/nvmeof/ — a control plane deploying an NVMe/TCP
target whose namespaces are rbd images; the data plane there is SPDK):
this module implements the TARGET itself over the NVMe/TCP binary
framing, plus the control surface (add/remove/list namespaces) the
reference drives over gRPC.

Wire shape (NVMe/TCP transport spec): every PDU starts with an 8-byte
common header [type u8][flags u8][hlen u8][pdo u8][plen u32le].
Implemented PDUs: ICReq(0x00)/ICResp(0x01), CapsuleCmd(0x04) carrying
a 64-byte SQE (+ optional in-capsule data), CapsuleResp(0x05) carrying
a 16-byte CQE, and C2HData(0x07) for read payloads.  Commands:

- Fabrics (opcode 0x7f): Connect (attach admin/io queue, return a
  controller id), Property Get/Set (CAP/VS/CC/CSTS — the register
  surface an initiator uses to enable the controller);
- Admin queue: Identify (CNS 01h controller, 00h namespace, 02h active
  namespace list), Set Features (number of queues), Keep Alive;
- IO queues: Read(02h)/Write(01h)/Flush(00h) in 512-byte LBAs, striped
  through the rbd Image data path (exclusive lock, snapshots, object
  map and journaling all apply — the gateway is just another librbd
  client, exactly the reference's layering).

The paired NvmeInitiator speaks the same framing for tests and tools —
the same in-repo-initiator pattern the NBD gateway uses.
"""

from __future__ import annotations

import socket
import struct
import threading

from ..msg.tcp import _recv_exact
from .rbd import RBD, RbdError

LBA_SHIFT = 9                 # 512-byte LBAs
LBA_SIZE = 1 << LBA_SHIFT

# PDU types
ICREQ, ICRESP = 0x00, 0x01
CAPSULE_CMD, CAPSULE_RESP = 0x04, 0x05
H2C_DATA, C2H_DATA = 0x06, 0x07

# opcodes
OP_FLUSH, OP_WRITE, OP_READ = 0x00, 0x01, 0x02
OP_IDENTIFY, OP_SET_FEATURES, OP_KEEP_ALIVE = 0x06, 0x09, 0x18
OP_FABRICS = 0x7F
FCTYPE_PROP_SET, FCTYPE_CONNECT, FCTYPE_PROP_GET = 0x00, 0x01, 0x04

SC_SUCCESS = 0x0
SC_INVALID_OPCODE = 0x1
SC_INVALID_FIELD = 0x2
SC_INVALID_NS = 0xB
SC_INTERNAL = 0x6
SC_LBA_RANGE = 0x80          # NVM command set: LBA Out of Range
MAX_NLB = 65536              # the 16-bit NLB field's ceiling

DISC_NQN = "nqn.2014-08.org.nvmexpress.discovery"


def _hdr(ptype: int, hlen: int, plen: int, pdo: int = 0,
         flags: int = 0) -> bytes:
    return struct.pack("<BBBBI", ptype, flags, hlen, pdo, plen)


def _cqe(cid: int, status: int, dw0: int = 0, sqid: int = 0,
         sqhd: int = 0) -> bytes:
    # 16-byte completion: result dw0, rsvd, sqhd, sqid, cid, status
    # (phase bit irrelevant on fabrics; status in bits 1..15)
    return struct.pack("<IIHHHH", dw0, 0, sqhd, sqid, cid,
                       status << 1)


class _Sqe:
    """Decoded 64-byte submission queue entry."""

    def __init__(self, raw: bytes):
        self.raw = raw
        self.opcode = raw[0]
        self.flags = raw[1]
        (self.cid,) = struct.unpack_from("<H", raw, 2)
        (self.nsid,) = struct.unpack_from("<I", raw, 4)
        self.cdw10, self.cdw11, self.cdw12, self.cdw13, self.cdw14, \
            self.cdw15 = struct.unpack_from("<6I", raw, 40)


class NvmeofTarget:
    """One NVMe/TCP subsystem whose namespaces are rbd images (the
    gateway role of src/nvmeof/: control plane + target)."""

    def __init__(self, client, pool: str,
                 nqn: str = "nqn.2016-06.io.ceph-tpu:sub1",
                 host: str = "127.0.0.1", port: int = 0):
        self.client = client
        self.pool = pool
        self.nqn = nqn
        self.rbd = RBD(client)
        self._lock = threading.Lock()
        self._namespaces: dict[int, str] = {}   # nsid -> image name
        self._images: dict[int, object] = {}    # nsid -> open Image
        self._next_ctrl = 1
        self._stop = threading.Event()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(8)
        self.port = self._srv.getsockname()[1]
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="nvmeof-target",
                                        daemon=True)
        self._thread.start()

    # ------------------------------------------------- control plane
    # (the gRPC namespace_add/namespace_list surface of the reference's
    # gateway, as direct calls)
    def add_namespace(self, image: str, nsid: int | None = None) -> int:
        with self._lock:
            if nsid is None:
                nsid = max(self._namespaces, default=0) + 1
            if nsid in self._namespaces:
                raise ValueError(f"nsid {nsid} in use")
            img = self.rbd.open(self.pool, image)  # raises if absent
            self._namespaces[nsid] = image
            self._images[nsid] = img
            return nsid

    def remove_namespace(self, nsid: int) -> None:
        with self._lock:
            self._namespaces.pop(nsid)
            img = self._images.pop(nsid, None)
        if img is not None:
            img.close()

    def list_namespaces(self) -> dict[int, str]:
        with self._lock:
            return dict(self._namespaces)

    def stop(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            for img in self._images.values():
                try:
                    img.close()
                except Exception:  # noqa: BLE001
                    pass
            self._images.clear()

    # --------------------------------------------------- connections
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _addr = self._srv.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(sock,),
                                 daemon=True)
            t.start()

    def _serve(self, sock: socket.socket) -> None:
        try:
            if not self._handshake(sock):
                return
            props = {0x14: 0}  # CC register (controller configuration)
            while not self._stop.is_set():
                head = _recv_exact(sock, 8)
                if head is None:
                    return
                ptype, _fl, hlen, pdo, plen = struct.unpack("<BBBBI",
                                                            head)
                body = _recv_exact(sock, plen - 8)
                if body is None:
                    return
                if ptype != CAPSULE_CMD or len(body) < 64:
                    continue  # tolerate keep-alive/no-op PDUs
                sqe = _Sqe(body[:64])
                data = body[hlen - 8 + max(0, pdo - hlen):] \
                    if plen > hlen else b""
                self._dispatch(sock, sqe, data, props)
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _handshake(self, sock: socket.socket) -> bool:
        head = _recv_exact(sock, 8)
        if head is None:
            return False
        ptype, _f, _h, _p, plen = struct.unpack("<BBBBI", head)
        if ptype != ICREQ:
            return False
        if _recv_exact(sock, plen - 8) is None:
            return False
        # ICResp: pfv 0, cpda 0, digests off, maxh2cdata 1 MiB
        payload = struct.pack("<HHBBI", 0, 0, 0, 0, 1 << 20)
        payload += b"\x00" * (120 - len(payload))
        sock.sendall(_hdr(ICRESP, 128, 128) + payload)
        return True

    # ------------------------------------------------------ dispatch
    def _reply(self, sock, cid: int, status: int, dw0: int = 0) -> None:
        cqe = _cqe(cid, status, dw0)
        sock.sendall(_hdr(CAPSULE_RESP, 24, 24) + cqe)

    def _c2h(self, sock, cid: int, data: bytes) -> None:
        # one C2HData PDU: ccid, datao, datal, rsvd then the payload
        head = struct.pack("<HHII", cid, 0, 0, len(data))
        pdu = _hdr(C2H_DATA, 24, 24 + len(data), pdo=24,
                   flags=0x0C)  # LAST_PDU | SUCCESS
        sock.sendall(pdu + head + b"\x00" * 4 + data)

    def _dispatch(self, sock, sqe: _Sqe, data: bytes, props) -> None:
        try:
            if sqe.opcode == OP_FABRICS:
                self._fabrics(sock, sqe, data, props)
            elif sqe.opcode == OP_IDENTIFY:
                self._identify(sock, sqe)
            elif sqe.opcode == OP_SET_FEATURES:
                # number-of-queues (feature 0x07): grant what was asked
                self._reply(sock, sqe.cid, SC_SUCCESS,
                            dw0=sqe.cdw11)
            elif sqe.opcode == OP_KEEP_ALIVE:
                self._reply(sock, sqe.cid, SC_SUCCESS)
            elif sqe.opcode == OP_FLUSH:
                self._reply(sock, sqe.cid, SC_SUCCESS)
            elif sqe.opcode == OP_WRITE:
                self._write(sock, sqe, data)
            elif sqe.opcode == OP_READ:
                self._read(sock, sqe)
            else:
                self._reply(sock, sqe.cid, SC_INVALID_OPCODE)
        except RbdError:
            self._reply(sock, sqe.cid, SC_INTERNAL)

    def _fabrics(self, sock, sqe: _Sqe, data: bytes, props) -> None:
        fctype = sqe.raw[4]
        if fctype == FCTYPE_CONNECT:
            with self._lock:
                ctrl = self._next_ctrl
                self._next_ctrl += 1
            # dw0 low 16 bits carry the controller id
            self._reply(sock, sqe.cid, SC_SUCCESS, dw0=ctrl)
        elif fctype == FCTYPE_PROP_GET:
            off = sqe.cdw11
            if off == 0x00:      # CAP low dword: MQES=1023
                # (fabrics Property Get here returns 32 bits via dw0;
                # the CCS/MPSMIN high dword is not representable and
                # this pair's initiator only reads the low half)
                val = 1023
            elif off == 0x08:    # VS 1.4
                val = 0x00010400
            elif off == 0x1C:    # CSTS: ready iff CC.EN
                val = 1 if props.get(0x14, 0) & 1 else 0
            else:
                val = props.get(off, 0)
            self._reply(sock, sqe.cid, SC_SUCCESS,
                        dw0=val & 0xFFFFFFFF)
        elif fctype == FCTYPE_PROP_SET:
            props[sqe.cdw11] = sqe.cdw12
            self._reply(sock, sqe.cid, SC_SUCCESS)
        else:
            self._reply(sock, sqe.cid, SC_INVALID_FIELD)

    def _identify(self, sock, sqe: _Sqe) -> None:
        cns = sqe.cdw10 & 0xFF
        buf = bytearray(4096)
        if cns == 0x01:          # controller
            struct.pack_into("<HH", buf, 0, 0xC3F0, 0x1AF4)  # vid/ssvid
            buf[4:24] = b"CEPHTPU-NVME-SN0001 "[:20]
            buf[24:64] = b"ceph-tpu nvmeof gateway".ljust(40)[:40]
            buf[64:72] = b"r5      "
            struct.pack_into("<I", buf, 516, len(self._namespaces))
            nqn = self.nqn.encode()
            buf[768:768 + len(nqn)] = nqn
        elif cns == 0x00:        # namespace
            img = self._images.get(sqe.nsid)
            if img is None:
                self._reply(sock, sqe.cid, SC_INVALID_NS)
                return
            img._load()
            blocks = img.size() >> LBA_SHIFT
            struct.pack_into("<QQQ", buf, 0, blocks, blocks, blocks)
            buf[25] = 0          # nlbaf=0 -> one LBA format
            struct.pack_into("<I", buf, 128, LBA_SHIFT << 16)  # lbads
        elif cns == 0x02:        # active namespace id list
            ids = sorted(n for n in self._namespaces
                         if n > sqe.nsid)[:1024]
            for i, nsid in enumerate(ids):
                struct.pack_into("<I", buf, i * 4, nsid)
        else:
            self._reply(sock, sqe.cid, SC_INVALID_FIELD)
            return
        self._c2h(sock, sqe.cid, bytes(buf))
        self._reply(sock, sqe.cid, SC_SUCCESS)

    def _io_image(self, sqe: _Sqe):
        img = self._images.get(sqe.nsid)
        return img

    def _write(self, sock, sqe: _Sqe, data: bytes) -> None:
        img = self._io_image(sqe)
        if img is None:
            self._reply(sock, sqe.cid, SC_INVALID_NS)
            return
        slba = sqe.cdw10 | (sqe.cdw11 << 32)
        nlb = (sqe.cdw12 & 0xFFFF) + 1
        want = nlb * LBA_SIZE
        if len(data) < want:
            self._reply(sock, sqe.cid, SC_INVALID_FIELD)
            return
        img._load()
        if (slba + nlb) * LBA_SIZE > img.size():
            self._reply(sock, sqe.cid, SC_LBA_RANGE)
            return
        img.write(slba * LBA_SIZE, data[:want])
        self._reply(sock, sqe.cid, SC_SUCCESS)

    def _read(self, sock, sqe: _Sqe) -> None:
        img = self._io_image(sqe)
        if img is None:
            self._reply(sock, sqe.cid, SC_INVALID_NS)
            return
        slba = sqe.cdw10 | (sqe.cdw11 << 32)
        nlb = (sqe.cdw12 & 0xFFFF) + 1
        img._load()
        if (slba + nlb) * LBA_SIZE > img.size():
            # a short/empty clamped read with SC_SUCCESS would silently
            # corrupt consumers that assume full-length reads
            self._reply(sock, sqe.cid, SC_LBA_RANGE)
            return
        data = img.read(slba * LBA_SIZE, nlb * LBA_SIZE)
        self._c2h(sock, sqe.cid, data)
        self._reply(sock, sqe.cid, SC_SUCCESS)


class NvmeInitiator:
    """Minimal NVMe/TCP host for tests and tooling (the nvme-cli role
    against this target): connect, enable the controller, identify,
    and issue LBA reads/writes."""

    def __init__(self, host: str, port: int,
                 nqn: str = "nqn.2016-06.io.ceph-tpu:sub1"):
        self.sock = socket.create_connection((host, port), timeout=10)
        self.nqn = nqn
        self._cid = 0
        self._icreq()
        self.ctrl_id = self._connect()
        # enable the controller: CC.EN=1, then poll CSTS.RDY
        self.prop_set(0x14, 1)
        assert self.prop_get(0x1C) & 1, "controller never became ready"

    # ---------------------------------------------------------- pdus
    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def _icreq(self) -> None:
        payload = struct.pack("<HHBBI", 0, 0, 0, 0, 0)
        payload += b"\x00" * (120 - len(payload))
        self.sock.sendall(_hdr(ICREQ, 128, 128) + payload)
        head = _recv_exact(self.sock, 8)
        ptype, _f, _h, _p, plen = struct.unpack("<BBBBI", head)
        assert ptype == ICRESP, hex(ptype)
        _recv_exact(self.sock, plen - 8)

    def _next_cid(self) -> int:
        self._cid = (self._cid + 1) & 0xFFFF
        return self._cid

    def _sqe(self, opcode: int, nsid: int = 0, cdw10: int = 0,
             cdw11: int = 0, cdw12: int = 0, fctype: int | None = None,
             ) -> bytes:
        raw = bytearray(64)
        raw[0] = opcode
        cid = self._next_cid()
        struct.pack_into("<H", raw, 2, cid)
        if fctype is not None:
            raw[4] = fctype
        else:
            struct.pack_into("<I", raw, 4, nsid)
        struct.pack_into("<6I", raw, 40, cdw10, cdw11, cdw12, 0, 0, 0)
        return bytes(raw)

    def _capsule(self, sqe: bytes, data: bytes = b"") -> None:
        plen = 8 + 64 + len(data)
        pdo = 72 if data else 0
        self.sock.sendall(_hdr(CAPSULE_CMD, 72, plen, pdo=pdo)
                          + sqe + data)

    def _collect(self) -> tuple[int, int, bytes]:
        """Read PDUs until the completion arrives; returns (status,
        dw0, concatenated C2H data)."""
        data = b""
        while True:
            head = _recv_exact(self.sock, 8)
            assert head is not None, "target hung up"
            ptype, _f, hlen, pdo, plen = struct.unpack("<BBBBI", head)
            body = _recv_exact(self.sock, plen - 8)
            assert body is not None, "target hung up mid-PDU"
            if ptype == C2H_DATA:
                data += body[max(0, pdo - 8):]
            elif ptype == CAPSULE_RESP:
                dw0, _r, _sqhd, _sqid, _cid, status = struct.unpack(
                    "<IIHHHH", body[:16])
                return status >> 1, dw0, data

    def _cmd(self, sqe: bytes, data: bytes = b"") -> tuple[int, int,
                                                           bytes]:
        self._capsule(sqe, data)
        return self._collect()

    # ------------------------------------------------------ commands
    def _connect(self) -> int:
        st, dw0, _ = self._cmd(self._sqe(OP_FABRICS,
                                         fctype=FCTYPE_CONNECT))
        assert st == SC_SUCCESS, st
        return dw0 & 0xFFFF

    def prop_get(self, off: int) -> int:
        st, dw0, _ = self._cmd(self._sqe(OP_FABRICS, cdw11=off,
                                         fctype=FCTYPE_PROP_GET))
        assert st == SC_SUCCESS, st
        return dw0

    def prop_set(self, off: int, val: int) -> None:
        st, _d, _ = self._cmd(self._sqe(OP_FABRICS, cdw11=off,
                                        cdw12=val,
                                        fctype=FCTYPE_PROP_SET))
        assert st == SC_SUCCESS, st

    def identify_controller(self) -> dict:
        st, _d, buf = self._cmd(self._sqe(OP_IDENTIFY, cdw10=0x01))
        assert st == SC_SUCCESS and len(buf) >= 1024
        return {"sn": buf[4:24].decode().strip(),
                "model": buf[24:64].decode().strip(),
                "nn": struct.unpack_from("<I", buf, 516)[0],
                "subnqn": buf[768:1024].split(b"\x00")[0].decode()}

    def identify_namespace(self, nsid: int) -> dict:
        st, _d, buf = self._cmd(self._sqe(OP_IDENTIFY, nsid=nsid,
                                          cdw10=0x00))
        if st != SC_SUCCESS:
            raise KeyError(nsid)
        nsze = struct.unpack_from("<Q", buf, 0)[0]
        lbads = (struct.unpack_from("<I", buf, 128)[0] >> 16) & 0xFF
        return {"nsze": nsze, "lba_size": 1 << lbads}

    def list_namespaces(self) -> list[int]:
        st, _d, buf = self._cmd(self._sqe(OP_IDENTIFY, cdw10=0x02))
        assert st == SC_SUCCESS
        out = []
        for i in range(0, len(buf), 4):
            (nsid,) = struct.unpack_from("<I", buf, i)
            if nsid == 0:
                break
            out.append(nsid)
        return out

    def write(self, nsid: int, slba: int, data: bytes) -> None:
        assert len(data) % LBA_SIZE == 0 and data
        total = len(data) // LBA_SIZE
        done = 0
        while done < total:  # the NLB field is 16-bit: split here
            nlb = min(MAX_NLB, total - done)
            chunk = data[done * LBA_SIZE:(done + nlb) * LBA_SIZE]
            at = slba + done
            st, _d, _ = self._cmd(
                self._sqe(OP_WRITE, nsid=nsid,
                          cdw10=at & 0xFFFFFFFF, cdw11=at >> 32,
                          cdw12=nlb - 1), chunk)
            assert st == SC_SUCCESS, st
            done += nlb

    def read(self, nsid: int, slba: int, nlb: int) -> bytes:
        out = b""
        done = 0
        while done < nlb:
            n = min(MAX_NLB, nlb - done)
            at = slba + done
            st, _d, data = self._cmd(
                self._sqe(OP_READ, nsid=nsid,
                          cdw10=at & 0xFFFFFFFF, cdw11=at >> 32,
                          cdw12=n - 1))
            assert st == SC_SUCCESS, st
            out += data
            done += n
        return out

    def flush(self, nsid: int) -> None:
        st, _d, _ = self._cmd(self._sqe(OP_FLUSH, nsid=nsid))
        assert st == SC_SUCCESS

    def keep_alive(self) -> None:
        st, _d, _ = self._cmd(self._sqe(OP_KEEP_ALIVE))
        assert st == SC_SUCCESS
