"""rbd-lite: block images over RADOS objects with COW snapshots.

The capability slice of the reference's librbd (src/librbd/ — image
create/open/list/remove, an Image handle with read/write/resize, and
snapshots; io dispatch layering striped over rbd_data.* objects).
Re-shaped for this build:

- image metadata lives in a codec-encoded header object
  (`rbd_header.<name>`): size, layout, snapshot table, snap id seq;
- data lives in `rbd_data.<name>.<objno>` pieces addressed through
  FileLayout (stripe_unit/stripe_count/object_size — the same
  file_layout_t algebra CephFS and libradosstriper use);
- snapshots are image-level COW: the FIRST write touching an object
  after a snapshot copies the object's bytes to
  `rbd_data.<name>.<objno>@<snapid>` before applying (the object-snap
  role of SnapMapper/clone-overlap, done at the client like librbd's
  object copy-up).  Reading snapshot s serves each object from its
  oldest copy with id >= s, falling back to the head.  Removing a
  snapshot retires its record (copies stay while an older snapshot
  might read through them) and purges copies when nothing older
  remains.

Single-writer images (the exclusive-lock feature of the reference is a
later slice); all ops are synchronous like the rest of the client
stack.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..client.rados import RadosClient, RadosError
from ..client.striper import FileLayout
from ..utils.codec import Decoder, Encodable, Encoder

_HEADER = "rbd_header.{name}"
_DATA = "rbd_data.{name}.{objno:016x}"
_SNAP = "rbd_data.{name}.{objno:016x}@{snap}"
_DIR = "rbd_directory"


class RbdError(Exception):
    pass


@dataclass
class SnapRecord(Encodable):
    snap_id: int
    name: str            # "" once retired (removed but copies retained)
    size: int            # image size when the snapshot was taken
    copied: list = field(default_factory=list)  # objnos with COW copies

    VERSION, COMPAT = 1, 1

    def encode(self, enc: Encoder) -> None:
        def body(e):
            e.u64(self.snap_id)
            e.string(self.name)
            e.u64(self.size)
            e.seq(sorted(self.copied), Encoder.u64)
        enc.versioned(self.VERSION, self.COMPAT, body)

    @classmethod
    def decode(cls, dec: Decoder) -> "SnapRecord":
        def body(d, v):
            return cls(d.u64(), d.string(), d.u64(), d.seq(Decoder.u64))
        return dec.versioned(cls.VERSION, body)


@dataclass
class ImageHeader(Encodable):
    size: int
    object_size: int
    stripe_unit: int
    stripe_count: int
    snap_seq: int = 0
    snaps: list = field(default_factory=list)  # [SnapRecord]

    VERSION, COMPAT = 1, 1

    def encode(self, enc: Encoder) -> None:
        def body(e):
            e.u64(self.size)
            e.u64(self.object_size)
            e.u64(self.stripe_unit)
            e.u64(self.stripe_count)
            e.u64(self.snap_seq)
            e.seq(self.snaps, lambda ee, s: s.encode(ee))
        enc.versioned(self.VERSION, self.COMPAT, body)

    @classmethod
    def decode(cls, dec: Decoder) -> "ImageHeader":
        def body(d, v):
            h = cls(d.u64(), d.u64(), d.u64(), d.u64(), d.u64())
            h.snaps = d.seq(SnapRecord.decode)
            return h
        return dec.versioned(cls.VERSION, body)

    def layout(self) -> FileLayout:
        return FileLayout(self.stripe_unit, self.stripe_count,
                          self.object_size)


class RBD:
    """Pool-level image operations (the librbd RBD class shape)."""

    def __init__(self, client: RadosClient):
        self.client = client

    def create(self, pool: str, name: str, size: int,
               object_size: int = 4 * 1024 * 1024,
               stripe_unit: int | None = None,
               stripe_count: int = 1) -> "Image":
        if size < 0:
            raise RbdError("negative size")
        header = _HEADER.format(name=name)
        try:
            self.client.read(pool, header, length=1)
            raise RbdError(f"image {name!r} exists")
        except RadosError:
            pass
        su = stripe_unit or object_size
        h = ImageHeader(size, object_size, su, stripe_count)
        FileLayout(su, stripe_count, object_size)  # validates
        self.client.write_full(pool, header, h.encode_bytes())
        self._dir_update(pool, add=name)
        return self.open(pool, name)

    def open(self, pool: str, name: str) -> "Image":
        return Image(self.client, pool, name)

    def list(self, pool: str) -> list[str]:
        try:
            raw = self.client.read(pool, _DIR)
        except RadosError:
            return []
        d = Decoder(raw)
        return d.seq(Decoder.string)

    def remove(self, pool: str, name: str) -> None:
        img = self.open(pool, name)
        img.purge()
        self._dir_update(pool, remove=name)

    def _dir_update(self, pool: str, add: str | None = None,
                    remove: str | None = None) -> None:
        names = set(self.list(pool))
        if add:
            names.add(add)
        if remove:
            names.discard(remove)
        e = Encoder()
        e.seq(sorted(names), Encoder.string)
        self.client.write_full(pool, _DIR, e.tobytes())


class Image:
    """An open image handle (librbd Image shape)."""

    def __init__(self, client: RadosClient, pool: str, name: str):
        self.client = client
        self.pool = pool
        self.name = name
        self._load()

    # ------------------------------------------------------------- header
    def _load(self) -> None:
        try:
            raw = self.client.read(self.pool,
                                   _HEADER.format(name=self.name))
        except RadosError as e:
            raise RbdError(f"no image {self.name!r}") from e
        self.header = ImageHeader.decode_bytes(raw)

    def _save(self) -> None:
        self.client.write_full(self.pool, _HEADER.format(name=self.name),
                               self.header.encode_bytes())

    def size(self) -> int:
        return self.header.size

    # ---------------------------------------------------------------- io
    def _piece(self, objno: int) -> str:
        return _DATA.format(name=self.name, objno=objno)

    def _snap_piece(self, objno: int, snap_id: int) -> str:
        return _SNAP.format(name=self.name, objno=objno, snap=snap_id)

    def _read_piece(self, oid: str, off: int, length: int) -> bytes:
        try:
            data = self.client.read(self.pool, oid, offset=off,
                                    length=length)
        except RadosError:
            data = b""  # sparse hole
        return data + b"\0" * (length - len(data))

    def _newest_snap(self) -> SnapRecord | None:
        """COW target: the newest record, live OR retired (older live
        snapshots read through newer copies)."""
        return self.header.snaps[-1] if self.header.snaps else None

    def _cow_object(self, objno: int, newest: SnapRecord) -> bool:
        """Copy-up the head object to the newest snapshot before its
        first post-snapshot mutation.  Returns True if the header now
        needs saving."""
        if objno in newest.copied:
            return False
        try:
            old = self.client.read(self.pool, self._piece(objno))
        except RadosError:
            old = b""
        self.client.write_full(self.pool,
                               self._snap_piece(objno, newest.snap_id),
                               old)
        newest.copied.append(objno)
        return True

    def _objects_covering(self, size: int) -> set[int]:
        objs: set[int] = set()
        if size > 0:
            for objno, _o, _t in self.header.layout().file_to_extents(
                    0, size):
                objs.add(objno)
        return objs

    def write(self, off: int, data: bytes) -> None:
        if off + len(data) > self.header.size:
            raise RbdError("write past end of image (resize first)")
        if not data:
            return
        layout = self.header.layout()
        newest = self._newest_snap()
        per_obj: dict[int, list] = {}
        pos = 0
        for objno, obj_off, take in layout.file_to_extents(off,
                                                           len(data)):
            per_obj.setdefault(objno, []).append((obj_off, pos, take))
            pos += take
        dirty_header = False
        for objno, extents in per_obj.items():
            if newest is not None:
                dirty_header |= self._cow_object(objno, newest)
            for obj_off, p, take in extents:
                self.client.write(self.pool, self._piece(objno),
                                  data[p:p + take], offset=obj_off)
        if dirty_header:
            self._save()

    def read(self, off: int, length: int,
             snap: str | None = None) -> bytes:
        bound = self.header.size if snap is None \
            else self._snap_record(snap).size
        length = max(0, min(length, bound - off))
        if length <= 0:
            return b""
        layout = self.header.layout()
        out = bytearray(length)
        pos = 0
        snap_id = None if snap is None else self._snap_record(snap).snap_id
        for objno, obj_off, take in layout.file_to_extents(off, length):
            oid = self._piece(objno) if snap_id is None \
                else self._resolve_snap_object(objno, snap_id)
            out[pos:pos + take] = self._read_piece(oid, obj_off, take)
            pos += take
        return bytes(out)

    def _resolve_snap_object(self, objno: int, snap_id: int) -> str:
        """Oldest COW copy with id >= snap_id, else the head object —
        the snapshot read-through chain."""
        for rec in self.header.snaps:  # ordered oldest -> newest
            if rec.snap_id >= snap_id and objno in rec.copied:
                return self._snap_piece(objno, rec.snap_id)
        return self._piece(objno)

    # ------------------------------------------------------------- resize
    def _zero_tail(self, new_size: int, old_size: int) -> None:
        """Zero the KEPT objects' stale ranges beyond new_size (up to
        the object-SET boundary — with striping, a kept object holds
        file ranges across the whole set span) so a later grow reads
        zeros, not resurrection."""
        layout = self.header.layout()
        span = layout.stripe_count * layout.object_size
        set_end = -(-new_size // span) * span
        tail = min(old_size, set_end) - new_size
        if tail > 0:
            prev = self.header.size
            self.header.size = max(prev, new_size + tail)
            self.write(new_size, b"\0" * tail)
            self.header.size = prev

    def resize(self, new_size: int) -> None:
        if new_size < 0:
            raise RbdError("negative size")
        old = self.header.size
        if new_size < old:
            # trim: COW whole objects into the newest snapshot (a live
            # snapshot must keep reading the frozen bytes), then drop
            # them; zero the kept partial range
            keep_objs = self._objects_covering(new_size)
            newest = self._newest_snap()
            dirty = False
            for objno in sorted(self._objects_covering(old) - keep_objs):
                if newest is not None:
                    dirty |= self._cow_object(objno, newest)
                try:
                    self.client.remove(self.pool, self._piece(objno))
                except RadosError:
                    pass
            if dirty:
                self._save()
            self._zero_tail(new_size, old)
        self.header.size = new_size
        self._save()

    # ---------------------------------------------------------- snapshots
    def _snap_record(self, name: str) -> SnapRecord:
        for rec in self.header.snaps:
            if rec.name == name:
                return rec
        raise RbdError(f"no snapshot {name!r}")

    def snap_create(self, name: str) -> int:
        if any(r.name == name for r in self.header.snaps):
            raise RbdError(f"snapshot {name!r} exists")
        self.header.snap_seq += 1
        rec = SnapRecord(self.header.snap_seq, name, self.header.size)
        self.header.snaps.append(rec)
        self._save()
        return rec.snap_id

    def snap_list(self) -> list[dict]:
        return [{"id": r.snap_id, "name": r.name, "size": r.size}
                for r in self.header.snaps if r.name]

    def snap_remove(self, name: str) -> None:
        rec = self._snap_record(name)
        older_live = any(r.name and r.snap_id < rec.snap_id
                        for r in self.header.snaps)
        if older_live:
            rec.name = ""  # retire: older snapshots read through it
        else:
            for objno in rec.copied:
                try:
                    self.client.remove(
                        self.pool,
                        self._snap_piece(objno, rec.snap_id))
                except RadosError:
                    pass
            self.header.snaps.remove(rec)
        # purge retired records nothing can read through anymore
        while self.header.snaps:
            first = self.header.snaps[0]
            if first.name:
                break
            for objno in first.copied:
                try:
                    self.client.remove(
                        self.pool,
                        self._snap_piece(objno, first.snap_id))
                except RadosError:
                    pass
            self.header.snaps.pop(0)
        self._save()

    def snap_rollback(self, name: str) -> None:
        """head := the image content at the snapshot (librbd rollback).
        Rollback is itself a mutation: objects copy-up to snapshots
        NEWER than the target first, so those snapshots stay frozen."""
        rec = self._snap_record(name)
        cur = self.header.size
        newest = self._newest_snap()
        cow_target = newest if (newest is not None
                                and newest.snap_id > rec.snap_id) \
            else None
        restore = self._objects_covering(rec.size)
        beyond = self._objects_covering(cur) - restore
        dirty = False
        for objno in sorted(restore | beyond):
            if cow_target is not None:
                dirty |= self._cow_object(objno, cow_target)
            if objno in beyond:
                # head shrinks back to the snapshot's extent
                try:
                    self.client.remove(self.pool, self._piece(objno))
                except RadosError:
                    pass
                continue
            src = self._resolve_snap_object(objno, rec.snap_id)
            if src == self._piece(objno):
                continue  # head unchanged since the snapshot
            try:
                content = self.client.read(self.pool, src)
            except RadosError:
                content = b""
            self.client.write_full(self.pool, self._piece(objno), content)
        if dirty:
            self._save()
        # restored copies may carry bytes past the snapshot's size; zero
        # the kept range so a later grow reads zeros
        self._zero_tail(rec.size, max(cur, rec.size))
        self.header.size = rec.size
        self._save()

    # -------------------------------------------------------------- purge
    def purge(self) -> None:
        layout = self.header.layout()
        span = max(self.header.size,
                   max((r.size for r in self.header.snaps), default=0))
        objs = set()
        if span:
            for objno, _o, _t in layout.file_to_extents(0, span):
                objs.add(objno)
        for objno in objs:
            try:
                self.client.remove(self.pool, self._piece(objno))
            except RadosError:
                pass
            for rec in self.header.snaps:
                if objno in rec.copied:
                    try:
                        self.client.remove(
                            self.pool,
                            self._snap_piece(objno, rec.snap_id))
                    except RadosError:
                        pass
        try:
            self.client.remove(self.pool,
                               _HEADER.format(name=self.name))
        except RadosError:
            pass
